"""Webhook extension (reference `extension-webhook`).

POSTs document lifecycle events to a URL with an HMAC-SHA256 signature
header `X-Hocuspocus-Signature-256`; imports JSON into empty fields on
load; onConnect response JSON becomes connection context (failure =>
Forbidden).

Requests carry a timeout and retry transient failures (network errors
and 5xx responses) with bounded exponential backoff + jitter — the
reference ships webhook retries; firing once with no timeout turns any
slow endpoint into a hung hook chain. Retries are counted in
`hocuspocus_webhook_retries_total` (exposed when a `Metrics` extension
is configured). 4xx responses are NOT retried: the endpoint understood
the request and rejected it.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import time
from enum import Enum
from typing import Any, Optional

import aiohttp

from ..observability.metrics import Counter

# process-global (the wire-telemetry pattern): several Webhook
# instances share ONE counter object, so a second instance's registry
# adoption is a no-op instead of a swallowed name collision that would
# hide its retries from /metrics
_RETRIES_TOTAL = Counter(
    "hocuspocus_webhook_retries_total",
    "Webhook request retries after a transient failure, by event",
)

from ..protocol.close_events import CloseError, FORBIDDEN
from ..server import logger
from ..server.types import Extension, Payload
from ..transformer import TiptapTransformer


class Events(str, Enum):
    onChange = "change"
    onConnect = "connect"
    onCreate = "create"
    onDisconnect = "disconnect"


class Webhook(Extension):
    def __init__(
        self,
        url: str,
        secret: str = "",
        transformer: Any = None,
        events: Optional[list[Events]] = None,
        debounce: Optional[float] = 2000,
        debounce_max_wait: float = 10000,
        request_timeout: float = 10000,
        retries: int = 2,
        retry_base_ms: float = 250,
        retry_max_ms: float = 5000,
    ) -> None:
        if not url:
            raise ValueError("url is required!")
        self.url = url
        self.secret = secret
        self.transformer = transformer or TiptapTransformer
        self.events = events if events is not None else [Events.onChange]
        self.debounce_ms = debounce
        self.debounce_max_wait = debounce_max_wait
        self.debounced: dict[str, dict] = {}
        # delivery robustness: per-request timeout (ms) + bounded
        # exponential-backoff retries with full jitter on transient
        # failures (connection errors, timeouts, 5xx)
        self.request_timeout_ms = request_timeout
        self.retries = max(int(retries), 0)
        self.retry_base_ms = retry_base_ms
        self.retry_max_ms = retry_max_ms
        self.retries_total = _RETRIES_TOTAL

    async def on_configure(self, data: Payload) -> None:
        # surface the retry counter on /metrics when a Metrics extension
        # is configured (its registry adopts pre-built collectors)
        for extension in getattr(data.instance.configuration, "extensions", []):
            registry = getattr(extension, "registry", None)
            if registry is not None and hasattr(registry, "register"):
                try:
                    registry.register(self.retries_total)
                except (ValueError, AttributeError):
                    pass
                break

    def create_signature(self, body: bytes) -> str:
        digest = hmac.new(self.secret.encode(), body, hashlib.sha256).hexdigest()
        return f"sha256={digest}"

    def debounce(self, id: str, fn) -> None:
        old = self.debounced.pop(id, None)
        start = old["start"] if old else time.monotonic()
        if old:
            old["handle"].cancel()

        def run() -> None:
            self.debounced.pop(id, None)
            asyncio.ensure_future(fn())

        if (time.monotonic() - start) * 1000 >= self.debounce_max_wait:
            run()
            return
        handle = asyncio.get_event_loop().call_later(self.debounce_ms / 1000, run)
        self.debounced[id] = {"start": start, "handle": handle}

    def _retry_delay(self, attempt: int) -> float:
        from ..aio import backoff_delay_s

        return backoff_delay_s(attempt, self.retry_base_ms, self.retry_max_ms)

    async def send_request(self, event: Events, payload: Any) -> tuple[int, Any]:
        body = json.dumps({"event": event.value, "payload": payload}).encode()
        headers = {
            "X-Hocuspocus-Signature-256": self.create_signature(body),
            "Content-Type": "application/json",
        }
        timeout = aiohttp.ClientTimeout(total=self.request_timeout_ms / 1000.0)
        attempts = self.retries + 1
        last_error: Optional[Exception] = None
        for attempt in range(attempts):
            if attempt:
                self.retries_total.inc(event=event.value)
                await asyncio.sleep(self._retry_delay(attempt - 1))
            try:
                async with aiohttp.ClientSession(timeout=timeout) as session:
                    async with session.post(
                        self.url, data=body, headers=headers
                    ) as response:
                        try:
                            data = await response.json(content_type=None)
                        except Exception:
                            data = await response.text()
                        if response.status >= 500 and attempt + 1 < attempts:
                            # server-side failure: retryable; a 4xx is a
                            # decision, returned to the caller as-is
                            last_error = RuntimeError(
                                f"webhook returned {response.status}"
                            )
                            continue
                        return response.status, data
            except asyncio.CancelledError:
                raise
            except Exception as error:  # connect/timeout/transport
                last_error = error
        raise last_error if last_error is not None else RuntimeError(
            "webhook request failed"
        )

    async def on_change(self, data: Payload) -> None:
        if Events.onChange not in self.events:
            return

        async def save() -> None:
            try:
                await self.send_request(
                    Events.onChange,
                    {
                        "document": self.transformer.from_ydoc(data.document),
                        "documentName": data.document_name,
                        "context": data.context,
                        "requestHeaders": data.request_headers,
                        "requestParameters": dict(data.request_parameters or {}),
                    },
                )
            except Exception as error:
                logger.log_error(f"caught error in extension-webhook: {error}")

        if not self.debounce_ms:
            await save()
            return
        self.debounce(data.document_name, save)

    async def on_load_document(self, data: Payload) -> None:
        if Events.onCreate not in self.events:
            return
        try:
            status, response = await self.send_request(
                Events.onCreate,
                {
                    "documentName": data.document_name,
                    "requestHeaders": data.request_headers,
                    "requestParameters": dict(data.request_parameters or {}),
                },
            )
            if status != 200 or not response:
                return
            document = json.loads(response) if isinstance(response, str) else response
            for field_name, field_doc in document.items():
                if data.document.is_empty(field_name):
                    data.document.merge(self.transformer.to_ydoc(field_doc, field_name))
        except Exception as error:
            logger.log_error(f"caught error in extension-webhook: {error}")

    async def on_connect(self, data: Payload) -> Any:
        if Events.onConnect not in self.events:
            return
        try:
            status, response = await self.send_request(
                Events.onConnect,
                {
                    "documentName": data.document_name,
                    "requestHeaders": data.request_headers,
                    "requestParameters": dict(data.request_parameters or {}),
                },
            )
            if status >= 400:
                raise RuntimeError(f"webhook returned {status}")
            if isinstance(response, str) and response:
                return json.loads(response)
            return response
        except Exception as error:
            logger.log_error(f"caught error in extension-webhook: {error}")
            raise CloseError(FORBIDDEN)

    async def on_disconnect(self, data: Payload) -> None:
        if Events.onDisconnect not in self.events:
            return
        try:
            await self.send_request(
                Events.onDisconnect,
                {
                    "documentName": data.document_name,
                    "requestHeaders": data.request_headers,
                    "requestParameters": dict(data.request_parameters or {}),
                    "context": data.context,
                },
            )
        except Exception as error:
            logger.log_error(f"caught error in extension-webhook: {error}")
