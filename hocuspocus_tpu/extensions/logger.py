"""Hook-event logging extension (reference `extension-logger`)."""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Any, Callable, Optional


from ..server.types import Extension, Payload


class Logger(Extension):
    def __init__(
        self,
        log: Optional[Callable[[str], None]] = None,
        on_load_document: bool = True,
        on_change: bool = True,
        on_store_document: bool = True,
        on_connect: bool = True,
        on_disconnect: bool = True,
        on_upgrade: bool = True,
        on_request: bool = True,
        on_destroy: bool = True,
        on_configure: bool = True,
    ) -> None:
        self._log = log or print
        self.flags = {
            "on_load_document": on_load_document,
            "on_change": on_change,
            "on_store_document": on_store_document,
            "on_connect": on_connect,
            "on_disconnect": on_disconnect,
            "on_upgrade": on_upgrade,
            "on_request": on_request,
            "on_destroy": on_destroy,
            "on_configure": on_configure,
        }
        self.name: Optional[str] = None

    def log(self, message: str) -> None:
        meta = datetime.now(timezone.utc).isoformat()
        if self.name:
            meta = f"{self.name} {meta}"
        self._log(f"[{meta}] {message}")

    async def on_configure(self, data: Payload) -> None:
        self.name = data.instance.configuration.name

    async def on_load_document(self, data: Payload) -> None:
        if self.flags["on_load_document"]:
            self.log(f'Loaded document "{data.document_name}".')

    async def on_change(self, data: Payload) -> None:
        if self.flags["on_change"]:
            self.log(f'Document "{data.document_name}" changed.')

    async def on_store_document(self, data: Payload) -> None:
        if self.flags["on_store_document"]:
            self.log(f'Store "{data.document_name}".')

    async def on_connect(self, data: Payload) -> None:
        if self.flags["on_connect"]:
            self.log(f'New connection to "{data.document_name}".')

    async def on_disconnect(self, data: Payload) -> None:
        if self.flags["on_disconnect"]:
            self.log(f'Connection to "{data.document_name}" closed.')

    async def on_upgrade(self, data: Payload) -> None:
        if self.flags["on_upgrade"]:
            self.log("Upgrading connection …")

    async def on_request(self, data: Payload) -> None:
        if self.flags["on_request"]:
            self.log(f"Incoming HTTP Request to {data.request.rel_url}")

    async def on_listen(self, data: Payload) -> None:
        pass

    async def on_destroy(self, data: Payload) -> None:
        if self.flags["on_destroy"]:
            self.log("Shut down.")
