"""S3 persistence extension (reference `extension-s3`).

Stores each document at `{prefix}{documentName}.bin`. Instead of the AWS
SDK the reference uses, this ships a minimal async S3 REST client with
SigV4 signing over aiohttp — self-contained, testable against any
S3-compatible endpoint (MinIO, fakes).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
from typing import Optional
from urllib.parse import quote

import aiohttp

from ..server.types import Payload
from .database import Database


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client:
    """Tiny SigV4 S3 client: get_object / put_object / head_bucket."""

    def __init__(
        self,
        bucket: str,
        region: str = "us-east-1",
        endpoint: Optional[str] = None,
        access_key_id: Optional[str] = None,
        secret_access_key: Optional[str] = None,
        force_path_style: bool = True,
    ) -> None:
        self.bucket = bucket
        self.region = region
        self.endpoint = (endpoint or f"https://s3.{region}.amazonaws.com").rstrip("/")
        self.access_key_id = access_key_id or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_access_key = secret_access_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", ""
        )
        self.force_path_style = force_path_style

    def _url_and_path(self, key: str) -> tuple[str, str]:
        path = f"/{self.bucket}/{quote(key)}" if self.force_path_style else f"/{quote(key)}"
        return f"{self.endpoint}{path}", path

    def _headers(self, method: str, path: str, payload: bytes, host: str) -> dict:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date_stamp = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(payload).hexdigest()
        canonical_headers = f"host:{host}\nx-amz-content-sha256:{payload_hash}\nx-amz-date:{amz_date}\n"
        signed_headers = "host;x-amz-content-sha256;x-amz-date"
        canonical_request = (
            f"{method}\n{path}\n\n{canonical_headers}\n{signed_headers}\n{payload_hash}"
        )
        scope = f"{date_stamp}/{self.region}/s3/aws4_request"
        string_to_sign = (
            f"AWS4-HMAC-SHA256\n{amz_date}\n{scope}\n"
            f"{hashlib.sha256(canonical_request.encode()).hexdigest()}"
        )
        k_date = _sign(f"AWS4{self.secret_access_key}".encode(), date_stamp)
        k_region = hmac.new(k_date, self.region.encode(), hashlib.sha256).digest()
        k_service = hmac.new(k_region, b"s3", hashlib.sha256).digest()
        k_signing = hmac.new(k_service, b"aws4_request", hashlib.sha256).digest()
        signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()
        authorization = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key_id}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return {
            "Authorization": authorization,
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }

    async def get_object(self, key: str) -> Optional[bytes]:
        url, path = self._url_and_path(key)
        host = url.split("//", 1)[1].split("/", 1)[0]
        headers = self._headers("GET", path, b"", host)
        async with aiohttp.ClientSession() as session:
            async with session.get(url, headers=headers) as response:
                if response.status == 404:
                    return None
                response.raise_for_status()
                return await response.read()

    async def put_object(self, key: str, data: bytes) -> None:
        url, path = self._url_and_path(key)
        host = url.split("//", 1)[1].split("/", 1)[0]
        headers = self._headers("PUT", path, data, host)
        async with aiohttp.ClientSession() as session:
            async with session.put(url, data=data, headers=headers) as response:
                response.raise_for_status()

    async def head_bucket(self) -> bool:
        path = f"/{self.bucket}" if self.force_path_style else "/"
        url = f"{self.endpoint}{path}"
        host = url.split("//", 1)[1].split("/", 1)[0]
        headers = self._headers("HEAD", path, b"", host)
        async with aiohttp.ClientSession() as session:
            async with session.head(url, headers=headers) as response:
                return response.status < 400


class S3(Database):
    def __init__(
        self,
        bucket: str,
        region: str = "us-east-1",
        prefix: str = "",
        endpoint: Optional[str] = None,
        access_key_id: Optional[str] = None,
        secret_access_key: Optional[str] = None,
        client: Optional[S3Client] = None,
        force_path_style: bool = True,
    ) -> None:
        super().__init__(fetch=self._fetch, store=self._store)
        self.prefix = prefix
        self.client = client or S3Client(
            bucket=bucket,
            region=region,
            endpoint=endpoint,
            access_key_id=access_key_id,
            secret_access_key=secret_access_key,
            force_path_style=force_path_style,
        )

    def object_key(self, document_name: str) -> str:
        return f"{self.prefix}{document_name}.bin"

    async def on_configure(self, data: Payload) -> None:
        try:
            await self.client.head_bucket()
        except Exception as error:
            from ..server import logger

            logger.log_error(f"S3 connection probe failed: {error}")

    async def _fetch(self, data: Payload) -> Optional[bytes]:
        return await self.client.get_object(self.object_key(data.document_name))

    async def _store(self, data: Payload) -> None:
        await self.client.put_object(self.object_key(data.document_name), data["state"])
