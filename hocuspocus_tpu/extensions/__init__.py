from ..storage import Durability
from .database import Database
from .history import History
from .incremental import IncrementalSQLite
from .logger import Logger
from .redis import Redis
from .s3 import S3, S3Client
from .sqlite import SQLite
from .throttle import Throttle
from .webhook import Events, Webhook

__all__ = [
    "Database",
    "Durability",
    "History",
    "IncrementalSQLite",
    "Logger",
    "Redis",
    "S3",
    "S3Client",
    "SQLite",
    "Throttle",
    "Events",
    "Webhook",
]
