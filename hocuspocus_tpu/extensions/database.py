"""Generic persistence extension (reference `extension-database`).

The user supplies async `fetch`/`store` callables; onLoadDocument applies
the fetched update, onStoreDocument persists the full encoded state.
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional

from ..crdt import apply_update, encode_state_as_update
from ..server.types import Extension, Payload


class Database(Extension):
    def __init__(
        self,
        fetch: Optional[Callable[[Payload], Awaitable[Optional[bytes]]]] = None,
        store: Optional[Callable[[Payload], Awaitable[None]]] = None,
    ) -> None:
        self.fetch = fetch or (lambda data: _none())
        self.store = store or (lambda data: _noop())
        # WAL truncation seam (storage/extension.py): only a REAL store
        # may declare the log covered — a Database() with the default
        # no-op store persists nothing, and truncating on its "success"
        # would delete the only durable copy of every update
        self._covers_wal = store is not None

    async def on_load_document(self, data: Payload) -> None:
        update = await self.fetch(data)
        if update:
            apply_update(data.document, update)

    async def on_store_document(self, data: Payload) -> None:
        data["state"] = encode_state_as_update(data.document)
        await self.store(data)
        if self._covers_wal:
            # everything encoded into `state` is durable downstream:
            # the Durability extension may truncate the WAL through the
            # position it captured before this chain began
            data["wal_covered"] = True


async def _none() -> None:
    return None


async def _noop() -> None:
    return None
