"""Connection throttling by IP (reference `extension-throttle`).

Sliding window: more than `throttle` connection attempts within
`considered_seconds` bans the IP for `ban_time` minutes; onConnect
rejects while banned (aborting the hook chain = connection refused).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..server.types import Extension, Payload


class ThrottleRejection(Exception):
    def __init__(self) -> None:
        # empty message: rejection without an error log (reference throws
        # an empty rejection)
        super().__init__("")
        self.reason = "Too many connection attempts"


class Throttle(Extension):
    def __init__(
        self,
        throttle: Optional[int] = 15,
        considered_seconds: float = 60,
        ban_time: float = 5,
        cleanup_interval: float = 90,
    ) -> None:
        self.throttle_limit = throttle
        self.considered_seconds = considered_seconds
        self.ban_time = ban_time
        self.cleanup_interval = cleanup_interval
        self.connections_by_ip: dict[str, list[float]] = {}
        self.banned_ips: dict[str, float] = {}
        self._cleanup_task: Optional[asyncio.Task] = None

    async def on_configure(self, data: Payload) -> None:
        if self._cleanup_task is None:
            self._cleanup_task = asyncio.ensure_future(self._cleanup_loop())

    async def on_destroy(self, data: Payload) -> None:
        if self._cleanup_task is not None:
            self._cleanup_task.cancel()
            self._cleanup_task = None

    async def _cleanup_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cleanup_interval)
            self.clear_maps()

    def clear_maps(self) -> None:
        now = time.monotonic()
        for ip in list(self.connections_by_ip):
            fresh = [
                t for t in self.connections_by_ip[ip] if t + self.considered_seconds > now
            ]
            if fresh:
                self.connections_by_ip[ip] = fresh
            else:
                del self.connections_by_ip[ip]
        for ip in list(self.banned_ips):
            if not self.is_banned(ip):
                del self.banned_ips[ip]

    def is_banned(self, ip: str) -> bool:
        banned_at = self.banned_ips.get(ip)
        if banned_at is None:
            return False
        return time.monotonic() < banned_at + self.ban_time * 60

    def _throttle(self, ip: str) -> bool:
        if not self.throttle_limit:
            return False
        if self.is_banned(ip):
            return True
        self.banned_ips.pop(ip, None)
        now = time.monotonic()
        attempts = self.connections_by_ip.get(ip, [])
        attempts.append(now)
        attempts = [t for t in attempts if t + self.considered_seconds > now]
        self.connections_by_ip[ip] = attempts
        if len(attempts) > self.throttle_limit:
            self.banned_ips[ip] = now
            return True
        return False

    async def on_connect(self, data: Payload) -> None:
        headers = data.request_headers or {}
        ip = (
            headers.get("x-real-ip")
            or headers.get("X-Real-IP")
            or headers.get("x-forwarded-for")
            or headers.get("X-Forwarded-For")
            or getattr(data.get("request"), "remote", None)
            or ""
        )
        if self._throttle(str(ip)):
            raise ThrottleRejection()
