"""SQLite persistence extension (reference `extension-sqlite`).

Uses the stdlib sqlite3 driver; blocking calls run in a worker thread.
Schema: documents(name UNIQUE, data BLOB) with upsert-on-conflict.
"""

from __future__ import annotations

import asyncio
import sqlite3
from typing import Optional

from ..server.types import Payload
from .database import Database

SQLITE_INMEMORY = ":memory:"

SCHEMA = """CREATE TABLE IF NOT EXISTS "documents" (
  "name" varchar(255) NOT NULL,
  "data" blob NOT NULL,
  UNIQUE(name)
)"""

SELECT_QUERY = 'SELECT data FROM "documents" WHERE name = :name ORDER BY rowid DESC'

UPSERT_QUERY = """INSERT INTO "documents" ("name", "data") VALUES (:name, :data)
  ON CONFLICT(name) DO UPDATE SET data = :data"""


class SQLite(Database):
    def __init__(self, database: str = SQLITE_INMEMORY, schema: str = SCHEMA) -> None:
        super().__init__(fetch=self._fetch, store=self._store)
        self.database = database
        self.schema = schema
        self.db: Optional[sqlite3.Connection] = None

    async def on_configure(self, data: Payload) -> None:
        self.db = sqlite3.connect(self.database, check_same_thread=False)
        self.db.execute(self.schema)
        self.db.commit()

    async def on_listen(self, data: Payload) -> None:
        if self.database == SQLITE_INMEMORY:
            import logging

            logging.getLogger("hocuspocus_tpu").warning(
                "The SQLite extension is configured as an in-memory database. "
                "All changes will be lost on restart!"
            )

    async def _fetch(self, data: Payload) -> Optional[bytes]:
        if self.db is None:
            return None

        def query() -> Optional[bytes]:
            row = self.db.execute(SELECT_QUERY, {"name": data.document_name}).fetchone()
            return row[0] if row else None

        return await asyncio.to_thread(query)

    async def _store(self, data: Payload) -> None:
        if self.db is None:
            return

        def write() -> None:
            self.db.execute(
                UPSERT_QUERY, {"name": data.document_name, "data": data["state"]}
            )
            self.db.commit()

        await asyncio.to_thread(write)

    async def on_destroy(self, data: Payload) -> None:
        if self.db is not None:
            self.db.close()
            self.db = None
