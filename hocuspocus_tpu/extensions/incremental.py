"""Incremental append-log persistence with snapshot compaction.

The reference's persistence model rewrites the FULL document state on
every debounced store (`extension-database` Database.onStoreDocument →
`Y.encodeStateAsUpdate(document)`, reference
`packages/extension-database/src/Database.ts:55-60`), which scales with
document size, not edit size. This extension stores only the DELTA
since the last store (state-vector diff), appending rows to a log, and
periodically compacts the log into one snapshot row — the persistence
shape the catch-up-storm baseline (BASELINE.md config 5) wants:
snapshot + replay.

Correctness notes:
- A stale in-memory last-state-vector (e.g. after another instance
  stored under the distributed lock) only makes the next delta larger
  and overlapping — applying overlapping updates is idempotent.
- Deltas capture deletions too: encode_state_as_update(doc, sv)
  includes the delete set, and loading merges every row in order.
"""

from __future__ import annotations

import asyncio
import sqlite3
import threading
from typing import Optional

from ..crdt import encode_state_as_update, encode_state_vector, merge_updates
from ..server.types import Payload
from .database import Database

_EMPTY_DELTA = b"\x00\x00"  # 0 struct clients + empty delete set

SCHEMA = """CREATE TABLE IF NOT EXISTS "document_updates" (
  "seq" INTEGER PRIMARY KEY AUTOINCREMENT,
  "name" varchar(255) NOT NULL,
  "data" blob NOT NULL
);
CREATE INDEX IF NOT EXISTS "document_updates_name" ON "document_updates" ("name")"""


class IncrementalSQLite(Database):
    """SQLite-backed append-log store: deltas per store, compaction."""

    def __init__(
        self,
        database: str = ":memory:",
        compact_after: int = 64,
    ) -> None:
        super().__init__(fetch=self._fetch)  # store path overridden below
        self.database = database
        self.compact_after = compact_after
        self.db: Optional[sqlite3.Connection] = None
        self._last_sv: dict[str, bytes] = {}
        # save_mutex serializes stores per DOCUMENT; different documents
        # store concurrently on this one shared connection, so every db
        # access takes this lock — otherwise another document's commit()
        # lands mid-compaction and makes the DELETE durable without the
        # snapshot INSERT (data loss on crash)
        self._db_lock = threading.Lock()

    async def on_configure(self, data: Payload) -> None:
        if self.db is not None:
            self.db.close()
        self.db = sqlite3.connect(self.database, check_same_thread=False)
        self.db.executescript(SCHEMA)
        self.db.commit()

    async def _fetch(self, data: Payload) -> Optional[bytes]:
        if self.db is None:
            return None
        name = data.document_name

        def query() -> Optional[bytes]:
            with self._db_lock:
                rows = self.db.execute(
                    'SELECT data FROM "document_updates" WHERE name = ? ORDER BY seq',
                    (name,),
                ).fetchall()
            if not rows:
                return None
            return merge_updates([row[0] for row in rows])

        merged = await asyncio.to_thread(query)
        return merged

    async def on_load_document(self, data: Payload) -> None:
        await super().on_load_document(data)
        # remember what is durable so the first store is a pure delta
        self._last_sv[data.document_name] = encode_state_vector(data.document)

    async def on_store_document(self, data: Payload) -> None:
        if self.db is None:
            return
        name = data.document_name
        delta = encode_state_as_update(data.document, self._last_sv.get(name))
        if delta == _EMPTY_DELTA:
            # nothing new since the last store — the log rows already
            # cover everything, so the WAL may still truncate
            data["wal_covered"] = True
            return
        current_sv = encode_state_vector(data.document)

        def count_rows() -> int:
            with self._db_lock:
                return self.db.execute(
                    'SELECT COUNT(*) FROM "document_updates" WHERE name = ?', (name,)
                ).fetchone()[0]

        # document.save_mutex serializes stores per doc, so the count
        # cannot change between this read and the write below
        count = await asyncio.to_thread(count_rows)
        # compact when the log is long: one snapshot row replaces it
        # (encoded here, on the event loop, so the doc cannot mutate
        # mid-encode)
        snapshot = (
            encode_state_as_update(data.document)
            if count + 1 > self.compact_after
            else None
        )

        def write() -> None:
            with self._db_lock:
                if snapshot is not None:
                    self.db.execute(
                        'DELETE FROM "document_updates" WHERE name = ?', (name,)
                    )
                    self.db.execute(
                        'INSERT INTO "document_updates" ("name", "data") VALUES (?, ?)',
                        (name, snapshot),
                    )
                else:
                    self.db.execute(
                        'INSERT INTO "document_updates" ("name", "data") VALUES (?, ?)',
                        (name, delta),
                    )
                self.db.commit()

        await asyncio.to_thread(write)
        self._last_sv[name] = current_sv
        # delta (or snapshot) row committed: the WAL suffix up to the
        # Durability extension's captured position is covered
        data["wal_covered"] = True

    async def after_unload_document(self, data: Payload) -> None:
        self._last_sv.pop(data.document_name, None)

    async def on_destroy(self, data: Payload) -> None:
        if self.db is not None:
            self.db.close()
            self.db = None

    def log_length(self, name: str) -> int:
        """Rows currently in the log for `name` (tests/operations)."""
        if self.db is None:
            return 0
        with self._db_lock:
            return self.db.execute(
                'SELECT COUNT(*) FROM "document_updates" WHERE name = ?', (name,)
            ).fetchone()[0]
