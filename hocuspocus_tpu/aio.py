"""Small asyncio helpers shared across the runtime."""

from __future__ import annotations

import asyncio


def spawn_tracked(registry: set, coro) -> "asyncio.Task":
    """Fire-and-forget with a strong reference.

    The event loop only weakly references tasks: an unreferenced
    fire-and-forget task can be garbage-collected mid-flight and
    silently never complete (dropping a frame, stalling a pipeline, or
    stranding a lock acquisition). The caller-owned `registry` set
    holds the strong ref until the task settles.
    """
    task = asyncio.ensure_future(coro)
    registry.add(task)
    task.add_done_callback(registry.discard)
    return task
