"""Small asyncio helpers shared across the runtime."""

from __future__ import annotations

import asyncio
import random


def backoff_delay_s(attempt: int, base_ms: float, max_ms: float) -> float:
    """Bounded exponential backoff with full jitter, in SECONDS.

    `attempt` counts completed failures (0 = first retry). The ceiling
    doubles per attempt up to `max_ms`; the delay is drawn uniformly
    from [ceiling/2, ceiling] so a herd of retriers spreads out. Shared
    by the store-retry chain (server/hocuspocus.py) and the webhook
    delivery retries (extensions/webhook.py)."""
    ceiling = min(base_ms * (2 ** attempt), max_ms)
    return random.uniform(ceiling / 2, ceiling) / 1000.0


async def await_synced(providers, timeout: float = 30.0, what: str = "providers") -> None:
    """Event-driven sync barrier over providers.

    Resolves on each provider's "synced" emit (no interval polling), so
    the timeout is a pure liveness bound. Raises TimeoutError naming
    `what` and the stragglers' count."""
    providers = list(providers)
    loop = asyncio.get_running_loop()
    handlers = []
    futs = []
    try:
        for p in providers:
            if p.synced:
                continue
            fut = loop.create_future()

            def handler(payload, fut=fut):
                if payload.get("state") and not fut.done():
                    fut.set_result(None)

            p.on("synced", handler)
            handlers.append((p, handler))
            futs.append(fut)
        if futs:
            await asyncio.wait_for(asyncio.gather(*futs), timeout=timeout)
    except asyncio.TimeoutError:
        raise TimeoutError(
            f"{what}: {sum(1 for p in providers if not p.synced)}/"
            f"{len(providers)} providers never synced"
        )
    finally:
        for p, handler in handlers:
            p.off("synced", handler)


def spawn_tracked(registry: set, coro) -> "asyncio.Task":
    """Fire-and-forget with a strong reference.

    The event loop only weakly references tasks: an unreferenced
    fire-and-forget task can be garbage-collected mid-flight and
    silently never complete (dropping a frame, stalling a pipeline, or
    stranding a lock acquisition). The caller-owned `registry` set
    holds the strong ref until the task settles.
    """
    task = asyncio.ensure_future(coro)
    registry.add(task)
    task.add_done_callback(registry.discard)
    return task
