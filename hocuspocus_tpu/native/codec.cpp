// Native update codec for hocuspocus_tpu.
//
// C++ implementation of the Yjs v1 update *decode* hot path (lib0
// varints, struct sections, delete sets) feeding the TPU merge plane's
// host-side lowering. Replaces the reference's lib0/yjs JavaScript
// decode layer (SURVEY.md §2.2 "native equivalents"); the pure-Python
// decoder in hocuspocus_tpu.crdt remains the fallback and the
// correctness reference.
//
// Exposes:
//   decode_update(bytes) -> (structs, deletes)
//     structs: list of (client, clock, kind, origin_client, origin_clock,
//              right_client, right_clock, payload)
//              kind 0 = string run (payload: str)
//                   1 = deleted run (payload: length int)
//                   2 = GC run (payload: length int)
//                   3 = Skip run (payload: length int)
//                   4 = other content (payload: length int) — caller
//                       falls back to the Python path for this doc
//     deletes: list of (client, clock, length)
//   utf16_len(str) -> int      (JS string .length semantics)
//
// Wire-frame hot path (reference IncomingMessage/OutgoingMessage,
// `packages/server/src/OutgoingMessage.ts:24-28` frame layout
// [varString documentName][varUint msgType][payload]):
//   parse_frame_header(bytes) -> (document_name, msg_type, offset)
//     one call replacing the per-message Python varint reads used for
//     routing (ClientConnection.messageHandler) and dispatch
//   build_update_frame(name, update, reply) -> bytes
//     the broadcast frame [name][Sync|SyncReply][yjsUpdate][update] —
//     built once per document update (Document.handleUpdate fan-out)
//   build_sync_status_frame(name, ok) -> bytes
//     the per-update durability ack [name][SyncStatus][0|1]
//
// Build: g++ -O2 -shared -fPIC (see build.py); no external deps.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

struct Reader {
    const uint8_t* buf;
    Py_ssize_t len;
    Py_ssize_t pos = 0;

    bool eof() const { return pos >= len; }

    uint8_t u8() {
        if (pos >= len) throw std::runtime_error("unexpected end of buffer");
        return buf[pos++];
    }

    uint64_t var_uint() {
        uint64_t num = 0;
        int shift = 0;
        while (true) {
            uint8_t b = u8();
            num |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (b < 0x80) return num;
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
    }

    // Validate an untrusted varuint length against the remaining bytes
    // BEFORE any signed cast: a length near 2^64 cast to Py_ssize_t
    // goes negative and would slip past a `pos + n > len` check,
    // turning a 10-byte pre-auth frame into an out-of-bounds read.
    Py_ssize_t checked_len(uint64_t n) {
        if (n > static_cast<uint64_t>(len - pos))
            throw std::runtime_error("length prefix exceeds buffer");
        return static_cast<Py_ssize_t>(n);
    }

    void skip(Py_ssize_t n) {
        if (n < 0 || pos + n > len)
            throw std::runtime_error("unexpected end of buffer");
        pos += n;
    }

    const char* bytes(Py_ssize_t n) {
        if (n < 0 || pos + n > len)
            throw std::runtime_error("unexpected end of buffer");
        const char* p = reinterpret_cast<const char*>(buf + pos);
        pos += n;
        return p;
    }

    // lib0 readVarString: utf-8 bytes with varuint length prefix
    std::pair<const char*, Py_ssize_t> var_string() {
        Py_ssize_t n = checked_len(var_uint());
        return {bytes(n), n};
    }

    void skip_var_string() { skip(checked_len(var_uint())); }

    void skip_var_bytes() { skip(checked_len(var_uint())); }

    // lib0 readAny (tags 116-127) — value discarded, cursor advanced
    void skip_any() {
        uint8_t tag = u8();
        switch (tag) {
            case 127:  // undefined
            case 126:  // null
            case 121:  // false
            case 120:  // true
                return;
            case 125: {  // varint
                uint8_t b = u8();
                while (b & 0x80) b = u8();
                return;
            }
            case 124: skip(4); return;  // float32
            case 123: skip(8); return;  // float64
            case 122: skip(8); return;  // bigint64
            case 119: skip_var_string(); return;
            case 118: {  // object
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) {
                    skip_var_string();
                    skip_any();
                }
                return;
            }
            case 117: {  // array
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) skip_any();
                return;
            }
            case 116: skip_var_bytes(); return;
            default:
                throw std::runtime_error("unknown Any tag");
        }
    }
};

constexpr uint8_t BIT_ORIGIN = 0x80;
constexpr uint8_t BIT_RIGHT_ORIGIN = 0x40;
constexpr uint8_t BIT_PARENT_SUB = 0x20;
constexpr int64_t NONE_CLIENT = 0xFFFFFFFFll;

// UTF-16 code-unit count of a UTF-8 byte range (JS string length).
Py_ssize_t utf8_to_utf16_len(const char* s, Py_ssize_t n) {
    Py_ssize_t units = 0;
    for (Py_ssize_t i = 0; i < n;) {
        uint8_t c = static_cast<uint8_t>(s[i]);
        if (c < 0x80) { i += 1; units += 1; }
        else if (c < 0xE0) { i += 2; units += 1; }
        else if (c < 0xF0) { i += 3; units += 1; }
        else { i += 4; units += 2; }  // astral -> surrogate pair
    }
    return units;
}

PyObject* decode_update(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Reader r{static_cast<const uint8_t*>(view.buf), view.len};

    PyObject* structs = PyList_New(0);
    PyObject* deletes = PyList_New(0);
    if (!structs || !deletes) {
        PyBuffer_Release(&view);
        Py_XDECREF(structs);
        Py_XDECREF(deletes);
        return nullptr;
    }

    try {
        uint64_t num_clients = r.var_uint();
        for (uint64_t ci = 0; ci < num_clients; ci++) {
            uint64_t num_structs = r.var_uint();
            int64_t client = static_cast<int64_t>(r.var_uint());
            int64_t clock = static_cast<int64_t>(r.var_uint());
            for (uint64_t si = 0; si < num_structs; si++) {
                uint8_t info = r.u8();
                uint8_t ref = info & 0x1F;
                int64_t kind;
                int64_t origin_client = NONE_CLIENT, origin_clock = 0;
                int64_t right_client = NONE_CLIENT, right_clock = 0;
                PyObject* payload = nullptr;
                int64_t length = 0;

                if (ref == 0) {  // GC
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 2;
                    payload = PyLong_FromLongLong(length);
                } else if (ref == 10) {  // Skip
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 3;
                    payload = PyLong_FromLongLong(length);
                } else {
                    if (info & BIT_ORIGIN) {
                        origin_client = static_cast<int64_t>(r.var_uint());
                        origin_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (info & BIT_RIGHT_ORIGIN) {
                        right_client = static_cast<int64_t>(r.var_uint());
                        right_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (!(info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                        // parent info
                        if (r.var_uint() == 1) {
                            r.skip_var_string();  // root key
                        } else {
                            r.var_uint();  // parent id client
                            r.var_uint();  // parent id clock
                        }
                        if (info & BIT_PARENT_SUB) r.skip_var_string();
                    }
                    switch (ref) {
                        case 1: {  // ContentDeleted
                            length = static_cast<int64_t>(r.var_uint());
                            kind = 1;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 4: {  // ContentString
                            auto [p, n] = r.var_string();
                            length = utf8_to_utf16_len(p, n);
                            kind = 0;
                            payload = PyUnicode_DecodeUTF8(p, n, "replace");
                            break;
                        }
                        case 2: {  // ContentJSON
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_var_string();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 3:  // ContentBinary
                            r.skip_var_bytes();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 5:  // ContentEmbed
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 6:  // ContentFormat
                            r.skip_var_string();
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 7: {  // ContentType
                            uint64_t type_ref = r.var_uint();
                            if (type_ref == 3 || type_ref == 5) r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 8: {  // ContentAny
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_any();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 9:  // ContentDoc
                            r.skip_var_string();
                            r.skip_any();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        default:
                            throw std::runtime_error("unknown content ref");
                    }
                }
                if (!payload) throw std::runtime_error("payload alloc failed");
                PyObject* tup = Py_BuildValue(
                    "(LLLLLLLN)", client, clock, kind, origin_client, origin_clock,
                    right_client, right_clock, payload);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(structs, tup);
                Py_DECREF(tup);
                clock += length;
            }
        }
        // delete set
        uint64_t ds_clients = r.var_uint();
        for (uint64_t i = 0; i < ds_clients; i++) {
            int64_t client = static_cast<int64_t>(r.var_uint());
            uint64_t ranges = r.var_uint();
            for (uint64_t j = 0; j < ranges; j++) {
                int64_t clock = static_cast<int64_t>(r.var_uint());
                int64_t dlen = static_cast<int64_t>(r.var_uint());
                PyObject* tup = Py_BuildValue("(LLL)", client, clock, dlen);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(deletes, tup);
                Py_DECREF(tup);
            }
        }
    } catch (const std::exception& e) {
        PyBuffer_Release(&view);
        Py_DECREF(structs);
        Py_DECREF(deletes);
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }

    PyBuffer_Release(&view);
    return Py_BuildValue("(NN)", structs, deletes);
}

PyObject* utf16_len(PyObject* /*self*/, PyObject* arg) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(arg, &n);
    if (!s) return nullptr;
    return PyLong_FromSsize_t(utf8_to_utf16_len(s, n));
}

// lib0 writeVarUint: 7-bit groups, little-endian, continuation bit 0x80
void put_var_uint(std::string& out, uint64_t num) {
    while (num > 0x7F) {
        out.push_back(static_cast<char>(0x80 | (num & 0x7F)));
        num >>= 7;
    }
    out.push_back(static_cast<char>(num));
}

void put_var_string(std::string& out, const char* s, Py_ssize_t n) {
    put_var_uint(out, static_cast<uint64_t>(n));
    out.append(s, static_cast<size_t>(n));
}

constexpr uint64_t MSG_SYNC = 0;
constexpr uint64_t MSG_SYNC_REPLY = 4;
constexpr uint64_t MSG_SYNC_STATUS = 8;
constexpr uint64_t MSG_YJS_UPDATE = 2;

PyObject* parse_frame_header(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Reader r{static_cast<const uint8_t*>(view.buf), view.len};
    PyObject* result = nullptr;
    try {
        auto [p, n] = r.var_string();
        uint64_t msg_type = r.var_uint();
        // strict decode like the Python Decoder.read_var_string: both
        // paths must reject an invalid-UTF-8 name the same way
        PyObject* name = PyUnicode_DecodeUTF8(p, n, nullptr);
        if (!name) {
            PyErr_Clear();
            throw std::runtime_error("invalid utf-8 in document name");
        }
        result = Py_BuildValue("(NKn)", name, msg_type, r.pos);
    } catch (const std::exception& e) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }
    PyBuffer_Release(&view);
    return result;
}

PyObject* build_update_frame(PyObject* /*self*/, PyObject* args) {
    const char* name;
    Py_ssize_t name_len;
    Py_buffer update;
    int reply = 0;
    if (!PyArg_ParseTuple(args, "s#y*|p", &name, &name_len, &update, &reply))
        return nullptr;
    std::string out;
    out.reserve(static_cast<size_t>(name_len + update.len) + 12);
    put_var_string(out, name, name_len);
    put_var_uint(out, reply ? MSG_SYNC_REPLY : MSG_SYNC);
    put_var_uint(out, MSG_YJS_UPDATE);
    put_var_uint(out, static_cast<uint64_t>(update.len));
    out.append(static_cast<const char*>(update.buf),
               static_cast<size_t>(update.len));
    PyBuffer_Release(&update);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

PyObject* build_sync_status_frame(PyObject* /*self*/, PyObject* args) {
    const char* name;
    Py_ssize_t name_len;
    int ok = 0;
    if (!PyArg_ParseTuple(args, "s#p", &name, &name_len, &ok)) return nullptr;
    std::string out;
    out.reserve(static_cast<size_t>(name_len) + 8);
    put_var_string(out, name, name_len);
    put_var_uint(out, MSG_SYNC_STATUS);
    put_var_uint(out, ok ? 1 : 0);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

// Serve-path struct-section encoder (the write mirror of decode_update,
// restricted to the shapes the TPU plane serves hot: string runs and GC
// ranges). Python keeps the semantic work — cutoff trimming, first-item
// offset/origin rewrite, group ordering — and hands fully-resolved
// groups here for pure byte emission. Replaces ~15 Python-level calls
// per item in `crdt/update.py:_write_structs` / `crdt/structs.py
// Item.write` on broadcast/sync serves (reference hot path:
// `packages/server/src/MessageReceiver.ts:137-213` encode side).
//
//   encode_text_window(groups) -> bytes
//     groups: list of (client, write_clock, items), caller-ordered
//     item: (kind, origin_client, origin_clock, right_client,
//            right_clock, parent_name|None, payload)
//       kind 0: string run — payload str; negative origin client means
//               absent; when both origins absent parent_name (a root
//               type name) is written
//       kind 1: GC range — payload int length
//       kind 2: deleted run (ContentDeleted) — payload int length;
//               origins/parent rules as kind 0
constexpr uint8_t CONTENT_STRING_REF = 4;
constexpr uint8_t CONTENT_DELETED_REF = 1;
constexpr uint8_t STRUCT_GC_REF = 0;

PyObject* encode_text_window(PyObject* /*self*/, PyObject* arg) {
    PyObject* groups = PySequence_Fast(arg, "groups must be a sequence");
    if (!groups) return nullptr;
    std::string out;
    out.reserve(256);
    Py_ssize_t num_groups = PySequence_Fast_GET_SIZE(groups);
    put_var_uint(out, static_cast<uint64_t>(num_groups));
    for (Py_ssize_t g = 0; g < num_groups; ++g) {
        PyObject* group = PySequence_Fast_GET_ITEM(groups, g);
        unsigned long long client, write_clock;
        PyObject* items_obj;
        if (!PyArg_ParseTuple(group, "KKO", &client, &write_clock, &items_obj)) {
            Py_DECREF(groups);
            return nullptr;
        }
        PyObject* items = PySequence_Fast(items_obj, "items must be a sequence");
        if (!items) {
            Py_DECREF(groups);
            return nullptr;
        }
        Py_ssize_t num_items = PySequence_Fast_GET_SIZE(items);
        put_var_uint(out, static_cast<uint64_t>(num_items));
        put_var_uint(out, client);
        put_var_uint(out, write_clock);
        for (Py_ssize_t i = 0; i < num_items; ++i) {
            PyObject* item = PySequence_Fast_GET_ITEM(items, i);
            int kind;
            long long oc, ok, rc, rk;
            PyObject* parent_name;
            PyObject* payload;
            if (!PyArg_ParseTuple(item, "iLLLLOO", &kind, &oc, &ok,
                                  &rc, &rk, &parent_name, &payload)) {
                Py_DECREF(items);
                Py_DECREF(groups);
                return nullptr;
            }
            if (kind == 1) {  // GC range
                out.push_back(static_cast<char>(STRUCT_GC_REF));
                unsigned long long len = PyLong_AsUnsignedLongLong(payload);
                if (PyErr_Occurred()) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, len);
                continue;
            }
            uint8_t info =
                (kind == 2) ? CONTENT_DELETED_REF : CONTENT_STRING_REF;
            if (oc >= 0) info |= BIT_ORIGIN;
            if (rc >= 0) info |= BIT_RIGHT_ORIGIN;
            out.push_back(static_cast<char>(info));
            if (oc >= 0) {
                put_var_uint(out, static_cast<uint64_t>(oc));
                put_var_uint(out, static_cast<uint64_t>(ok));
            }
            if (rc >= 0) {
                put_var_uint(out, static_cast<uint64_t>(rc));
                put_var_uint(out, static_cast<uint64_t>(rk));
            }
            if (oc < 0 && rc < 0) {
                // origin-less: wire parent is a root type name
                Py_ssize_t n;
                const char* s = PyUnicode_AsUTF8AndSize(parent_name, &n);
                if (!s) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, 1);
                put_var_string(out, s, n);
            }
            if (kind == 2) {  // deleted run: just its length
                unsigned long long len = PyLong_AsUnsignedLongLong(payload);
                if (PyErr_Occurred()) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, len);
            } else {
                Py_ssize_t n;
                const char* s = PyUnicode_AsUTF8AndSize(payload, &n);
                if (!s) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_string(out, s, n);
            }
        }
        Py_DECREF(items);
    }
    Py_DECREF(groups);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

PyMethodDef methods[] = {
    {"decode_update", decode_update, METH_O,
     "Decode a Yjs v1 update into (structs, deletes) tuples."},
    {"encode_text_window", encode_text_window, METH_O,
     "Encode resolved (string|GC) struct groups into update bytes."},
    {"utf16_len", utf16_len, METH_O, "UTF-16 code unit count of a string."},
    {"parse_frame_header", parse_frame_header, METH_O,
     "Parse [varString name][varUint type] -> (name, type, offset)."},
    {"build_update_frame", build_update_frame, METH_VARARGS,
     "Build [name][Sync|SyncReply][yjsUpdate][update] broadcast frame."},
    {"build_sync_status_frame", build_sync_status_frame, METH_VARARGS,
     "Build [name][SyncStatus][0|1] durability ack frame."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native Yjs v1 update codec (C++)", -1, methods,
};

}  // namespace

// text_lane.cpp — the native host path for plain-text documents
void register_text_lane(PyObject* module);

PyMODINIT_FUNC PyInit__codec(void) {
    PyObject* m = PyModule_Create(&module);
    if (m) register_text_lane(m);
    return m;
}
