// Native update codec for hocuspocus_tpu.
//
// C++ implementation of the Yjs v1 update *decode* hot path (lib0
// varints, struct sections, delete sets) feeding the TPU merge plane's
// host-side lowering. Replaces the reference's lib0/yjs JavaScript
// decode layer (SURVEY.md §2.2 "native equivalents"); the pure-Python
// decoder in hocuspocus_tpu.crdt remains the fallback and the
// correctness reference.
//
// Exposes:
//   decode_update(bytes) -> (structs, deletes)
//     structs: list of (client, clock, kind, origin_client, origin_clock,
//              right_client, right_clock, payload)
//              kind 0 = string run (payload: str)
//                   1 = deleted run (payload: length int)
//                   2 = GC run (payload: length int)
//                   3 = Skip run (payload: length int)
//                   4 = other content (payload: length int) — caller
//                       falls back to the Python path for this doc
//     deletes: list of (client, clock, length)
//   utf16_len(str) -> int      (JS string .length semantics)
//
// Build: g++ -O2 -shared -fPIC (see build.py); no external deps.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

struct Reader {
    const uint8_t* buf;
    Py_ssize_t len;
    Py_ssize_t pos = 0;

    bool eof() const { return pos >= len; }

    uint8_t u8() {
        if (pos >= len) throw std::runtime_error("unexpected end of buffer");
        return buf[pos++];
    }

    uint64_t var_uint() {
        uint64_t num = 0;
        int shift = 0;
        while (true) {
            uint8_t b = u8();
            num |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (b < 0x80) return num;
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
    }

    void skip(Py_ssize_t n) {
        if (pos + n > len) throw std::runtime_error("unexpected end of buffer");
        pos += n;
    }

    const char* bytes(Py_ssize_t n) {
        if (pos + n > len) throw std::runtime_error("unexpected end of buffer");
        const char* p = reinterpret_cast<const char*>(buf + pos);
        pos += n;
        return p;
    }

    // lib0 readVarString: utf-8 bytes with varuint length prefix
    std::pair<const char*, Py_ssize_t> var_string() {
        Py_ssize_t n = static_cast<Py_ssize_t>(var_uint());
        return {bytes(n), n};
    }

    void skip_var_string() {
        Py_ssize_t n = static_cast<Py_ssize_t>(var_uint());
        skip(n);
    }

    void skip_var_bytes() {
        Py_ssize_t n = static_cast<Py_ssize_t>(var_uint());
        skip(n);
    }

    // lib0 readAny (tags 116-127) — value discarded, cursor advanced
    void skip_any() {
        uint8_t tag = u8();
        switch (tag) {
            case 127:  // undefined
            case 126:  // null
            case 121:  // false
            case 120:  // true
                return;
            case 125: {  // varint
                uint8_t b = u8();
                while (b & 0x80) b = u8();
                return;
            }
            case 124: skip(4); return;  // float32
            case 123: skip(8); return;  // float64
            case 122: skip(8); return;  // bigint64
            case 119: skip_var_string(); return;
            case 118: {  // object
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) {
                    skip_var_string();
                    skip_any();
                }
                return;
            }
            case 117: {  // array
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) skip_any();
                return;
            }
            case 116: skip_var_bytes(); return;
            default:
                throw std::runtime_error("unknown Any tag");
        }
    }
};

constexpr uint8_t BIT_ORIGIN = 0x80;
constexpr uint8_t BIT_RIGHT_ORIGIN = 0x40;
constexpr uint8_t BIT_PARENT_SUB = 0x20;
constexpr int64_t NONE_CLIENT = 0xFFFFFFFFll;

// UTF-16 code-unit count of a UTF-8 byte range (JS string length).
Py_ssize_t utf8_to_utf16_len(const char* s, Py_ssize_t n) {
    Py_ssize_t units = 0;
    for (Py_ssize_t i = 0; i < n;) {
        uint8_t c = static_cast<uint8_t>(s[i]);
        if (c < 0x80) { i += 1; units += 1; }
        else if (c < 0xE0) { i += 2; units += 1; }
        else if (c < 0xF0) { i += 3; units += 1; }
        else { i += 4; units += 2; }  // astral -> surrogate pair
    }
    return units;
}

PyObject* decode_update(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Reader r{static_cast<const uint8_t*>(view.buf), view.len};

    PyObject* structs = PyList_New(0);
    PyObject* deletes = PyList_New(0);
    if (!structs || !deletes) {
        PyBuffer_Release(&view);
        Py_XDECREF(structs);
        Py_XDECREF(deletes);
        return nullptr;
    }

    try {
        uint64_t num_clients = r.var_uint();
        for (uint64_t ci = 0; ci < num_clients; ci++) {
            uint64_t num_structs = r.var_uint();
            int64_t client = static_cast<int64_t>(r.var_uint());
            int64_t clock = static_cast<int64_t>(r.var_uint());
            for (uint64_t si = 0; si < num_structs; si++) {
                uint8_t info = r.u8();
                uint8_t ref = info & 0x1F;
                int64_t kind;
                int64_t origin_client = NONE_CLIENT, origin_clock = 0;
                int64_t right_client = NONE_CLIENT, right_clock = 0;
                PyObject* payload = nullptr;
                int64_t length = 0;

                if (ref == 0) {  // GC
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 2;
                    payload = PyLong_FromLongLong(length);
                } else if (ref == 10) {  // Skip
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 3;
                    payload = PyLong_FromLongLong(length);
                } else {
                    if (info & BIT_ORIGIN) {
                        origin_client = static_cast<int64_t>(r.var_uint());
                        origin_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (info & BIT_RIGHT_ORIGIN) {
                        right_client = static_cast<int64_t>(r.var_uint());
                        right_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (!(info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                        // parent info
                        if (r.var_uint() == 1) {
                            r.skip_var_string();  // root key
                        } else {
                            r.var_uint();  // parent id client
                            r.var_uint();  // parent id clock
                        }
                        if (info & BIT_PARENT_SUB) r.skip_var_string();
                    }
                    switch (ref) {
                        case 1: {  // ContentDeleted
                            length = static_cast<int64_t>(r.var_uint());
                            kind = 1;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 4: {  // ContentString
                            auto [p, n] = r.var_string();
                            length = utf8_to_utf16_len(p, n);
                            kind = 0;
                            payload = PyUnicode_DecodeUTF8(p, n, "replace");
                            break;
                        }
                        case 2: {  // ContentJSON
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_var_string();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 3:  // ContentBinary
                            r.skip_var_bytes();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 5:  // ContentEmbed
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 6:  // ContentFormat
                            r.skip_var_string();
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 7: {  // ContentType
                            uint64_t type_ref = r.var_uint();
                            if (type_ref == 3 || type_ref == 5) r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 8: {  // ContentAny
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_any();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 9:  // ContentDoc
                            r.skip_var_string();
                            r.skip_any();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        default:
                            throw std::runtime_error("unknown content ref");
                    }
                }
                if (!payload) throw std::runtime_error("payload alloc failed");
                PyObject* tup = Py_BuildValue(
                    "(LLLLLLLN)", client, clock, kind, origin_client, origin_clock,
                    right_client, right_clock, payload);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(structs, tup);
                Py_DECREF(tup);
                clock += length;
            }
        }
        // delete set
        uint64_t ds_clients = r.var_uint();
        for (uint64_t i = 0; i < ds_clients; i++) {
            int64_t client = static_cast<int64_t>(r.var_uint());
            uint64_t ranges = r.var_uint();
            for (uint64_t j = 0; j < ranges; j++) {
                int64_t clock = static_cast<int64_t>(r.var_uint());
                int64_t dlen = static_cast<int64_t>(r.var_uint());
                PyObject* tup = Py_BuildValue("(LLL)", client, clock, dlen);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(deletes, tup);
                Py_DECREF(tup);
            }
        }
    } catch (const std::exception& e) {
        PyBuffer_Release(&view);
        Py_DECREF(structs);
        Py_DECREF(deletes);
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }

    PyBuffer_Release(&view);
    return Py_BuildValue("(NN)", structs, deletes);
}

PyObject* utf16_len(PyObject* /*self*/, PyObject* arg) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(arg, &n);
    if (!s) return nullptr;
    return PyLong_FromSsize_t(utf8_to_utf16_len(s, n));
}

PyMethodDef methods[] = {
    {"decode_update", decode_update, METH_O,
     "Decode a Yjs v1 update into (structs, deletes) tuples."},
    {"utf16_len", utf16_len, METH_O, "UTF-16 code unit count of a string."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native Yjs v1 update codec (C++)", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__codec(void) { return PyModule_Create(&module); }
