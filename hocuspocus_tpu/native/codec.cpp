// Native update codec for hocuspocus_tpu.
//
// C++ implementation of the Yjs v1 update *decode* hot path (lib0
// varints, struct sections, delete sets) feeding the TPU merge plane's
// host-side lowering. Replaces the reference's lib0/yjs JavaScript
// decode layer (SURVEY.md §2.2 "native equivalents"); the pure-Python
// decoder in hocuspocus_tpu.crdt remains the fallback and the
// correctness reference.
//
// Exposes:
//   decode_update(bytes) -> (structs, deletes)
//     structs: list of (client, clock, kind, origin_client, origin_clock,
//              right_client, right_clock, payload)
//              kind 0 = string run (payload: str)
//                   1 = deleted run (payload: length int)
//                   2 = GC run (payload: length int)
//                   3 = Skip run (payload: length int)
//                   4 = other content (payload: length int) — caller
//                       falls back to the Python path for this doc
//     deletes: list of (client, clock, length)
//   utf16_len(str) -> int      (JS string .length semantics)
//
// Wire-frame hot path (reference IncomingMessage/OutgoingMessage,
// `packages/server/src/OutgoingMessage.ts:24-28` frame layout
// [varString documentName][varUint msgType][payload]):
//   parse_frame_header(bytes) -> (document_name, msg_type, offset)
//     one call replacing the per-message Python varint reads used for
//     routing (ClientConnection.messageHandler) and dispatch
//   build_update_frame(name, update, reply) -> bytes
//     the broadcast frame [name][Sync|SyncReply][yjsUpdate][update] —
//     built once per document update (Document.handleUpdate fan-out)
//   build_sync_status_frame(name, ok) -> bytes
//     the per-update durability ack [name][SyncStatus][0|1]
//
// Batched wire path (one Python->C++ call per drain batch, GIL released
// during the pure-byte passes — protocol/frames.py entry points):
//   parse_frame_headers_batch(frames, skip_malformed=False)
//     -> list[(name, type, offset)] (or None slots in skip mode);
//     repeated document names within a batch share ONE str object
//   build_update_frames_batch(items) -> list[bytes]
//     items: (name, update[, reply]) triples, frames built in one pass
//   coalesce_updates(updates) -> bytes | None
//     docless merge of N Y-updates at the BYTE level: struct spans are
//     copied verbatim when that is provably identical to the Python
//     merge_updates re-encode (canonical varints, strict UTF-8, content
//     refs in {GC, Deleted, Binary, String, Skip}, no overlapping runs
//     needing an offset split); returns None when it cannot guarantee
//     byte identity and the caller falls back to the Python merge
//   scan_update_frontier(update) -> (list[(client, end_clock)], ds_empty)
//     per-client clock frontier of an update without building structs —
//     powers the idempotent-redelivery fast-drop in crdt/update.py
//   parse_envelope(bytes) / parse_envelopes_batch(raws, skip_malformed)
//     edge relay envelope [kind][session][aux][payload] decode
//   read_var_uints(data, pos, count) -> (tuple, new_pos)
//   encode_var_uints(seq) -> bytes
//     bulk varint helpers for crdt/encoding.py hot loops
//
// Build: g++ -O2 -shared -fPIC (see build.py); no external deps.
// NATIVE_API_VERSION gates the stale-.so rebuild in native/__init__.py —
// bump it whenever a symbol is added or a signature changes.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <tuple>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct Reader {
    const uint8_t* buf;
    Py_ssize_t len;
    Py_ssize_t pos = 0;
    // Set when any varint read so far was non-minimal (e.g. 0x80 0x00).
    // A re-encode of such input would shrink it, so byte-verbatim span
    // copies (coalesce_updates) are only safe while this stays false.
    bool noncanonical = false;

    bool eof() const { return pos >= len; }

    uint8_t u8() {
        if (pos >= len) throw std::runtime_error("unexpected end of buffer");
        return buf[pos++];
    }

    uint64_t var_uint() {
        uint64_t num = 0;
        int shift = 0;
        uint8_t last = 0;
        while (true) {
            uint8_t b = u8();
            last = b;
            num |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (b < 0x80) break;
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
        // minimal encoding never ends with a zero continuation group
        if (shift > 0 && last == 0) noncanonical = true;
        return num;
    }

    // Validate an untrusted varuint length against the remaining bytes
    // BEFORE any signed cast: a length near 2^64 cast to Py_ssize_t
    // goes negative and would slip past a `pos + n > len` check,
    // turning a 10-byte pre-auth frame into an out-of-bounds read.
    Py_ssize_t checked_len(uint64_t n) {
        if (n > static_cast<uint64_t>(len - pos))
            throw std::runtime_error("length prefix exceeds buffer");
        return static_cast<Py_ssize_t>(n);
    }

    void skip(Py_ssize_t n) {
        if (n < 0 || pos + n > len)
            throw std::runtime_error("unexpected end of buffer");
        pos += n;
    }

    const char* bytes(Py_ssize_t n) {
        if (n < 0 || pos + n > len)
            throw std::runtime_error("unexpected end of buffer");
        const char* p = reinterpret_cast<const char*>(buf + pos);
        pos += n;
        return p;
    }

    // lib0 readVarString: utf-8 bytes with varuint length prefix
    std::pair<const char*, Py_ssize_t> var_string() {
        Py_ssize_t n = checked_len(var_uint());
        return {bytes(n), n};
    }

    void skip_var_string() { skip(checked_len(var_uint())); }

    void skip_var_bytes() { skip(checked_len(var_uint())); }

    // lib0 readAny (tags 116-127) — value discarded, cursor advanced
    void skip_any() {
        uint8_t tag = u8();
        switch (tag) {
            case 127:  // undefined
            case 126:  // null
            case 121:  // false
            case 120:  // true
                return;
            case 125: {  // varint
                uint8_t b = u8();
                while (b & 0x80) b = u8();
                return;
            }
            case 124: skip(4); return;  // float32
            case 123: skip(8); return;  // float64
            case 122: skip(8); return;  // bigint64
            case 119: skip_var_string(); return;
            case 118: {  // object
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) {
                    skip_var_string();
                    skip_any();
                }
                return;
            }
            case 117: {  // array
                uint64_t n = var_uint();
                for (uint64_t i = 0; i < n; i++) skip_any();
                return;
            }
            case 116: skip_var_bytes(); return;
            default:
                throw std::runtime_error("unknown Any tag");
        }
    }
};

constexpr uint8_t BIT_ORIGIN = 0x80;
constexpr uint8_t BIT_RIGHT_ORIGIN = 0x40;
constexpr uint8_t BIT_PARENT_SUB = 0x20;
constexpr int64_t NONE_CLIENT = 0xFFFFFFFFll;

// UTF-16 code-unit count of a UTF-8 byte range (JS string length).
Py_ssize_t utf8_to_utf16_len(const char* s, Py_ssize_t n) {
    Py_ssize_t units = 0;
    for (Py_ssize_t i = 0; i < n;) {
        uint8_t c = static_cast<uint8_t>(s[i]);
        if (c < 0x80) { i += 1; units += 1; }
        else if (c < 0xE0) { i += 2; units += 1; }
        else if (c < 0xF0) { i += 3; units += 1; }
        else { i += 4; units += 2; }  // astral -> surrogate pair
    }
    return units;
}

PyObject* decode_update(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Reader r{static_cast<const uint8_t*>(view.buf), view.len};

    PyObject* structs = PyList_New(0);
    PyObject* deletes = PyList_New(0);
    if (!structs || !deletes) {
        PyBuffer_Release(&view);
        Py_XDECREF(structs);
        Py_XDECREF(deletes);
        return nullptr;
    }

    try {
        uint64_t num_clients = r.var_uint();
        for (uint64_t ci = 0; ci < num_clients; ci++) {
            uint64_t num_structs = r.var_uint();
            int64_t client = static_cast<int64_t>(r.var_uint());
            int64_t clock = static_cast<int64_t>(r.var_uint());
            for (uint64_t si = 0; si < num_structs; si++) {
                uint8_t info = r.u8();
                uint8_t ref = info & 0x1F;
                int64_t kind;
                int64_t origin_client = NONE_CLIENT, origin_clock = 0;
                int64_t right_client = NONE_CLIENT, right_clock = 0;
                PyObject* payload = nullptr;
                int64_t length = 0;

                if (ref == 0) {  // GC
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 2;
                    payload = PyLong_FromLongLong(length);
                } else if (ref == 10) {  // Skip
                    length = static_cast<int64_t>(r.var_uint());
                    kind = 3;
                    payload = PyLong_FromLongLong(length);
                } else {
                    if (info & BIT_ORIGIN) {
                        origin_client = static_cast<int64_t>(r.var_uint());
                        origin_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (info & BIT_RIGHT_ORIGIN) {
                        right_client = static_cast<int64_t>(r.var_uint());
                        right_clock = static_cast<int64_t>(r.var_uint());
                    }
                    if (!(info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                        // parent info
                        if (r.var_uint() == 1) {
                            r.skip_var_string();  // root key
                        } else {
                            r.var_uint();  // parent id client
                            r.var_uint();  // parent id clock
                        }
                        if (info & BIT_PARENT_SUB) r.skip_var_string();
                    }
                    switch (ref) {
                        case 1: {  // ContentDeleted
                            length = static_cast<int64_t>(r.var_uint());
                            kind = 1;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 4: {  // ContentString
                            auto [p, n] = r.var_string();
                            length = utf8_to_utf16_len(p, n);
                            kind = 0;
                            payload = PyUnicode_DecodeUTF8(p, n, "replace");
                            break;
                        }
                        case 2: {  // ContentJSON
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_var_string();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 3:  // ContentBinary
                            r.skip_var_bytes();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 5:  // ContentEmbed
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 6:  // ContentFormat
                            r.skip_var_string();
                            r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        case 7: {  // ContentType
                            uint64_t type_ref = r.var_uint();
                            if (type_ref == 3 || type_ref == 5) r.skip_var_string();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 8: {  // ContentAny
                            uint64_t n = r.var_uint();
                            for (uint64_t i = 0; i < n; i++) r.skip_any();
                            length = static_cast<int64_t>(n);
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        }
                        case 9:  // ContentDoc
                            r.skip_var_string();
                            r.skip_any();
                            length = 1;
                            kind = 4;
                            payload = PyLong_FromLongLong(length);
                            break;
                        default:
                            throw std::runtime_error("unknown content ref");
                    }
                }
                if (!payload) throw std::runtime_error("payload alloc failed");
                PyObject* tup = Py_BuildValue(
                    "(LLLLLLLN)", client, clock, kind, origin_client, origin_clock,
                    right_client, right_clock, payload);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(structs, tup);
                Py_DECREF(tup);
                clock += length;
            }
        }
        // delete set
        uint64_t ds_clients = r.var_uint();
        for (uint64_t i = 0; i < ds_clients; i++) {
            int64_t client = static_cast<int64_t>(r.var_uint());
            uint64_t ranges = r.var_uint();
            for (uint64_t j = 0; j < ranges; j++) {
                int64_t clock = static_cast<int64_t>(r.var_uint());
                int64_t dlen = static_cast<int64_t>(r.var_uint());
                PyObject* tup = Py_BuildValue("(LLL)", client, clock, dlen);
                if (!tup) throw std::runtime_error("tuple alloc failed");
                PyList_Append(deletes, tup);
                Py_DECREF(tup);
            }
        }
    } catch (const std::exception& e) {
        PyBuffer_Release(&view);
        Py_DECREF(structs);
        Py_DECREF(deletes);
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }

    PyBuffer_Release(&view);
    return Py_BuildValue("(NN)", structs, deletes);
}

PyObject* utf16_len(PyObject* /*self*/, PyObject* arg) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(arg, &n);
    if (!s) return nullptr;
    return PyLong_FromSsize_t(utf8_to_utf16_len(s, n));
}

// lib0 writeVarUint: 7-bit groups, little-endian, continuation bit 0x80
void put_var_uint(std::string& out, uint64_t num) {
    while (num > 0x7F) {
        out.push_back(static_cast<char>(0x80 | (num & 0x7F)));
        num >>= 7;
    }
    out.push_back(static_cast<char>(num));
}

void put_var_string(std::string& out, const char* s, Py_ssize_t n) {
    put_var_uint(out, static_cast<uint64_t>(n));
    out.append(s, static_cast<size_t>(n));
}

constexpr uint64_t MSG_SYNC = 0;
constexpr uint64_t MSG_SYNC_REPLY = 4;
constexpr uint64_t MSG_SYNC_STATUS = 8;
constexpr uint64_t MSG_YJS_UPDATE = 2;

PyObject* parse_frame_header(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    Reader r{static_cast<const uint8_t*>(view.buf), view.len};
    PyObject* result = nullptr;
    try {
        auto [p, n] = r.var_string();
        uint64_t msg_type = r.var_uint();
        // strict decode like the Python Decoder.read_var_string: both
        // paths must reject an invalid-UTF-8 name the same way
        PyObject* name = PyUnicode_DecodeUTF8(p, n, nullptr);
        if (!name) {
            PyErr_Clear();
            throw std::runtime_error("invalid utf-8 in document name");
        }
        result = Py_BuildValue("(NKn)", name, msg_type, r.pos);
    } catch (const std::exception& e) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }
    PyBuffer_Release(&view);
    return result;
}

PyObject* build_update_frame(PyObject* /*self*/, PyObject* args) {
    const char* name;
    Py_ssize_t name_len;
    Py_buffer update;
    int reply = 0;
    if (!PyArg_ParseTuple(args, "s#y*|p", &name, &name_len, &update, &reply))
        return nullptr;
    std::string out;
    out.reserve(static_cast<size_t>(name_len + update.len) + 12);
    put_var_string(out, name, name_len);
    put_var_uint(out, reply ? MSG_SYNC_REPLY : MSG_SYNC);
    put_var_uint(out, MSG_YJS_UPDATE);
    put_var_uint(out, static_cast<uint64_t>(update.len));
    out.append(static_cast<const char*>(update.buf),
               static_cast<size_t>(update.len));
    PyBuffer_Release(&update);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

PyObject* build_sync_status_frame(PyObject* /*self*/, PyObject* args) {
    const char* name;
    Py_ssize_t name_len;
    int ok = 0;
    if (!PyArg_ParseTuple(args, "s#p", &name, &name_len, &ok)) return nullptr;
    std::string out;
    out.reserve(static_cast<size_t>(name_len) + 8);
    put_var_string(out, name, name_len);
    put_var_uint(out, MSG_SYNC_STATUS);
    put_var_uint(out, ok ? 1 : 0);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

// Serve-path struct-section encoder (the write mirror of decode_update,
// restricted to the shapes the TPU plane serves hot: string runs and GC
// ranges). Python keeps the semantic work — cutoff trimming, first-item
// offset/origin rewrite, group ordering — and hands fully-resolved
// groups here for pure byte emission. Replaces ~15 Python-level calls
// per item in `crdt/update.py:_write_structs` / `crdt/structs.py
// Item.write` on broadcast/sync serves (reference hot path:
// `packages/server/src/MessageReceiver.ts:137-213` encode side).
//
//   encode_text_window(groups) -> bytes
//     groups: list of (client, write_clock, items), caller-ordered
//     item: (kind, origin_client, origin_clock, right_client,
//            right_clock, parent_name|None, payload)
//       kind 0: string run — payload str; negative origin client means
//               absent; when both origins absent parent_name (a root
//               type name) is written
//       kind 1: GC range — payload int length
//       kind 2: deleted run (ContentDeleted) — payload int length;
//               origins/parent rules as kind 0
constexpr uint8_t CONTENT_STRING_REF = 4;
constexpr uint8_t CONTENT_DELETED_REF = 1;
constexpr uint8_t STRUCT_GC_REF = 0;

PyObject* encode_text_window(PyObject* /*self*/, PyObject* arg) {
    PyObject* groups = PySequence_Fast(arg, "groups must be a sequence");
    if (!groups) return nullptr;
    std::string out;
    out.reserve(256);
    Py_ssize_t num_groups = PySequence_Fast_GET_SIZE(groups);
    put_var_uint(out, static_cast<uint64_t>(num_groups));
    for (Py_ssize_t g = 0; g < num_groups; ++g) {
        PyObject* group = PySequence_Fast_GET_ITEM(groups, g);
        unsigned long long client, write_clock;
        PyObject* items_obj;
        if (!PyArg_ParseTuple(group, "KKO", &client, &write_clock, &items_obj)) {
            Py_DECREF(groups);
            return nullptr;
        }
        PyObject* items = PySequence_Fast(items_obj, "items must be a sequence");
        if (!items) {
            Py_DECREF(groups);
            return nullptr;
        }
        Py_ssize_t num_items = PySequence_Fast_GET_SIZE(items);
        put_var_uint(out, static_cast<uint64_t>(num_items));
        put_var_uint(out, client);
        put_var_uint(out, write_clock);
        for (Py_ssize_t i = 0; i < num_items; ++i) {
            PyObject* item = PySequence_Fast_GET_ITEM(items, i);
            int kind;
            long long oc, ok, rc, rk;
            PyObject* parent_name;
            PyObject* payload;
            if (!PyArg_ParseTuple(item, "iLLLLOO", &kind, &oc, &ok,
                                  &rc, &rk, &parent_name, &payload)) {
                Py_DECREF(items);
                Py_DECREF(groups);
                return nullptr;
            }
            if (kind == 1) {  // GC range
                out.push_back(static_cast<char>(STRUCT_GC_REF));
                unsigned long long len = PyLong_AsUnsignedLongLong(payload);
                if (PyErr_Occurred()) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, len);
                continue;
            }
            uint8_t info =
                (kind == 2) ? CONTENT_DELETED_REF : CONTENT_STRING_REF;
            if (oc >= 0) info |= BIT_ORIGIN;
            if (rc >= 0) info |= BIT_RIGHT_ORIGIN;
            out.push_back(static_cast<char>(info));
            if (oc >= 0) {
                put_var_uint(out, static_cast<uint64_t>(oc));
                put_var_uint(out, static_cast<uint64_t>(ok));
            }
            if (rc >= 0) {
                put_var_uint(out, static_cast<uint64_t>(rc));
                put_var_uint(out, static_cast<uint64_t>(rk));
            }
            if (oc < 0 && rc < 0) {
                // origin-less: wire parent is a root type name
                Py_ssize_t n;
                const char* s = PyUnicode_AsUTF8AndSize(parent_name, &n);
                if (!s) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, 1);
                put_var_string(out, s, n);
            }
            if (kind == 2) {  // deleted run: just its length
                unsigned long long len = PyLong_AsUnsignedLongLong(payload);
                if (PyErr_Occurred()) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_uint(out, len);
            } else {
                Py_ssize_t n;
                const char* s = PyUnicode_AsUTF8AndSize(payload, &n);
                if (!s) {
                    Py_DECREF(items);
                    Py_DECREF(groups);
                    return nullptr;
                }
                put_var_string(out, s, n);
            }
        }
        Py_DECREF(items);
    }
    Py_DECREF(groups);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

// ---------------------------------------------------------------------------
// Batched wire path (PR 20). Everything below runs its pure-byte passes
// with the GIL released; Python objects are only touched in the collect /
// materialize phases at the edges of each call.
// ---------------------------------------------------------------------------

// Bump when a symbol is added or a signature changes: native/__init__.py
// compares this against its stamp file and rebuilds a stale .so once.
constexpr long NATIVE_API_VERSION = 2;

// CPython-strict UTF-8 validity (rejects overlongs, surrogates, >U+10FFFF).
// Used to prove a byte span can be copied verbatim: Python's merge path
// round-trips strings through strict decode/encode, which either raises
// (invalid) or reproduces the exact bytes (valid + canonical varints).
bool utf8_valid_strict(const uint8_t* s, Py_ssize_t n) {
    Py_ssize_t i = 0;
    while (i < n) {
        uint8_t c = s[i];
        if (c < 0x80) { i += 1; continue; }
        if (c < 0xC2) return false;  // continuation or overlong lead
        if (c < 0xE0) {
            if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
            i += 2; continue;
        }
        if (c < 0xF0) {
            if (i + 2 >= n) return false;
            uint8_t c1 = s[i + 1], c2 = s[i + 2];
            if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80) return false;
            if (c == 0xE0 && c1 < 0xA0) return false;   // overlong
            if (c == 0xED && c1 >= 0xA0) return false;  // surrogate
            i += 3; continue;
        }
        if (c < 0xF5) {
            if (i + 3 >= n) return false;
            uint8_t c1 = s[i + 1], c2 = s[i + 2], c3 = s[i + 3];
            if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80 ||
                (c3 & 0xC0) != 0x80) return false;
            if (c == 0xF0 && c1 < 0x90) return false;   // overlong
            if (c == 0xF4 && c1 >= 0x90) return false;  // > U+10FFFF
            i += 4; continue;
        }
        return false;
    }
    return true;
}

// One struct's byte span inside a source update, plus the clock geometry
// the merge planner needs. `src` indexes the input update buffer.
struct SpanRec {
    Py_ssize_t start = 0;
    Py_ssize_t end = 0;
    uint64_t clock = 0;
    uint64_t length = 0;
    bool is_skip = false;
    int src = 0;
};

struct ClientSpans {
    uint64_t client = 0;
    std::vector<SpanRec> spans;
};

struct DeleteRange {
    uint64_t client = 0, clock = 0, length = 0;
};

// Walk one update's struct sections recording byte spans. Mirrors the
// cursor discipline of decode_update exactly. When `verbatim` is set it
// additionally proves every span re-encodes to itself under the Python
// merge (strict UTF-8 strings, canonical varints, content refs whose
// write mirror is byte-stable, no parent-sub-with-origins shapes) and
// throws std::runtime_error("not verbatim-safe") as soon as the proof
// fails — callers catch and fall back to the Python path.
void scan_update_spans(Reader& r, int src, bool verbatim,
                       std::vector<ClientSpans>& out,
                       std::vector<DeleteRange>& deletes) {
    auto bail = []() -> void {
        throw std::runtime_error("not verbatim-safe");
    };
    uint64_t num_clients = r.var_uint();
    for (uint64_t ci = 0; ci < num_clients; ci++) {
        uint64_t num_structs = r.var_uint();
        uint64_t client = r.var_uint();
        uint64_t clock = r.var_uint();
        ClientSpans* cs = nullptr;
        for (auto& existing : out) {
            if (existing.client == client) { cs = &existing; break; }
        }
        if (!cs) {
            out.push_back(ClientSpans{client, {}});
            cs = &out.back();
        }
        for (uint64_t si = 0; si < num_structs; si++) {
            SpanRec rec;
            rec.src = src;
            rec.start = r.pos;
            rec.clock = clock;
            uint8_t info = r.u8();
            uint8_t ref = info & 0x1F;
            if (ref == 0 || ref == 10) {  // GC / Skip
                rec.length = r.var_uint();
                rec.is_skip = (ref == 10);
                // read_struct ignores high info bits on GC/Skip but the
                // write mirror emits a bare ref byte — a decorated info
                // byte would not round-trip verbatim
                if (verbatim && info != ref) bail();
            } else {
                if (verbatim && (info & BIT_PARENT_SUB) &&
                    (info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                    // Item.write re-derives parent_sub presence from the
                    // parent field, which is only populated when both
                    // origins are absent — this shape does not round-trip
                    bail();
                }
                if (info & BIT_ORIGIN) { r.var_uint(); r.var_uint(); }
                if (info & BIT_RIGHT_ORIGIN) { r.var_uint(); r.var_uint(); }
                if (!(info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                    if (r.var_uint() == 1) {
                        auto [p, n] = r.var_string();
                        if (verbatim &&
                            !utf8_valid_strict(
                                reinterpret_cast<const uint8_t*>(p), n))
                            bail();
                    } else {
                        r.var_uint();
                        r.var_uint();
                    }
                    if (info & BIT_PARENT_SUB) {
                        auto [p, n] = r.var_string();
                        if (verbatim &&
                            !utf8_valid_strict(
                                reinterpret_cast<const uint8_t*>(p), n))
                            bail();
                    }
                }
                switch (ref) {
                    case 1:  // ContentDeleted
                        rec.length = r.var_uint();
                        break;
                    case 4: {  // ContentString
                        auto [p, n] = r.var_string();
                        if (verbatim &&
                            !utf8_valid_strict(
                                reinterpret_cast<const uint8_t*>(p), n))
                            bail();
                        rec.length = static_cast<uint64_t>(
                            utf8_to_utf16_len(p, n));
                        break;
                    }
                    case 2: {  // ContentJSON — json round-trip not stable
                        if (verbatim) bail();
                        uint64_t n = r.var_uint();
                        for (uint64_t i = 0; i < n; i++) r.skip_var_string();
                        rec.length = n;
                        break;
                    }
                    case 3:  // ContentBinary — bytes round-trip verbatim
                        r.skip_var_bytes();
                        rec.length = 1;
                        break;
                    case 5:  // ContentEmbed
                        if (verbatim) bail();
                        r.skip_var_string();
                        rec.length = 1;
                        break;
                    case 6:  // ContentFormat
                        if (verbatim) bail();
                        r.skip_var_string();
                        r.skip_var_string();
                        rec.length = 1;
                        break;
                    case 7: {  // ContentType
                        if (verbatim) bail();
                        uint64_t type_ref = r.var_uint();
                        if (type_ref == 3 || type_ref == 5)
                            r.skip_var_string();
                        rec.length = 1;
                        break;
                    }
                    case 8: {  // ContentAny
                        if (verbatim) bail();
                        uint64_t n = r.var_uint();
                        for (uint64_t i = 0; i < n; i++) r.skip_any();
                        rec.length = n;
                        break;
                    }
                    case 9:  // ContentDoc
                        if (verbatim) bail();
                        r.skip_var_string();
                        r.skip_any();
                        rec.length = 1;
                        break;
                    default:
                        throw std::runtime_error("unknown content ref");
                }
                if (verbatim && rec.length == 0) bail();  // degenerate run
            }
            rec.end = r.pos;
            clock += rec.length;
            cs->spans.push_back(rec);
        }
    }
    uint64_t ds_clients = r.var_uint();
    for (uint64_t i = 0; i < ds_clients; i++) {
        uint64_t client = r.var_uint();
        uint64_t ranges = r.var_uint();
        for (uint64_t j = 0; j < ranges; j++) {
            uint64_t dclock = r.var_uint();
            uint64_t dlen = r.var_uint();
            deletes.push_back(DeleteRange{client, dclock, dlen});
        }
    }
    if (r.pos != r.len) throw std::runtime_error("trailing bytes in update");
    if (verbatim && r.noncanonical) bail();
}

// coalesce_updates(updates) -> merged bytes, or None to signal "fall back
// to the Python merge". Byte-identical to crdt/update.py merge_updates for
// every input it accepts; bails (None) whenever identity is not provable.
PyObject* coalesce_updates_native(PyObject* /*self*/, PyObject* arg) {
    PyObject* seq = PySequence_Fast(arg, "updates must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (n == 0) {
        Py_DECREF(seq);
        return PyBytes_FromStringAndSize("\x00\x00", 2);
    }
    if (n == 1) {
        PyObject* only = PySequence_Fast_GET_ITEM(seq, 0);
        Py_INCREF(only);
        Py_DECREF(seq);
        return only;
    }
    std::vector<Py_buffer> views(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, i), &views[i],
                               PyBUF_SIMPLE) != 0) {
            PyErr_Clear();
            for (Py_ssize_t j = 0; j < i; j++) PyBuffer_Release(&views[j]);
            Py_DECREF(seq);
            Py_RETURN_NONE;  // non-buffer input: let Python decide
        }
    }

    bool failed = false;
    std::string out;
    Py_BEGIN_ALLOW_THREADS
    try {
        std::vector<ClientSpans> clients;
        std::vector<DeleteRange> deletes;
        for (Py_ssize_t i = 0; i < n; i++) {
            Reader r{static_cast<const uint8_t*>(views[i].buf), views[i].len};
            scan_update_spans(r, static_cast<int>(i), /*verbatim=*/true,
                              clients, deletes);
        }
        // Per client: stable sort by clock (mirrors Python's
        // sort(key=clock) on the concatenated per-update span lists),
        // then plan the emission — verbatim span copies with synthetic
        // Skips bridging gaps, duplicates dropped, overlaps (which the
        // Python path resolves with an offset re-encode) rejected.
        struct EmitEntry {
            bool synth_skip;
            uint64_t clock;
            uint64_t skip_len;
            const SpanRec* span;
        };
        struct ClientPlan {
            uint64_t client;
            std::vector<EmitEntry> entries;
        };
        std::vector<ClientPlan> plans;
        for (auto& cs : clients) {
            std::stable_sort(cs.spans.begin(), cs.spans.end(),
                             [](const SpanRec& a, const SpanRec& b) {
                                 return a.clock < b.clock;
                             });
            ClientPlan plan{cs.client, {}};
            uint64_t cur = cs.spans.front().clock;
            for (const auto& s : cs.spans) {
                if (s.is_skip) continue;
                uint64_t end = s.clock + s.length;
                if (end <= cur) continue;  // fully covered duplicate
                if (s.clock > cur) {
                    plan.entries.push_back(
                        EmitEntry{true, cur, s.clock - cur, nullptr});
                    cur = s.clock;
                }
                if (s.clock < cur)  // partial overlap: needs offset split
                    throw std::runtime_error("overlapping struct runs");
                plan.entries.push_back(EmitEntry{false, s.clock, 0, &s});
                cur = end;
            }
            // Python pops trailing Skips (all synthetic at this point)
            while (!plan.entries.empty() && plan.entries.back().synth_skip)
                plan.entries.pop_back();
            if (!plan.entries.empty()) plans.push_back(std::move(plan));
        }
        std::sort(plans.begin(), plans.end(),
                  [](const ClientPlan& a, const ClientPlan& b) {
                      return a.client > b.client;  // DESC like Python
                  });
        out.reserve(256);
        put_var_uint(out, static_cast<uint64_t>(plans.size()));
        for (const auto& plan : plans) {
            put_var_uint(out, static_cast<uint64_t>(plan.entries.size()));
            put_var_uint(out, plan.client);
            put_var_uint(out, plan.entries.front().clock);
            for (const auto& e : plan.entries) {
                if (e.synth_skip) {
                    out.push_back(static_cast<char>(10));  // Skip info byte
                    put_var_uint(out, e.skip_len);
                } else {
                    const Py_buffer& v = views[e.span->src];
                    out.append(
                        static_cast<const char*>(v.buf) + e.span->start,
                        static_cast<size_t>(e.span->end - e.span->start));
                }
            }
        }
        // Merged delete set: union ranges per client, sort, coalesce —
        // mirrors delete_set.py merge_delete_sets + sort_and_merge.
        std::unordered_map<uint64_t,
                           std::vector<std::pair<uint64_t, uint64_t>>> ds;
        for (const auto& d : deletes)
            ds[d.client].emplace_back(d.clock, d.length);
        std::vector<uint64_t> ds_clients;
        ds_clients.reserve(ds.size());
        for (auto& kv : ds) ds_clients.push_back(kv.first);
        std::sort(ds_clients.begin(), ds_clients.end(),
                  std::greater<uint64_t>());
        put_var_uint(out, static_cast<uint64_t>(ds_clients.size()));
        for (uint64_t client : ds_clients) {
            auto& ranges = ds[client];
            std::sort(ranges.begin(), ranges.end());
            std::vector<std::pair<uint64_t, uint64_t>> merged;
            for (const auto& [clock, length] : ranges) {
                if (!merged.empty() &&
                    merged.back().first + merged.back().second >= clock) {
                    auto& prev = merged.back();
                    prev.second =
                        std::max(prev.second, clock + length - prev.first);
                } else {
                    merged.emplace_back(clock, length);
                }
            }
            put_var_uint(out, client);
            put_var_uint(out, static_cast<uint64_t>(merged.size()));
            for (const auto& [clock, length] : merged) {
                put_var_uint(out, clock);
                put_var_uint(out, length);
            }
        }
    } catch (...) {
        failed = true;
    }
    Py_END_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) PyBuffer_Release(&views[i]);
    Py_DECREF(seq);
    if (failed) Py_RETURN_NONE;
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

// scan_update_frontier(update) -> ([(client, end_clock), ...], ds_empty)
// end_clock is the highest clock+length over the update's non-Skip structs
// per client; ds_empty is True when the delete set carries no ranges.
// Powers the idempotent-redelivery fast-drop: if every (client, end) is
// <= the local StructStore state and the delete set is empty, applying
// the update is a no-op and the Python decoder can be skipped entirely.
PyObject* scan_update_frontier(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    bool failed = false;
    bool has_deletes = false;
    std::vector<std::pair<uint64_t, uint64_t>> frontier;
    Py_BEGIN_ALLOW_THREADS
    try {
        std::vector<ClientSpans> clients;
        std::vector<DeleteRange> deletes;
        Reader r{static_cast<const uint8_t*>(view.buf), view.len};
        scan_update_spans(r, 0, /*verbatim=*/false, clients, deletes);
        has_deletes = !deletes.empty();
        for (const auto& cs : clients) {
            uint64_t end = 0;
            bool any = false;
            for (const auto& s : cs.spans) {
                if (s.is_skip) continue;
                any = true;
                end = std::max(end, s.clock + s.length);
            }
            if (any) frontier.emplace_back(cs.client, end);
        }
    } catch (...) {
        failed = true;
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&view);
    if (failed) {
        PyErr_SetString(PyExc_ValueError, "malformed update");
        return nullptr;
    }
    PyObject* list = PyList_New(static_cast<Py_ssize_t>(frontier.size()));
    if (!list) return nullptr;
    for (size_t i = 0; i < frontier.size(); i++) {
        PyObject* tup = Py_BuildValue("(KK)", frontier[i].first,
                                      frontier[i].second);
        if (!tup) {
            Py_DECREF(list);
            return nullptr;
        }
        PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i), tup);
    }
    return Py_BuildValue("(NO)", list, has_deletes ? Py_False : Py_True);
}

// parse_frame_headers_batch(frames, skip_malformed=False)
//   -> list[(name, type, offset) | None]
// One call per drain batch. The byte scan runs without the GIL; document
// names are materialized afterwards with run-length dedup (consecutive
// frames for the same doc share ONE str object — the common case for an
// inbox drain). skip_malformed=True yields None slots instead of raising
// (replication inboxes drop bad frames; client paths keep strict parity).
PyObject* parse_frame_headers_batch(PyObject* /*self*/, PyObject* args) {
    PyObject* frames_obj;
    int skip_malformed = 0;
    if (!PyArg_ParseTuple(args, "O|p", &frames_obj, &skip_malformed))
        return nullptr;
    PyObject* seq = PySequence_Fast(frames_obj, "frames must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    std::vector<Py_buffer> views(static_cast<size_t>(n));
    std::vector<char> have(static_cast<size_t>(n), 0);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, i), &views[i],
                               PyBUF_SIMPLE) == 0) {
            have[i] = 1;
        } else if (skip_malformed) {
            PyErr_Clear();
        } else {
            for (Py_ssize_t j = 0; j < i; j++)
                if (have[j]) PyBuffer_Release(&views[j]);
            Py_DECREF(seq);
            return nullptr;
        }
    }
    struct Hdr {
        Py_ssize_t name_off = 0, name_len = 0;
        uint64_t type = 0;
        Py_ssize_t payload_off = 0;
        bool ok = false;
    };
    std::vector<Hdr> hdrs(static_cast<size_t>(n));
    Py_ssize_t first_bad = -1;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!have[i]) {
            if (first_bad < 0) first_bad = i;
            continue;
        }
        Reader r{static_cast<const uint8_t*>(views[i].buf), views[i].len};
        try {
            Py_ssize_t nl = r.checked_len(r.var_uint());
            hdrs[i].name_off = r.pos;
            hdrs[i].name_len = nl;
            r.skip(nl);
            hdrs[i].type = r.var_uint();
            hdrs[i].payload_off = r.pos;
            hdrs[i].ok = true;
        } catch (...) {
            if (first_bad < 0) first_bad = i;
        }
    }
    Py_END_ALLOW_THREADS

    PyObject* result = nullptr;
    PyObject* prev_name = nullptr;
    const char* prev_ptr = nullptr;
    Py_ssize_t prev_len = -1;
    if (!skip_malformed && first_bad >= 0) {
        PyErr_Format(PyExc_ValueError, "malformed frame header at index %zd",
                     first_bad);
        goto cleanup;
    }
    result = PyList_New(n);
    if (!result) goto cleanup;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!hdrs[i].ok) {
            Py_INCREF(Py_None);
            PyList_SET_ITEM(result, i, Py_None);
            continue;
        }
        const char* p =
            static_cast<const char*>(views[i].buf) + hdrs[i].name_off;
        Py_ssize_t nl = hdrs[i].name_len;
        PyObject* name;
        if (prev_name && nl == prev_len && std::memcmp(p, prev_ptr, nl) == 0) {
            name = prev_name;
            Py_INCREF(name);
        } else {
            name = PyUnicode_DecodeUTF8(p, nl, nullptr);
            if (!name) {
                PyErr_Clear();
                if (skip_malformed) {
                    Py_INCREF(Py_None);
                    PyList_SET_ITEM(result, i, Py_None);
                    continue;
                }
                Py_DECREF(result);
                result = nullptr;
                PyErr_SetString(PyExc_ValueError,
                                "invalid utf-8 in document name");
                goto cleanup;
            }
            Py_XDECREF(prev_name);
            prev_name = name;
            Py_INCREF(prev_name);
            prev_ptr = p;
            prev_len = nl;
        }
        PyObject* tup = Py_BuildValue("(NKn)", name, hdrs[i].type,
                                      hdrs[i].payload_off);
        if (!tup) {
            Py_DECREF(result);
            result = nullptr;
            goto cleanup;
        }
        PyList_SET_ITEM(result, i, tup);
    }
cleanup:
    Py_XDECREF(prev_name);
    for (Py_ssize_t i = 0; i < n; i++)
        if (have[i]) PyBuffer_Release(&views[i]);
    Py_DECREF(seq);
    return result;
}

// build_update_frames_batch(items) -> list[bytes]
//   items: (name, update) or (name, update, reply) tuples.
// All frames are laid out in one arena with the GIL released, then cut
// into per-frame bytes objects (each recipient list owns its frame).
PyObject* build_update_frames_batch(PyObject* /*self*/, PyObject* arg) {
    PyObject* seq = PySequence_Fast(arg, "items must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    struct Item {
        const char* name;
        Py_ssize_t name_len;
        Py_buffer update;
        int reply;
    };
    std::vector<Item> items(static_cast<size_t>(n));
    Py_ssize_t acquired = 0;
    for (; acquired < n; acquired++) {
        PyObject* it = PySequence_Fast_GET_ITEM(seq, acquired);
        Item& slot = items[acquired];
        slot.reply = 0;
        PyObject* reply_obj = nullptr;
        PyObject* name_obj;
        PyObject* update_obj;
        if (!PyArg_ParseTuple(it, "UO|O", &name_obj, &update_obj,
                              &reply_obj))
            break;
        slot.name = PyUnicode_AsUTF8AndSize(name_obj, &slot.name_len);
        if (!slot.name) break;
        if (reply_obj) {
            slot.reply = PyObject_IsTrue(reply_obj);
            if (slot.reply < 0) break;
        }
        if (PyObject_GetBuffer(update_obj, &slot.update, PyBUF_SIMPLE) != 0)
            break;
    }
    if (acquired < n) {
        for (Py_ssize_t j = 0; j < acquired; j++)
            PyBuffer_Release(&items[j].update);
        Py_DECREF(seq);
        return nullptr;
    }
    std::string arena;
    std::vector<std::pair<size_t, size_t>> cuts(static_cast<size_t>(n));
    Py_BEGIN_ALLOW_THREADS
    {
        size_t total = 0;
        for (const auto& it : items)
            total += static_cast<size_t>(it.name_len + it.update.len) + 12;
        arena.reserve(total);
        for (Py_ssize_t i = 0; i < n; i++) {
            const Item& it = items[i];
            size_t start = arena.size();
            put_var_string(arena, it.name, it.name_len);
            put_var_uint(arena, it.reply ? MSG_SYNC_REPLY : MSG_SYNC);
            put_var_uint(arena, MSG_YJS_UPDATE);
            put_var_uint(arena, static_cast<uint64_t>(it.update.len));
            arena.append(static_cast<const char*>(it.update.buf),
                         static_cast<size_t>(it.update.len));
            cuts[i] = {start, arena.size() - start};
        }
    }
    Py_END_ALLOW_THREADS
    for (Py_ssize_t j = 0; j < n; j++) PyBuffer_Release(&items[j].update);
    Py_DECREF(seq);
    PyObject* result = PyList_New(n);
    if (!result) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* frame = PyBytes_FromStringAndSize(
            arena.data() + cuts[i].first,
            static_cast<Py_ssize_t>(cuts[i].second));
        if (!frame) {
            Py_DECREF(result);
            return nullptr;
        }
        PyList_SET_ITEM(result, i, frame);
    }
    return result;
}

// Relay envelope [varUint kind][varString session][varString aux]
// [varUint8Array payload] — mirrors edge/relay.py decode_envelope.
// `prev_session`/`prev_bytes` form a one-slot dedup window: consecutive
// envelopes for the same session reuse ONE str object (prev_bytes owns a
// copy of the session bytes so the window survives buffer release).
PyObject* parse_one_envelope(Py_buffer* view, PyObject** prev_session,
                             std::string* prev_bytes) {
    Reader r{static_cast<const uint8_t*>(view->buf), view->len};
    uint64_t kind;
    const char *sp, *ap, *pp;
    Py_ssize_t sn, an, pn;
    try {
        kind = r.var_uint();
        std::tie(sp, sn) = r.var_string();
        std::tie(ap, an) = r.var_string();
        Py_ssize_t plen = r.checked_len(r.var_uint());
        pp = r.bytes(plen);
        pn = plen;
    } catch (const std::exception& e) {
        PyErr_SetString(PyExc_ValueError, e.what());
        return nullptr;
    }
    PyObject* session;
    if (*prev_session &&
        sn == static_cast<Py_ssize_t>(prev_bytes->size()) &&
        std::memcmp(sp, prev_bytes->data(), static_cast<size_t>(sn)) == 0) {
        session = *prev_session;
        Py_INCREF(session);
    } else {
        session = PyUnicode_DecodeUTF8(sp, sn, nullptr);
        if (!session) {
            PyErr_Clear();
            PyErr_SetString(PyExc_ValueError,
                            "invalid utf-8 in envelope session");
            return nullptr;
        }
        Py_XDECREF(*prev_session);
        *prev_session = session;
        Py_INCREF(session);
        prev_bytes->assign(sp, static_cast<size_t>(sn));
    }
    PyObject* aux = PyUnicode_DecodeUTF8(ap, an, nullptr);
    if (!aux) {
        PyErr_Clear();
        Py_DECREF(session);
        PyErr_SetString(PyExc_ValueError, "invalid utf-8 in envelope aux");
        return nullptr;
    }
    return Py_BuildValue("(KNNy#)", kind, session, aux, pp, pn);
}

PyObject* parse_envelope(PyObject* /*self*/, PyObject* arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
    PyObject* prev = nullptr;
    std::string prev_bytes;
    PyObject* result = parse_one_envelope(&view, &prev, &prev_bytes);
    Py_XDECREF(prev);
    PyBuffer_Release(&view);
    return result;
}

// parse_envelopes_batch(raws, skip_malformed=False)
//   -> list[(kind, session, aux, payload) | None]
// Consecutive envelopes for the same session share ONE str object.
PyObject* parse_envelopes_batch(PyObject* /*self*/, PyObject* args) {
    PyObject* raws_obj;
    int skip_malformed = 0;
    if (!PyArg_ParseTuple(args, "O|p", &raws_obj, &skip_malformed))
        return nullptr;
    PyObject* seq = PySequence_Fast(raws_obj, "raws must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* result = PyList_New(n);
    if (!result) {
        Py_DECREF(seq);
        return nullptr;
    }
    PyObject* prev = nullptr;
    std::string prev_bytes;
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_buffer view;
        PyObject* tup = nullptr;
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(seq, i), &view,
                               PyBUF_SIMPLE) == 0) {
            tup = parse_one_envelope(&view, &prev, &prev_bytes);
            PyBuffer_Release(&view);
        }
        if (!tup) {
            if (!skip_malformed) {
                Py_XDECREF(prev);
                Py_DECREF(result);
                Py_DECREF(seq);
                return nullptr;
            }
            PyErr_Clear();
            tup = Py_None;
            Py_INCREF(tup);
        }
        PyList_SET_ITEM(result, i, tup);
    }
    Py_XDECREF(prev);
    Py_DECREF(seq);
    return result;
}

// read_var_uints(data, pos, count) -> (tuple_of_ints, new_pos)
// Bulk varint reads for crdt/encoding.py hot loops (struct runs, state
// vectors, delete-set ranges) — one call instead of `count` Python reads.
PyObject* read_var_uints(PyObject* /*self*/, PyObject* args) {
    Py_buffer view;
    Py_ssize_t pos, count;
    if (!PyArg_ParseTuple(args, "y*nn", &view, &pos, &count)) return nullptr;
    if (pos < 0 || pos > view.len || count < 0) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "invalid position or count");
        return nullptr;
    }
    // every varint is >= 1 byte: an untrusted count prefix larger than
    // the remaining buffer must fail BEFORE the result allocation
    if (count > view.len - pos) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "unexpected end of buffer");
        return nullptr;
    }
    std::vector<uint64_t> vals(static_cast<size_t>(count));
    Reader r{static_cast<const uint8_t*>(view.buf), view.len, pos};
    bool failed = false;
    Py_BEGIN_ALLOW_THREADS
    try {
        for (Py_ssize_t i = 0; i < count; i++) vals[i] = r.var_uint();
    } catch (...) {
        failed = true;
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&view);
    if (failed) {
        PyErr_SetString(PyExc_ValueError, "unexpected end of buffer");
        return nullptr;
    }
    PyObject* tup = PyTuple_New(count);
    if (!tup) return nullptr;
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject* v = PyLong_FromUnsignedLongLong(vals[i]);
        if (!v) {
            Py_DECREF(tup);
            return nullptr;
        }
        PyTuple_SET_ITEM(tup, i, v);
    }
    return Py_BuildValue("(Nn)", tup, r.pos);
}

// encode_var_uints(seq) -> bytes — bulk lib0 varint writes.
PyObject* encode_var_uints(PyObject* /*self*/, PyObject* arg) {
    PyObject* seq = PySequence_Fast(arg, "values must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    std::string out;
    out.reserve(static_cast<size_t>(n) * 2);
    for (Py_ssize_t i = 0; i < n; i++) {
        unsigned long long v = PyLong_AsUnsignedLongLong(
            PySequence_Fast_GET_ITEM(seq, i));
        if (v == static_cast<unsigned long long>(-1) && PyErr_Occurred()) {
            Py_DECREF(seq);
            return nullptr;
        }
        put_var_uint(out, v);
    }
    Py_DECREF(seq);
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

PyMethodDef methods[] = {
    {"decode_update", decode_update, METH_O,
     "Decode a Yjs v1 update into (structs, deletes) tuples."},
    {"encode_text_window", encode_text_window, METH_O,
     "Encode resolved (string|GC) struct groups into update bytes."},
    {"utf16_len", utf16_len, METH_O, "UTF-16 code unit count of a string."},
    {"parse_frame_header", parse_frame_header, METH_O,
     "Parse [varString name][varUint type] -> (name, type, offset)."},
    {"build_update_frame", build_update_frame, METH_VARARGS,
     "Build [name][Sync|SyncReply][yjsUpdate][update] broadcast frame."},
    {"build_sync_status_frame", build_sync_status_frame, METH_VARARGS,
     "Build [name][SyncStatus][0|1] durability ack frame."},
    {"parse_frame_headers_batch", parse_frame_headers_batch, METH_VARARGS,
     "Parse N frame headers in one call -> list[(name, type, offset)]."},
    {"build_update_frames_batch", build_update_frames_batch, METH_O,
     "Build N broadcast frames from (name, update[, reply]) tuples."},
    {"coalesce_updates", coalesce_updates_native, METH_O,
     "Byte-level merge of N Yjs updates; None = fall back to Python."},
    {"scan_update_frontier", scan_update_frontier, METH_O,
     "Per-client clock frontier of an update -> (pairs, ds_empty)."},
    {"parse_envelope", parse_envelope, METH_O,
     "Decode one relay envelope -> (kind, session, aux, payload)."},
    {"parse_envelopes_batch", parse_envelopes_batch, METH_VARARGS,
     "Decode N relay envelopes in one call."},
    {"read_var_uints", read_var_uints, METH_VARARGS,
     "Bulk varint reads -> (tuple_of_ints, new_pos)."},
    {"encode_var_uints", encode_var_uints, METH_O,
     "Bulk varint writes -> bytes."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_codec",
    "Native Yjs v1 update codec (C++)", -1, methods,
};

}  // namespace

// text_lane.cpp — the native host path for plain-text documents
void register_text_lane(PyObject* module);

PyMODINIT_FUNC PyInit__codec(void) {
    PyObject* m = PyModule_Create(&module);
    if (m) {
        register_text_lane(m);
        PyModule_AddIntConstant(m, "NATIVE_API_VERSION", NATIVE_API_VERSION);
    }
    return m;
}
