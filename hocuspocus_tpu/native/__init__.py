"""Native (C++) codec with automatic build and pure-Python fallback.

`get_codec()` returns the compiled `_codec` module, building it with
g++ on first use if needed, or None when no toolchain is available —
callers fall back to the pure-Python decoder in hocuspocus_tpu.crdt.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "codec.cpp"), os.path.join(_DIR, "text_lane.cpp")]
_SO = os.path.join(_DIR, f"_codec{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}")

_codec = None
_build_attempted = False


def build(force: bool = False) -> bool:
    """Compile the C++ sources into an extension module. Returns success."""
    if (
        not force
        and os.path.exists(_SO)
        and all(os.path.getmtime(_SO) >= os.path.getmtime(src) for src in _SRCS)
    ):
        return True
    include = sysconfig.get_paths()["include"]
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        f"-I{include}",
        *_SRCS,
        "-o",
        _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_codec():
    """The compiled codec module, or None if unavailable."""
    global _codec, _build_attempted
    if _codec is not None:
        return _codec
    if os.environ.get("HOCUSPOCUS_TPU_NO_NATIVE"):
        return None
    if not _build_attempted:
        # build() no-ops when the .so is newer than every source; a
        # stale .so (new source file added) must be rebuilt or the
        # module silently misses the new API
        _build_attempted = True
        build()
    if os.path.exists(_SO):
        try:
            if _DIR not in sys.path:
                sys.path.insert(0, _DIR)
            import _codec as codec_module  # type: ignore[import-not-found]

            _codec = codec_module
        except Exception:
            _codec = None
    return _codec
