"""Native (C++) codec with automatic build and pure-Python fallback.

`get_codec()` returns the compiled `_codec` module, building it with
g++ on first use if needed, or None when no toolchain is available —
callers fall back to the pure-Python decoder in hocuspocus_tpu.crdt.

Falling back is ALWAYS safe (byte-identical results) but never silent:
the first resolution emits one structured warning carrying the tail of
the compiler error, sets the `hocuspocus_native_codec_info` gauge
(status=native|fallback, rendered on /metrics once the Metrics
extension adopts it), and records a `__plane__` flight event so the
fallback shows up on /debug/docs/__plane__ and the fleet view next to
the other plane-level degradations.

Stale-.so hazard: mtime comparison alone cannot tell an .so compiled
from yesterday's sources apart from today's when a checkout rewrites
timestamps, and a batch API added to codec.cpp would then be silently
missing at runtime. A version stamp written at build time is compared
against EXPECTED_API_VERSION *before* the first import (an extension
module already imported in-process cannot be reliably reloaded —
CPython caches single-phase-init modules), forcing a rebuild while a
clean import is still possible.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import sysconfig
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "codec.cpp"), os.path.join(_DIR, "text_lane.cpp")]
_SO = os.path.join(_DIR, f"_codec{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}")
_STAMP = os.path.join(_DIR, "_codec.apiver")

# Bump IN LOCKSTEP with NATIVE_API_VERSION in codec.cpp whenever the
# module's API surface grows: the stamp check below rebuilds a stale
# .so before the first import can cache it.
EXPECTED_API_VERSION = 2

_logger = logging.getLogger("hocuspocus_tpu")

_codec = None
_build_attempted = False
_resolved = False
_last_build_error: Optional[str] = None
_status: Optional[str] = None  # "native" | "fallback" once resolved
_info_gauge = None


def _get_info_gauge():
    global _info_gauge
    if _info_gauge is None:
        from ..observability.metrics import Gauge

        _info_gauge = Gauge(
            "hocuspocus_native_codec_info",
            "Native codec availability: 1 on the active status series "
            "(status=native|fallback)",
        )
    return _info_gauge


def codec_info_metrics() -> list:
    """The process-global status gauge, for registry adoption (the
    Metrics extension calls this like the other global collectors)."""
    return [_get_info_gauge()]


def codec_status() -> "tuple[Optional[str], Optional[str]]":
    """(status, reason) — status is None until the first get_codec()
    resolves; reason carries the compiler error tail on fallback."""
    return _status, _last_build_error


def _note_status(status: str, reason: Optional[str]) -> None:
    """First-resolution bookkeeping: gauge, flight event, and (on
    fallback) ONE structured warning — never one per call site."""
    global _status
    if _status == status:
        return
    _status = status
    try:
        gauge = _get_info_gauge()
        gauge.set(1.0 if status == "native" else 0.0, status="native")
        gauge.set(1.0 if status == "fallback" else 0.0, status="fallback")
    except Exception:
        pass
    try:
        from ..observability.flight_recorder import get_flight_recorder

        attrs = {"status": status}
        if reason:
            attrs["reason"] = reason[:200]
        get_flight_recorder().record("__plane__", "native_codec", **attrs)
    except Exception:
        pass
    if status == "fallback":
        _logger.warning(
            "[native] codec unavailable, using the pure-Python fallback "
            "(byte-identical, slower). reason: %s",
            reason or "unknown",
        )


def _read_stamp() -> Optional[int]:
    try:
        with open(_STAMP, "r", encoding="ascii") as fh:
            return int(fh.read().strip())
    except Exception:
        return None


def _write_stamp() -> None:
    try:
        with open(_STAMP, "w", encoding="ascii") as fh:
            fh.write(str(EXPECTED_API_VERSION))
    except Exception:
        pass


def build(force: bool = False) -> bool:
    """Compile the C++ sources into an extension module. Returns success."""
    global _last_build_error
    if (
        not force
        and os.path.exists(_SO)
        and all(os.path.getmtime(_SO) >= os.path.getmtime(src) for src in _SRCS)
    ):
        return True
    include = sysconfig.get_paths()["include"]
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        f"-I{include}",
        *_SRCS,
        "-o",
        _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        _write_stamp()
        return True
    except subprocess.CalledProcessError as exc:
        stderr = exc.stderr or b""
        tail = stderr.decode("utf-8", "replace").strip()[-400:]
        _last_build_error = f"compiler failed: ...{tail}" if tail else "compiler failed"
        return False
    except FileNotFoundError:
        _last_build_error = f"no C++ toolchain ({cmd[0]} not found)"
        return False
    except Exception as exc:
        _last_build_error = f"build error: {exc!r}"
        return False


def _import_codec():
    """Import (or re-import) the extension module; None on failure."""
    try:
        if _DIR not in sys.path:
            sys.path.insert(0, _DIR)
        sys.modules.pop("_codec", None)
        import _codec as codec_module  # type: ignore[import-not-found]

        return codec_module
    except Exception:
        return None


def get_codec():
    """The compiled codec module, or None if unavailable."""
    global _codec, _build_attempted, _resolved, _last_build_error
    if os.environ.get("HOCUSPOCUS_TPU_NO_NATIVE"):
        if _status is None:
            _note_status("fallback", "disabled by HOCUSPOCUS_TPU_NO_NATIVE")
        return None
    if _resolved:
        # hot path: one env read + one flag check per call — a broken
        # .so must not cost an import attempt per frame
        return _codec
    if not _build_attempted:
        _build_attempted = True
        if os.path.exists(_SO) and _read_stamp() != EXPECTED_API_VERSION:
            # the .so predates the current API surface (or has no
            # stamp): rebuild BEFORE the first import caches it
            build(force=True)
        else:
            build()
    if os.path.exists(_SO):
        module = _import_codec()
        if module is not None and (
            getattr(module, "NATIVE_API_VERSION", 0) < EXPECTED_API_VERSION
        ):
            # stale module despite the mtime check (e.g. a pre-stamp
            # .so imported by an older process image): rebuild once and
            # retry — if the cached copy survives the re-import, fall
            # back rather than return a module missing the new API
            build(force=True)
            module = _import_codec()
            if module is not None and (
                getattr(module, "NATIVE_API_VERSION", 0) < EXPECTED_API_VERSION
            ):
                _last_build_error = (
                    "stale native module cached in-process "
                    "(restart to pick up the rebuilt codec)"
                )
                module = None
        _codec = module
    _resolved = True
    if _codec is not None:
        _note_status("native", None)
    else:
        _note_status("fallback", _last_build_error or "native codec unavailable")
    return _codec
