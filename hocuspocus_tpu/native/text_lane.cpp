// Native "text lane": the full host-side hot path for plain-text docs.
//
// The round-3 verdict's host-plane bottleneck (~17 us of Python per
// doc-window) is death-by-a-thousand-cuts across lowering, serve-log
// bookkeeping and window encoding — no single hotspot to shave. This
// module owns the WHOLE per-update path for the hot shape (documents
// whose device content is one root text sequence: BASELINE configs
// 1/2/5, the 100k-doc regime):
//
//   lane_apply(handle, slot, update, presync, remote)
//       decode (Yjs v1) + causal lowering (known clocks, pending
//       buffering, gc routing, overlap trimming — the exact semantics
//       of tpu/lowering.DocLowerer restricted to this shape) + append
//       to the native serve log / unit log / dispatch queue.
//       Returns None when the update needs the Python path (rich
//       content, tree parents, map entries): the caller demotes the
//       doc and re-lowers from the CPU snapshot.
//   lane_drain(handle, k)
//       pops up to k ops per lane slot across EVERY lane slot into
//       columnar buffers the flush scatters straight into the device
//       batch (replaces the per-op Python loop in _build_batch).
//   lane_window(handle, slot, from_idx, ...)
//       one call per dirty doc building the broadcast window update
//       bytes (struct groups + window delete set) and the
//       cross-instance variant (remote-origin records excluded) —
//       byte-identical to serving._encode_window + DeleteSet.write.
//   lane_export(handle, slot) / lane_known(handle, slot)
//       materialize the log for the Python serving paths that stay
//       cold (stale/cold sync serves, text(), the RLE payload index).
//
// Reference hot loop being replaced: per-message decode+apply+fan-out
// in `packages/server/src/MessageReceiver.ts:195-213` and
// `packages/server/src/Document.ts:228-240`.
//
// lib0 varint / utf helpers are duplicated from codec.cpp (anonymous
// namespace, internal linkage — both objects link into one module).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// -- lib0 primitives ---------------------------------------------------------

struct LaneReader {
    const uint8_t* buf;
    Py_ssize_t len;
    Py_ssize_t pos = 0;

    uint8_t u8() {
        if (pos >= len) throw std::runtime_error("unexpected end of buffer");
        return buf[pos++];
    }
    uint64_t var_uint() {
        uint64_t num = 0;
        int shift = 0;
        while (true) {
            uint8_t b = u8();
            num |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (b < 0x80) return num;
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
    }
    Py_ssize_t checked_len(uint64_t n) {
        if (n > static_cast<uint64_t>(len - pos))
            throw std::runtime_error("length prefix exceeds buffer");
        return static_cast<Py_ssize_t>(n);
    }
    const char* bytes(Py_ssize_t n) {
        if (n < 0 || pos + n > len)
            throw std::runtime_error("unexpected end of buffer");
        const char* p = reinterpret_cast<const char*>(buf + pos);
        pos += n;
        return p;
    }
    std::pair<const char*, Py_ssize_t> var_string() {
        Py_ssize_t n = checked_len(var_uint());
        return {bytes(n), n};
    }
};

void put_var_uint(std::string& out, uint64_t num) {
    while (num > 0x7F) {
        out.push_back(static_cast<char>(0x80 | (num & 0x7F)));
        num >>= 7;
    }
    out.push_back(static_cast<char>(num));
}

void put_var_string(std::string& out, const char* s, size_t n) {
    put_var_uint(out, n);
    out.append(s, n);
}

constexpr uint8_t BIT_ORIGIN = 0x80;
constexpr uint8_t BIT_RIGHT_ORIGIN = 0x40;
constexpr uint8_t BIT_PARENT_SUB = 0x20;
constexpr uint32_t NONE_CLIENT = 0xFFFFFFFFu;

// utf-8 -> utf-16 code units with U+FFFD replacement (JS semantics)
void utf8_to_utf16(const char* s, Py_ssize_t n, std::vector<uint16_t>& out) {
    Py_ssize_t i = 0;
    while (i < n) {
        uint8_t c = static_cast<uint8_t>(s[i]);
        uint32_t cp;
        int need;
        if (c < 0x80) { cp = c; need = 0; }
        else if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; need = 1; }
        else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; need = 2; }
        else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; need = 3; }
        else { out.push_back(0xFFFD); i++; continue; }
        bool ok = true;
        for (int k = 1; k <= need; ++k) {
            if (i + k >= n || (static_cast<uint8_t>(s[i + k]) & 0xC0) != 0x80) {
                ok = false;
                break;
            }
            cp = (cp << 6) | (static_cast<uint8_t>(s[i + k]) & 0x3F);
        }
        if (!ok) { out.push_back(0xFFFD); i++; continue; }
        i += need + 1;
        if (cp >= 0x10000) {
            cp -= 0x10000;
            out.push_back(static_cast<uint16_t>(0xD800 + (cp >> 10)));
            out.push_back(static_cast<uint16_t>(0xDC00 + (cp & 0x3FF)));
        } else {
            out.push_back(static_cast<uint16_t>(cp));
        }
    }
}

// utf-16 code units -> utf-8, lone surrogates -> U+FFFD (TextEncoder)
void utf16_to_utf8(const uint16_t* s, size_t n, std::string& out) {
    size_t i = 0;
    while (i < n) {
        uint32_t cp = s[i];
        if (cp >= 0xD800 && cp < 0xDC00) {
            if (i + 1 < n && s[i + 1] >= 0xDC00 && s[i + 1] < 0xE000) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (s[i + 1] - 0xDC00);
                i += 2;
            } else {
                cp = 0xFFFD;
                i += 1;
            }
        } else if (cp >= 0xDC00 && cp < 0xE000) {
            cp = 0xFFFD;
            i += 1;
        } else {
            i += 1;
        }
        if (cp < 0x80) {
            out.push_back(static_cast<char>(cp));
        } else if (cp < 0x800) {
            out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
            out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        } else {
            out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
        }
    }
}

// -- lane state ---------------------------------------------------------------

constexpr int32_t KIND_INSERT = 1;
constexpr int32_t KIND_DELETE = 2;

constexpr uint8_t F_DELETED_CONTENT = 1;
constexpr uint8_t F_GC = 2;
constexpr uint8_t F_PRESYNC = 4;
constexpr uint8_t F_REMOTE = 8;

struct LaneOp {
    int32_t kind;
    uint32_t client;
    int64_t clock;
    int32_t run_len;
    uint32_t left_client;
    int64_t left_clock;
    uint32_t right_client;
    int64_t right_clock;
    int64_t unit_off;  // inserts: payload offset into units
    uint8_t flags;
};

// decoded struct waiting on (or ready for) emission
struct PendStruct {
    uint32_t client;
    int64_t clock;
    int32_t kind;  // 0 string, 1 deleted, 2 gc
    int64_t length;
    bool has_origin = false, has_right = false, has_root_parent = false;
    uint32_t oc = 0, rc = 0;
    int64_t ok = 0, rk = 0;
    std::string root;          // utf8, when has_root_parent
    std::vector<uint16_t> text;  // string payload
};

struct Interval {
    int64_t start, end;
    uint8_t tag;  // 0 seq, 1 gc
};

struct DelRange {
    uint32_t client;
    int64_t clock;
    int64_t len;
};

struct SlotLane {
    std::string root;  // single root seq name; empty until discovered
    bool root_known = false;
    std::vector<LaneOp> ops;       // serve log (inserts, deletes, gc)
    std::vector<uint16_t> units;   // insert payloads, arrival order
    std::vector<uint32_t> queue;   // undispatched op indices
    size_t q_pos = 0;
    std::unordered_map<uint32_t, int64_t> known;
    std::unordered_map<uint32_t, std::vector<Interval>> routes;
    std::vector<PendStruct> pending;
    std::vector<DelRange> pending_deletes;
    bool dead = false;

    int64_t known_of(uint32_t c) const {
        auto it = known.find(c);
        return it == known.end() ? 0 : it->second;
    }
    bool id_known(uint32_t c, int64_t k) const { return k < known_of(c); }

    const Interval* run_of_id(uint32_t c, int64_t k) const {
        auto it = routes.find(c);
        if (it == routes.end() || it->second.empty()) return nullptr;
        const auto& v = it->second;
        // emits per client are clock-ordered: binary search by start
        size_t lo = 0, hi = v.size();
        while (lo < hi) {
            size_t mid = (lo + hi) / 2;
            if (v[mid].start <= k) lo = mid + 1; else hi = mid;
        }
        if (lo == 0) return nullptr;
        const Interval& iv = v[lo - 1];
        return (iv.start <= k && k < iv.end) ? &iv : nullptr;
    }
    void record_route(uint32_t c, int64_t start, int64_t len, uint8_t tag) {
        routes[c].push_back(Interval{start, start + len, tag});
    }
};

struct LaneRegistry {
    std::unordered_map<int64_t, SlotLane> slots;
};

void registry_destructor(PyObject* cap) {
    delete static_cast<LaneRegistry*>(
        PyCapsule_GetPointer(cap, "hocuspocus_lane"));
}

LaneRegistry* registry_of(PyObject* cap) {
    return static_cast<LaneRegistry*>(
        PyCapsule_GetPointer(cap, "hocuspocus_lane"));
}

// -- lowering (DocLowerer semantics, text-lane subset) ------------------------

bool struct_ready(const SlotLane& lane, const PendStruct& p) {
    if (p.clock > lane.known_of(p.client)) return false;  // same-client gap
    if (p.has_origin && !lane.id_known(p.oc, p.ok)) return false;
    if (p.has_right && !lane.id_known(p.rc, p.rk)) return false;
    return true;
}

bool collected_by_gc(const SlotLane& lane, const PendStruct& p) {
    if (p.has_origin) {
        const Interval* iv = lane.run_of_id(p.oc, p.ok);
        if (iv && iv->tag == 1) return true;
    }
    if (p.has_right) {
        const Interval* iv = lane.run_of_id(p.rc, p.rk);
        if (iv && iv->tag == 1) return true;
    }
    return false;
}

// emit one causally-ready struct; returns false -> lane dead (demote)
bool emit_struct(SlotLane& lane, const PendStruct& p, uint8_t base_flags,
                 int64_t& queued_insert_units) {
    int64_t known = lane.known_of(p.client);
    if (p.clock + p.length <= known) return true;  // full duplicate
    if (p.kind == 2 || collected_by_gc(lane, p)) {
        // GC struct (or item resolving into a collected range): serve-
        // log-only record, never queued to the device
        int64_t offset = std::max<int64_t>(known - p.clock, 0);
        LaneOp op{};
        op.kind = KIND_INSERT;
        op.client = p.client;
        op.clock = p.clock + offset;
        op.run_len = static_cast<int32_t>(p.length - offset);
        op.left_client = NONE_CLIENT;
        op.right_client = NONE_CLIENT;
        op.unit_off = static_cast<int64_t>(lane.units.size());
        op.flags = static_cast<uint8_t>(base_flags | F_GC);
        lane.ops.push_back(op);
        lane.record_route(p.client, p.clock + offset, p.length - offset, 1);
        lane.known[p.client] = p.clock + p.length;
        return true;
    }
    // route resolution (text subset): explicit root parent, or via
    // an origin's recorded run
    if (p.has_root_parent) {
        if (!lane.root_known) {
            lane.root = p.root;
            lane.root_known = true;
        } else if (lane.root != p.root) {
            return false;  // a second root sequence: tree/map doc
        }
    } else {
        uint32_t ref_c;
        int64_t ref_k;
        if (p.has_origin) { ref_c = p.oc; ref_k = p.ok; }
        else if (p.has_right) { ref_c = p.rc; ref_k = p.rk; }
        else return false;  // no origins and no parent: undecidable
        const Interval* iv = lane.run_of_id(ref_c, ref_k);
        if (!iv || iv->tag != 0) return false;  // unknown/odd route
    }
    int64_t offset = std::max<int64_t>(known - p.clock, 0);
    uint32_t lc = p.has_origin ? p.oc : NONE_CLIENT;
    int64_t lk = p.has_origin ? p.ok : 0;
    if (offset > 0) {
        lc = p.client;
        lk = p.clock + offset - 1;
    }
    LaneOp op{};
    op.kind = KIND_INSERT;
    op.client = p.client;
    op.clock = p.clock + offset;
    op.run_len = static_cast<int32_t>(p.length - offset);
    op.left_client = lc;
    op.left_clock = lk;
    op.right_client = p.has_right ? p.rc : NONE_CLIENT;
    op.right_clock = p.has_right ? p.rk : 0;
    op.unit_off = static_cast<int64_t>(lane.units.size());
    op.flags = base_flags;
    if (p.kind == 1) {  // ContentDeleted run: zero markers in the log
        op.flags |= F_DELETED_CONTENT;
        lane.units.insert(lane.units.end(),
                          static_cast<size_t>(p.length - offset), 0);
    } else {
        lane.units.insert(lane.units.end(), p.text.begin() + offset,
                          p.text.end());
    }
    lane.ops.push_back(op);
    lane.queue.push_back(static_cast<uint32_t>(lane.ops.size() - 1));
    queued_insert_units += op.run_len;
    if (p.kind == 1) {
        // idempotent id-range tombstone over the full struct range
        LaneOp del{};
        del.kind = KIND_DELETE;
        del.client = p.client;
        del.clock = p.clock;
        del.run_len = static_cast<int32_t>(p.length);
        del.left_client = NONE_CLIENT;
        del.right_client = NONE_CLIENT;
        del.unit_off = static_cast<int64_t>(lane.units.size());
        del.flags = base_flags;
        lane.ops.push_back(del);
        lane.queue.push_back(static_cast<uint32_t>(lane.ops.size() - 1));
    }
    lane.record_route(p.client, p.clock + offset, p.length - offset, 0);
    lane.known[p.client] = p.clock + p.length;
    return true;
}

// split an id range across the runs it covers; false -> lane dead
bool route_delete(SlotLane& lane, uint32_t client, int64_t clock, int64_t len,
                  uint8_t base_flags) {
    int64_t end = clock + len;
    while (clock < end) {
        const Interval* iv = lane.run_of_id(client, clock);
        if (!iv) return false;  // covers ids never integrated
        int64_t upto = std::min(end, iv->end);
        if (iv->tag == 0) {
            LaneOp del{};
            del.kind = KIND_DELETE;
            del.client = client;
            del.clock = clock;
            del.run_len = static_cast<int32_t>(upto - clock);
            del.left_client = NONE_CLIENT;
            del.right_client = NONE_CLIENT;
            del.unit_off = static_cast<int64_t>(lane.units.size());
            del.flags = base_flags;
            lane.ops.push_back(del);
            lane.queue.push_back(static_cast<uint32_t>(lane.ops.size() - 1));
        }  // tag gc: already collected, tombstones meaningless
        clock = upto;
    }
    return true;
}

// the _drain loop: emit everything causally ready, then apply the
// known prefix of pending deletes
bool drain(SlotLane& lane, uint8_t base_flags, int64_t& queued_insert_units) {
    bool progress = true;
    while (progress) {
        progress = false;
        std::vector<PendStruct> remaining;
        remaining.reserve(lane.pending.size());
        for (auto& p : lane.pending) {
            if (struct_ready(lane, p)) {
                if (!emit_struct(lane, p, base_flags, queued_insert_units))
                    return false;
                progress = true;
            } else {
                remaining.push_back(std::move(p));
            }
        }
        lane.pending = std::move(remaining);
    }
    std::vector<DelRange> remaining_deletes;
    for (const auto& d : lane.pending_deletes) {
        int64_t known = lane.known_of(d.client);
        int64_t upto = std::min(known, d.clock + d.len);
        if (upto > d.clock) {
            if (!route_delete(lane, d.client, d.clock, upto - d.clock,
                              base_flags))
                return false;
        }
        if (upto < d.clock + d.len) {
            int64_t from = std::max(d.clock, upto);
            remaining_deletes.push_back(
                DelRange{d.client, from, d.clock + d.len - from});
        }
    }
    lane.pending_deletes = std::move(remaining_deletes);
    return true;
}

// decode one v1 update into pending structs/deletes; false -> unsupported
bool decode_into(SlotLane& lane, const uint8_t* buf, Py_ssize_t len) {
    LaneReader r{buf, len};
    uint64_t num_clients = r.var_uint();
    for (uint64_t ci = 0; ci < num_clients; ci++) {
        uint64_t num_structs = r.var_uint();
        uint32_t client = static_cast<uint32_t>(r.var_uint());
        int64_t clock = static_cast<int64_t>(r.var_uint());
        for (uint64_t si = 0; si < num_structs; si++) {
            uint8_t info = r.u8();
            uint8_t ref = info & 0x1F;
            PendStruct p{};
            p.client = client;
            p.clock = clock;
            if (ref == 0) {  // GC
                p.kind = 2;
                p.length = static_cast<int64_t>(r.var_uint());
            } else if (ref == 10) {  // Skip: host-only -> python path
                return false;
            } else if (ref == 1 || ref == 4) {  // Deleted / String
                if (info & BIT_ORIGIN) {
                    p.has_origin = true;
                    p.oc = static_cast<uint32_t>(r.var_uint());
                    p.ok = static_cast<int64_t>(r.var_uint());
                }
                if (info & BIT_RIGHT_ORIGIN) {
                    p.has_right = true;
                    p.rc = static_cast<uint32_t>(r.var_uint());
                    p.rk = static_cast<int64_t>(r.var_uint());
                }
                if (!(info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN))) {
                    if (r.var_uint() == 1) {
                        auto [s, n] = r.var_string();
                        p.has_root_parent = true;
                        p.root.assign(s, static_cast<size_t>(n));
                    } else {
                        return false;  // item parent: tree doc
                    }
                    if (info & BIT_PARENT_SUB) return false;  // map entry
                }
                if (ref == 1) {
                    p.kind = 1;
                    p.length = static_cast<int64_t>(r.var_uint());
                } else {
                    p.kind = 0;
                    auto [s, n] = r.var_string();
                    utf8_to_utf16(s, n, p.text);
                    p.length = static_cast<int64_t>(p.text.size());
                }
            } else {
                return false;  // any rich content: python path
            }
            clock += p.length;
            lane.pending.push_back(std::move(p));
        }
    }
    uint64_t ds_clients = r.var_uint();
    for (uint64_t i = 0; i < ds_clients; i++) {
        uint32_t client = static_cast<uint32_t>(r.var_uint());
        uint64_t ranges = r.var_uint();
        for (uint64_t j = 0; j < ranges; j++) {
            int64_t clock = static_cast<int64_t>(r.var_uint());
            int64_t dlen = static_cast<int64_t>(r.var_uint());
            lane.pending_deletes.push_back(DelRange{client, clock, dlen});
        }
    }
    return true;
}

// -- window encoding ----------------------------------------------------------

constexpr uint8_t CONTENT_STRING_REF = 4;
constexpr uint8_t CONTENT_DELETED_REF = 1;
constexpr uint8_t STRUCT_GC_REF = 0;

// emit one struct entry (GC ref / info byte / origins / root parent /
// payload), sliced by `offset` units for the first item of a cutoff
// group (offset 0 = the broadcast-window case). Shared by
// encode_window and lane_window_sm so the two paths can't diverge
// byte-wise. Returns false only for a rootless origin-less item.
bool emit_struct_entry(const SlotLane& lane, const LaneOp& op, int64_t offset,
                       std::string& out) {
    if (op.flags & F_GC) {
        out.push_back(static_cast<char>(STRUCT_GC_REF));
        put_var_uint(out, static_cast<uint64_t>(op.run_len - offset));
        return true;
    }
    uint8_t info = (op.flags & F_DELETED_CONTENT) ? CONTENT_DELETED_REF
                                                  : CONTENT_STRING_REF;
    uint32_t oc = op.left_client;
    int64_t ok = op.left_clock;
    if (offset > 0) {
        // emitting a tail: its origin is the unit just before the cut
        // (Item.write offset semantics)
        oc = op.client;
        ok = op.clock + offset - 1;
    }
    bool has_o = oc != NONE_CLIENT;
    bool has_r = op.right_client != NONE_CLIENT;
    if (has_o) info |= BIT_ORIGIN;
    if (has_r) info |= BIT_RIGHT_ORIGIN;
    out.push_back(static_cast<char>(info));
    if (has_o) {
        put_var_uint(out, oc);
        put_var_uint(out, static_cast<uint64_t>(ok));
    }
    if (has_r) {
        put_var_uint(out, op.right_client);
        put_var_uint(out, static_cast<uint64_t>(op.right_clock));
    }
    if (!has_o && !has_r) {
        if (!lane.root_known) return false;
        put_var_uint(out, 1);
        put_var_string(out, lane.root.data(), lane.root.size());
    }
    if (op.flags & F_DELETED_CONTENT) {
        put_var_uint(out, static_cast<uint64_t>(op.run_len - offset));
    } else {
        std::string payload;
        utf16_to_utf8(lane.units.data() + op.unit_off + offset,
                      static_cast<size_t>(op.run_len - offset), payload);
        put_var_string(out, payload.data(), payload.size());
    }
    return true;
}

// encode one window (indices into lane.ops) as update bytes;
// byte-identical to serving._encode_window + DeleteSet.write
bool encode_window(const SlotLane& lane, const std::vector<uint32_t>& recs,
                   std::string& out) {
    // group insert records by client
    std::map<uint32_t, std::vector<uint32_t>, std::greater<uint32_t>> by;
    std::map<uint32_t, std::vector<std::pair<int64_t, int64_t>>,
             std::greater<uint32_t>> ds;
    bool has_inserts = false;
    for (uint32_t idx : recs) {
        const LaneOp& op = lane.ops[idx];
        if (op.kind == KIND_DELETE) {
            ds[op.client].emplace_back(op.clock, op.run_len);
        } else if (op.kind == KIND_INSERT) {
            has_inserts = true;
            by[op.client].push_back(idx);
        }
    }
    if (!has_inserts && ds.empty()) return false;  // nothing to ship
    put_var_uint(out, by.size());
    for (auto& [client, idxs] : by) {
        std::stable_sort(idxs.begin(), idxs.end(),
                         [&](uint32_t a, uint32_t b) {
                             return lane.ops[a].clock < lane.ops[b].clock;
                         });
        put_var_uint(out, idxs.size());
        put_var_uint(out, client);
        put_var_uint(out, static_cast<uint64_t>(lane.ops[idxs[0]].clock));
        for (uint32_t idx : idxs) {
            if (!emit_struct_entry(lane, lane.ops[idx], 0, out)) return false;
        }
    }
    // window delete set: sorted + merged ranges, clients descending
    put_var_uint(out, ds.size());
    for (auto& [client, ranges] : ds) {
        std::sort(ranges.begin(), ranges.end());
        std::vector<std::pair<int64_t, int64_t>> merged;
        for (auto& [clock, rlen] : ranges) {
            if (!merged.empty() &&
                merged.back().first + merged.back().second >= clock) {
                merged.back().second =
                    std::max(merged.back().second,
                             clock + rlen - merged.back().first);
            } else {
                merged.emplace_back(clock, rlen);
            }
        }
        put_var_uint(out, client);
        put_var_uint(out, merged.size());
        for (auto& [clock, rlen] : merged) {
            put_var_uint(out, static_cast<uint64_t>(clock));
            put_var_uint(out, static_cast<uint64_t>(rlen));
        }
    }
    return true;
}

// -- python api ---------------------------------------------------------------

PyObject* lane_new(PyObject* /*self*/, PyObject* /*args*/) {
    return PyCapsule_New(new LaneRegistry(), "hocuspocus_lane",
                         registry_destructor);
}

PyObject* lane_open(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    reg->slots[slot];  // default-construct
    Py_RETURN_NONE;
}

PyObject* lane_close(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    reg->slots.erase(slot);
    Py_RETURN_NONE;
}

// lane_apply(cap, slot, update, presync, remote)
//   -> (ops_added, queued_insert_units, queued_ops, root_name|None)
//      | None=demote
//   ops_added counts serve-log records (incl. host-only GC records);
//   queued_ops counts only device-bound queue entries
PyObject* lane_apply(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    Py_buffer update;
    int presync = 0, remote = 0;
    if (!PyArg_ParseTuple(args, "OLy*pp", &cap, &slot, &update, &presync,
                          &remote))
        return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) {
        PyBuffer_Release(&update);
        return nullptr;
    }
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) {
        PyBuffer_Release(&update);
        PyErr_SetString(PyExc_KeyError, "lane slot not open");
        return nullptr;
    }
    SlotLane& lane = it->second;
    if (lane.dead) {
        PyBuffer_Release(&update);
        Py_RETURN_NONE;
    }
    uint8_t base_flags = static_cast<uint8_t>(
        (presync ? F_PRESYNC : 0) | (remote ? F_REMOTE : 0));
    size_t ops_before = lane.ops.size();
    size_t queued_before = lane.queue.size();
    int64_t queued_units = 0;
    bool ok;
    try {
        ok = decode_into(lane, static_cast<const uint8_t*>(update.buf),
                         update.len) &&
             drain(lane, base_flags, queued_units);
    } catch (const std::exception&) {
        ok = false;
    }
    PyBuffer_Release(&update);
    if (!ok) {
        lane.dead = true;
        Py_RETURN_NONE;  // caller demotes + re-lowers from CPU snapshot
    }
    PyObject* root = lane.root_known
                         ? PyUnicode_DecodeUTF8(lane.root.data(),
                                                static_cast<Py_ssize_t>(
                                                    lane.root.size()),
                                                "replace")
                         : Py_NewRef(Py_None);
    if (!root) return nullptr;
    return Py_BuildValue("(nLnN)",
                         static_cast<Py_ssize_t>(lane.ops.size() - ops_before),
                         static_cast<long long>(queued_units),
                         static_cast<Py_ssize_t>(lane.queue.size() - queued_before),
                         root);
}

PyObject* lane_queue_len(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) return PyLong_FromLong(0);
    return PyLong_FromSize_t(it->second.queue.size() - it->second.q_pos);
}

PyObject* lane_queue_total(PyObject* /*self*/, PyObject* arg) {
    LaneRegistry* reg = registry_of(arg);
    if (!reg) return nullptr;
    size_t total = 0;
    for (auto& [slot, lane] : reg->slots)
        total += lane.queue.size() - lane.q_pos;
    return PyLong_FromSize_t(total);
}

PyObject* lane_queue_max(PyObject* /*self*/, PyObject* arg) {
    LaneRegistry* reg = registry_of(arg);
    if (!reg) return nullptr;
    size_t mx = 0;
    for (auto& [slot, lane] : reg->slots)
        mx = std::max(mx, lane.queue.size() - lane.q_pos);
    return PyLong_FromSize_t(mx);
}

PyObject* lane_clear_queue(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it != reg->slots.end()) {
        it->second.queue.clear();
        it->second.q_pos = 0;
    }
    Py_RETURN_NONE;
}

// lane_drain(cap, k) -> (built, rows_i64, slots_i64, kind_i32,
//   client_u32, clock_i32, run_i32, lc_u32, lk_i32, rc_u32, rk_i32,
//   dispatch_slots_i64, dispatch_units_i64)
// Pops up to k ops per lane slot; buffers are bytes for np.frombuffer.
PyObject* lane_drain(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long k;
    if (!PyArg_ParseTuple(args, "OL", &cap, &k)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    std::vector<int64_t> rows, slots, d_slots, d_units;
    std::vector<int32_t> kind, clock, run, lk, rk;
    std::vector<uint32_t> client, lc, rc;
    for (auto& [slot, lane] : reg->slots) {
        size_t avail = lane.queue.size() - lane.q_pos;
        size_t take = std::min<size_t>(avail, static_cast<size_t>(k));
        if (!take) continue;
        int64_t units = 0;
        for (size_t i = 0; i < take; i++) {
            const LaneOp& op = lane.ops[lane.queue[lane.q_pos + i]];
            rows.push_back(static_cast<int64_t>(i));
            slots.push_back(slot);
            kind.push_back(op.kind);
            client.push_back(op.client);
            clock.push_back(static_cast<int32_t>(op.clock));
            run.push_back(op.run_len);
            lc.push_back(op.left_client);
            lk.push_back(static_cast<int32_t>(op.left_clock));
            rc.push_back(op.right_client);
            rk.push_back(static_cast<int32_t>(op.right_clock));
            if (op.kind == KIND_INSERT) units += op.run_len;
        }
        lane.q_pos += take;
        if (lane.q_pos == lane.queue.size()) {
            lane.queue.clear();
            lane.q_pos = 0;
        }
        d_slots.push_back(slot);
        d_units.push_back(units);
    }
    auto as_bytes = [](const void* p, size_t n) {
        return PyBytes_FromStringAndSize(static_cast<const char*>(p),
                                         static_cast<Py_ssize_t>(n));
    };
    return Py_BuildValue(
        "(nNNNNNNNNNNNN)", static_cast<Py_ssize_t>(rows.size()),
        as_bytes(rows.data(), rows.size() * 8),
        as_bytes(slots.data(), slots.size() * 8),
        as_bytes(kind.data(), kind.size() * 4),
        as_bytes(client.data(), client.size() * 4),
        as_bytes(clock.data(), clock.size() * 4),
        as_bytes(run.data(), run.size() * 4),
        as_bytes(lc.data(), lc.size() * 4),
        as_bytes(lk.data(), lk.size() * 4),
        as_bytes(rc.data(), rc.size() * 4),
        as_bytes(rk.data(), rk.size() * 4),
        as_bytes(d_slots.data(), d_slots.size() * 8),
        as_bytes(d_units.data(), d_units.size() * 8));
}

// lane_window(cap, slot, from_idx)
//   -> (full_update|None, cross_update|None, new_idx, log_len)
// cross excludes remote-origin records; None full = empty window.
// Identical semantics to serving.build_broadcast_pair's encode step.
PyObject* lane_window(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot, from_idx;
    if (!PyArg_ParseTuple(args, "OLL", &cap, &slot, &from_idx)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) {
        PyErr_SetString(PyExc_KeyError, "lane slot not open");
        return nullptr;
    }
    const SlotLane& lane = it->second;
    int64_t log_len = static_cast<int64_t>(lane.ops.size());
    int64_t start = std::min<int64_t>(from_idx, log_len);
    std::vector<uint32_t> window, local;
    for (int64_t i = start; i < log_len; i++) {
        const LaneOp& op = lane.ops[static_cast<size_t>(i)];
        if (op.flags & F_PRESYNC) continue;
        window.push_back(static_cast<uint32_t>(i));
        if (!(op.flags & F_REMOTE)) local.push_back(static_cast<uint32_t>(i));
    }
    if (window.empty())
        return Py_BuildValue("(OOLL)", Py_None, Py_None, log_len, log_len);
    std::string full;
    if (!encode_window(lane, window, full))
        return Py_BuildValue("(OOLL)", Py_None, Py_None, log_len, log_len);
    PyObject* full_obj =
        PyBytes_FromStringAndSize(full.data(),
                                  static_cast<Py_ssize_t>(full.size()));
    if (!full_obj) return nullptr;
    PyObject* cross_obj;
    if (local.size() == window.size()) {
        cross_obj = Py_NewRef(full_obj);
    } else if (local.empty()) {
        cross_obj = Py_NewRef(Py_None);
    } else {
        std::string cross;
        if (encode_window(lane, local, cross)) {
            cross_obj = PyBytes_FromStringAndSize(
                cross.data(), static_cast<Py_ssize_t>(cross.size()));
        } else {
            cross_obj = Py_NewRef(Py_None);
        }
        if (!cross_obj) {
            Py_DECREF(full_obj);
            return nullptr;
        }
    }
    return Py_BuildValue("(NNLL)", full_obj, cross_obj, log_len, log_len);
}

// lane_window_sm(cap, slot, [(client, cutoff), ...]) -> bytes
// The struct section of a stale/cold SyncStep2 for a lane doc: per-
// client cutoff trimming, the first emitted item's offset slice with
// its origin rewrite, and the mid-surrogate-pair cutoff widening — the
// native mirror of serving._encode_from_sm's struct work (the caller
// appends the device-tombstone delete set). Clients absent from the
// map are skipped, matching the Python path.
PyObject* lane_window_sm(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    PyObject* sm_obj;
    if (!PyArg_ParseTuple(args, "OLO", &cap, &slot, &sm_obj)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) {
        PyErr_SetString(PyExc_KeyError, "lane slot not open");
        return nullptr;
    }
    const SlotLane& lane = it->second;
    PyObject* sm_items = PySequence_Fast(sm_obj, "expected a sequence");
    if (!sm_items) return nullptr;
    std::unordered_map<uint32_t, int64_t> sm;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(sm_items); i++) {
        unsigned long long client;
        long long cutoff;
        if (!PyArg_ParseTuple(PySequence_Fast_GET_ITEM(sm_items, i), "KL",
                              &client, &cutoff)) {
            Py_DECREF(sm_items);
            return nullptr;
        }
        sm[static_cast<uint32_t>(client)] = cutoff;
    }
    Py_DECREF(sm_items);

    // mid-surrogate-pair cutoff widening, ONE pass over the log
    // (serving semantics: the unit AT the cutoff and the one BEFORE it
    // resolved independently — the pair may span two records)
    std::unordered_map<uint32_t, uint16_t> at_unit, prev_unit;
    for (const LaneOp& op : lane.ops) {
        if (op.kind != KIND_INSERT || (op.flags & F_GC) ||
            (op.flags & F_DELETED_CONTENT))
            continue;
        auto sit = sm.find(op.client);
        if (sit == sm.end() || sit->second <= 0) continue;
        int64_t cutoff = sit->second;
        if (op.clock <= cutoff && cutoff < op.clock + op.run_len)
            at_unit[op.client] = lane.units[static_cast<size_t>(
                op.unit_off + (cutoff - op.clock))];
        if (op.clock <= cutoff - 1 && cutoff - 1 < op.clock + op.run_len)
            prev_unit[op.client] = lane.units[static_cast<size_t>(
                op.unit_off + (cutoff - 1 - op.clock))];
    }
    for (auto& [client, at] : at_unit) {
        auto pit = prev_unit.find(client);
        if (pit != prev_unit.end() && at >= 0xDC00 && at < 0xE000 &&
            pit->second >= 0xD800 && pit->second < 0xDC00)
            sm[client] -= 1;
    }

    // group overlapping insert records by client (descending)
    std::map<uint32_t, std::vector<uint32_t>, std::greater<uint32_t>> by;
    for (uint32_t i = 0; i < lane.ops.size(); i++) {
        const LaneOp& op = lane.ops[i];
        if (op.kind != KIND_INSERT) continue;
        auto sit = sm.find(op.client);
        if (sit == sm.end()) continue;
        if (op.clock + op.run_len <= sit->second) continue;
        by[op.client].push_back(i);
    }
    std::string out;
    put_var_uint(out, by.size());
    for (auto& [client, idxs] : by) {
        std::stable_sort(idxs.begin(), idxs.end(),
                         [&](uint32_t a, uint32_t b) {
                             return lane.ops[a].clock < lane.ops[b].clock;
                         });
        int64_t cutoff = sm[client];
        int64_t write_clock = std::max(cutoff, lane.ops[idxs[0]].clock);
        put_var_uint(out, idxs.size());
        put_var_uint(out, client);
        put_var_uint(out, static_cast<uint64_t>(write_clock));
        bool first = true;
        for (uint32_t idx : idxs) {
            const LaneOp& op = lane.ops[idx];
            int64_t offset =
                first ? std::max<int64_t>(write_clock - op.clock, 0) : 0;
            first = false;
            if (!emit_struct_entry(lane, op, offset, out)) {
                PyErr_SetString(PyExc_ValueError, "rootless lane item");
                return nullptr;
            }
        }
    }
    return PyBytes_FromStringAndSize(out.data(),
                                     static_cast<Py_ssize_t>(out.size()));
}

// lane_covers(cap, slot, [(client, clock), ...]) -> bool
PyObject* lane_covers(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    PyObject* sv_obj;
    if (!PyArg_ParseTuple(args, "OLO", &cap, &slot, &sv_obj)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) Py_RETURN_FALSE;
    PyObject* items = PySequence_Fast(sv_obj, "expected a sequence");
    if (!items) return nullptr;
    bool ok = true;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(items); i++) {
        unsigned long long client;
        long long clock;
        if (!PyArg_ParseTuple(PySequence_Fast_GET_ITEM(items, i), "KL",
                              &client, &clock)) {
            Py_DECREF(items);
            return nullptr;
        }
        if (clock > it->second.known_of(static_cast<uint32_t>(client))) {
            ok = false;
            break;
        }
    }
    Py_DECREF(items);
    if (ok) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

// lane_known(cap, slot) -> dict client -> next clock
PyObject* lane_known(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    PyObject* known = PyDict_New();
    if (!known) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) return known;
    for (auto& [c, k] : it->second.known) {
        PyObject* key = PyLong_FromUnsignedLong(c);
        PyObject* val = PyLong_FromLongLong(k);
        if (!key || !val || PyDict_SetItem(known, key, val) < 0) {
            Py_XDECREF(key);
            Py_XDECREF(val);
            Py_DECREF(known);
            return nullptr;
        }
        Py_DECREF(key);
        Py_DECREF(val);
    }
    return known;
}

// lane_windows_batch(cap, [(slot, from_idx), ...])
//   -> [(full|None, cross|None, new_idx), ...]
// One call drains the whole dirty set's broadcast windows — the
// per-doc Python call overhead dominates the drain at 10k-doc width.
PyObject* lane_windows_batch(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    PyObject* items_obj;
    if (!PyArg_ParseTuple(args, "OO", &cap, &items_obj)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    PyObject* items = PySequence_Fast(items_obj, "expected a sequence");
    if (!items) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(items);
    PyObject* out = PyList_New(n);
    if (!out) {
        Py_DECREF(items);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        long long slot, from_idx;
        if (!PyArg_ParseTuple(PySequence_Fast_GET_ITEM(items, i), "LL", &slot,
                              &from_idx)) {
            Py_DECREF(items);
            Py_DECREF(out);
            return nullptr;
        }
        auto it = reg->slots.find(slot);
        PyObject* entry;
        if (it == reg->slots.end()) {
            entry = Py_BuildValue("(OOL)", Py_None, Py_None, from_idx);
        } else {
            const SlotLane& lane = it->second;
            int64_t log_len = static_cast<int64_t>(lane.ops.size());
            int64_t start = std::min<int64_t>(from_idx, log_len);
            std::vector<uint32_t> window, local;
            for (int64_t j = start; j < log_len; j++) {
                const LaneOp& op = lane.ops[static_cast<size_t>(j)];
                if (op.flags & F_PRESYNC) continue;
                window.push_back(static_cast<uint32_t>(j));
                if (!(op.flags & F_REMOTE))
                    local.push_back(static_cast<uint32_t>(j));
            }
            std::string full;
            if (window.empty() || !encode_window(lane, window, full)) {
                entry = Py_BuildValue("(OOL)", Py_None, Py_None, log_len);
            } else {
                PyObject* full_obj = PyBytes_FromStringAndSize(
                    full.data(), static_cast<Py_ssize_t>(full.size()));
                PyObject* cross_obj = nullptr;
                if (local.size() == window.size()) {
                    cross_obj = Py_NewRef(full_obj);
                } else if (local.empty()) {
                    cross_obj = Py_NewRef(Py_None);
                } else {
                    std::string cross;
                    if (encode_window(lane, local, cross)) {
                        cross_obj = PyBytes_FromStringAndSize(
                            cross.data(),
                            static_cast<Py_ssize_t>(cross.size()));
                    } else {
                        cross_obj = Py_NewRef(Py_None);
                    }
                }
                if (!full_obj || !cross_obj) {
                    Py_XDECREF(full_obj);
                    Py_XDECREF(cross_obj);
                    Py_DECREF(items);
                    Py_DECREF(out);
                    return nullptr;
                }
                entry = Py_BuildValue("(NNL)", full_obj, cross_obj, log_len);
            }
        }
        if (!entry) {
            Py_DECREF(items);
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, entry);
    }
    Py_DECREF(items);
    return out;
}

// lane_export(cap, slot) -> (ops list, units bytes u16le, known dict, root)
//   op: (kind, client, clock, run_len, lc, lk, rc, rk, unit_off, flags)
PyObject* lane_export(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) {
        PyErr_SetString(PyExc_KeyError, "lane slot not open");
        return nullptr;
    }
    const SlotLane& lane = it->second;
    PyObject* ops = PyList_New(static_cast<Py_ssize_t>(lane.ops.size()));
    if (!ops) return nullptr;
    for (size_t i = 0; i < lane.ops.size(); i++) {
        const LaneOp& op = lane.ops[i];
        PyObject* t = Py_BuildValue(
            "(iILiILILLi)", op.kind, op.client,
            static_cast<long long>(op.clock), op.run_len, op.left_client,
            static_cast<long long>(op.left_clock), op.right_client,
            static_cast<long long>(op.right_clock),
            static_cast<long long>(op.unit_off), static_cast<int>(op.flags));
        if (!t) {
            Py_DECREF(ops);
            return nullptr;
        }
        PyList_SET_ITEM(ops, static_cast<Py_ssize_t>(i), t);
    }
    PyObject* units = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(lane.units.data()),
        static_cast<Py_ssize_t>(lane.units.size() * 2));
    PyObject* known = PyDict_New();
    if (!units || !known) {
        Py_DECREF(ops);
        Py_XDECREF(units);
        Py_XDECREF(known);
        return nullptr;
    }
    for (auto& [c, k] : lane.known) {
        PyObject* key = PyLong_FromUnsignedLong(c);
        PyObject* val = PyLong_FromLongLong(k);
        if (!key || !val || PyDict_SetItem(known, key, val) < 0) {
            Py_XDECREF(key);
            Py_XDECREF(val);
            Py_DECREF(ops);
            Py_DECREF(units);
            Py_DECREF(known);
            return nullptr;
        }
        Py_DECREF(key);
        Py_DECREF(val);
    }
    PyObject* root =
        lane.root_known
            ? PyUnicode_DecodeUTF8(lane.root.data(),
                                   static_cast<Py_ssize_t>(lane.root.size()),
                                   "replace")
            : Py_NewRef(Py_None);
    if (!root) {
        Py_DECREF(ops);
        Py_DECREF(units);
        Py_DECREF(known);
        return nullptr;
    }
    return Py_BuildValue("(NNNN)", ops, units, known, root);
}

PyObject* lane_log_len(PyObject* /*self*/, PyObject* args) {
    PyObject* cap;
    long long slot;
    if (!PyArg_ParseTuple(args, "OL", &cap, &slot)) return nullptr;
    LaneRegistry* reg = registry_of(cap);
    if (!reg) return nullptr;
    auto it = reg->slots.find(slot);
    if (it == reg->slots.end()) return PyLong_FromLong(0);
    return Py_BuildValue(
        "(nn)", static_cast<Py_ssize_t>(it->second.ops.size()),
        static_cast<Py_ssize_t>(it->second.units.size()));
}

PyMethodDef lane_methods[] = {
    {"lane_new", lane_new, METH_NOARGS, "Create a text-lane registry."},
    {"lane_open", lane_open, METH_VARARGS, "Open a lane for a slot."},
    {"lane_close", lane_close, METH_VARARGS, "Release a slot's lane."},
    {"lane_apply", lane_apply, METH_VARARGS,
     "Decode+lower+append one update; None = needs the Python path."},
    {"lane_queue_len", lane_queue_len, METH_VARARGS,
     "Undispatched ops queued for one slot."},
    {"lane_queue_total", lane_queue_total, METH_O,
     "Undispatched ops across every lane slot."},
    {"lane_queue_max", lane_queue_max, METH_O,
     "Deepest per-slot undispatched queue (flush K sizing)."},
    {"lane_clear_queue", lane_clear_queue, METH_VARARGS,
     "Drop a slot's undispatched ops (retire path)."},
    {"lane_drain", lane_drain, METH_VARARGS,
     "Pop up to k ops per lane slot into columnar buffers."},
    {"lane_window", lane_window, METH_VARARGS,
     "Build (full, cross) broadcast window updates since an index."},
    {"lane_windows_batch", lane_windows_batch, METH_VARARGS,
     "Drain broadcast windows for many slots in one call."},
    {"lane_window_sm", lane_window_sm, METH_VARARGS,
     "Struct section of a stale/cold SyncStep2 under per-client cutoffs."},
    {"lane_covers", lane_covers, METH_VARARGS,
     "Whether the lane's known clocks cover a state vector."},
    {"lane_known", lane_known, METH_VARARGS,
     "The lane's per-client next-clock map."},
    {"lane_export", lane_export, METH_VARARGS,
     "Materialize a lane's log for the Python serving paths."},
    {"lane_log_len", lane_log_len, METH_VARARGS,
     "(ops, units) lengths of a slot's lane log."},
    {nullptr, nullptr, 0, nullptr},
};

}  // namespace

// called from codec.cpp's module init
void register_text_lane(PyObject* module) {
    PyModule_AddFunctions(module, lane_methods);
}
