from .awareness import Awareness, awareness_states_to_array, encode_awareness_update
from .close_events import (
    CloseEvent,
    CONNECTION_TIMEOUT,
    FORBIDDEN,
    MESSAGE_TOO_BIG,
    RESET_CONNECTION,
    UNAUTHORIZED,
)
from .message import IncomingMessage, MessageType, OutgoingMessage
from .sync import (
    MESSAGE_YJS_SYNC_STEP1,
    MESSAGE_YJS_SYNC_STEP2,
    MESSAGE_YJS_UPDATE,
    read_sync_message,
    read_sync_step1,
    read_sync_step2,
    read_update,
    write_sync_step1,
    write_sync_step2,
    write_update,
)
from .auth import (
    AuthMessageType,
    read_auth_message,
    write_authenticated,
    write_authentication,
    write_permission_denied,
)

__all__ = [
    "Awareness",
    "awareness_states_to_array",
    "encode_awareness_update",
    "CloseEvent",
    "CONNECTION_TIMEOUT",
    "FORBIDDEN",
    "MESSAGE_TOO_BIG",
    "RESET_CONNECTION",
    "UNAUTHORIZED",
    "IncomingMessage",
    "MessageType",
    "OutgoingMessage",
    "MESSAGE_YJS_SYNC_STEP1",
    "MESSAGE_YJS_SYNC_STEP2",
    "MESSAGE_YJS_UPDATE",
    "read_sync_message",
    "read_sync_step1",
    "read_sync_step2",
    "read_update",
    "write_sync_step1",
    "write_sync_step2",
    "write_update",
    "AuthMessageType",
    "read_auth_message",
    "write_authenticated",
    "write_authentication",
    "write_permission_denied",
]
