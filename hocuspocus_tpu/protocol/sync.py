"""y-protocols/sync equivalent: state-vector handshake + update relay."""

from __future__ import annotations

from typing import Any, Optional

from ..crdt import Doc, apply_update, encode_state_as_update, encode_state_vector
from ..crdt.encoding import Decoder, Encoder

MESSAGE_YJS_SYNC_STEP1 = 0
MESSAGE_YJS_SYNC_STEP2 = 1
MESSAGE_YJS_UPDATE = 2


def write_sync_step1(encoder: Encoder, doc: Doc) -> None:
    encoder.write_var_uint(MESSAGE_YJS_SYNC_STEP1)
    encoder.write_var_uint8_array(encode_state_vector(doc))


def write_sync_step2(encoder: Encoder, doc: Doc, encoded_state_vector: Optional[bytes] = None) -> None:
    encoder.write_var_uint(MESSAGE_YJS_SYNC_STEP2)
    encoder.write_var_uint8_array(encode_state_as_update(doc, encoded_state_vector))


def read_sync_step1(decoder: Decoder, encoder: Encoder, doc: Doc) -> None:
    write_sync_step2(encoder, doc, decoder.read_var_uint8_array())


def read_sync_step2(decoder: Decoder, doc: Doc, transaction_origin: Any = None) -> None:
    apply_update(doc, decoder.read_var_uint8_array(), transaction_origin)


def write_update(encoder: Encoder, update: bytes) -> None:
    encoder.write_var_uint(MESSAGE_YJS_UPDATE)
    encoder.write_var_uint8_array(update)


def coalesce_updates(updates: "list[bytes]") -> Optional[bytes]:
    """Merge one broadcast tick's captured updates into ONE equivalent
    update payload (the fan-out engine's per-tick frame — see
    server/fanout.py). Returns None when the merge fails; the caller
    must then fall back to per-update fan-out so no update is lost.

    Native-first: the C++ codec merges at the byte level (spans copied
    verbatim, GIL released) and returns None whenever it cannot prove
    byte identity with the Python merge — rich content refs, overlapping
    runs needing an offset split, non-canonical varints — in which case
    we fall through to :func:`crdt.update.merge_updates` unchanged.
    """
    if len(updates) == 1:
        return updates[0]
    from ..native import get_codec

    codec = get_codec()
    if codec is not None:
        merged = codec.coalesce_updates(updates)
        if merged is not None:
            return merged
    from ..crdt.update import merge_updates

    try:
        return merge_updates(updates)
    except Exception:
        return None


read_update = read_sync_step2


def read_sync_message(decoder: Decoder, encoder: Encoder, doc: Doc, transaction_origin: Any = None) -> int:
    message_type = decoder.read_var_uint()
    if message_type == MESSAGE_YJS_SYNC_STEP1:
        read_sync_step1(decoder, encoder, doc)
    elif message_type == MESSAGE_YJS_SYNC_STEP2:
        read_sync_step2(decoder, doc, transaction_origin)
    elif message_type == MESSAGE_YJS_UPDATE:
        read_update(decoder, doc, transaction_origin)
    else:
        raise ValueError(f"unknown sync message type {message_type}")
    return message_type
