"""Hot-path wire-frame helpers: native C++ fast path, Python fallback.

These cover the three per-message operations the server performs most:
routing (header parse), the update broadcast frame, and the per-update
durability ack (reference `packages/server/src/OutgoingMessage.ts`
frame layout; `Document.ts:228-240` fan-out; `MessageReceiver.ts:206-212`
ack). The pure-Python codec remains the correctness reference — the
native functions are byte-identical (tests/protocol/test_frames.py).
"""

from __future__ import annotations

from ..crdt.encoding import Decoder, Encoder
from ..native import get_codec
from .sync import MESSAGE_YJS_UPDATE


def parse_frame_header(data: bytes) -> tuple[str, int, int]:
    """[varString name][varUint type] -> (name, type, payload offset)."""
    codec = get_codec()
    if codec is not None:
        return codec.parse_frame_header(data)
    decoder = Decoder(data)
    name = decoder.read_var_string()
    msg_type = decoder.read_var_uint()
    return name, msg_type, decoder.pos


def build_update_frame(name: str, update: bytes, reply: bool = False) -> bytes:
    """[name][Sync|SyncReply][yjsUpdate][update] — the broadcast frame."""
    codec = get_codec()
    if codec is not None:
        return codec.build_update_frame(name, update, reply)
    from .message import MessageType

    encoder = Encoder()
    encoder.write_var_string(name)
    encoder.write_var_uint(MessageType.SyncReply if reply else MessageType.Sync)
    encoder.write_var_uint(MESSAGE_YJS_UPDATE)
    encoder.write_var_uint8_array(update)
    return encoder.to_bytes()


def build_sync_status_frame(name: str, ok: bool) -> bytes:
    """[name][SyncStatus][0|1] — the per-update durability ack."""
    codec = get_codec()
    if codec is not None:
        return codec.build_sync_status_frame(name, ok)
    from .message import MessageType

    encoder = Encoder()
    encoder.write_var_string(name)
    encoder.write_var_uint(MessageType.SyncStatus)
    encoder.write_var_uint(1 if ok else 0)
    return encoder.to_bytes()
