"""Hot-path wire-frame helpers: native C++ fast path, Python fallback.

These cover the three per-message operations the server performs most:
routing (header parse), the update broadcast frame, and the per-update
durability ack (reference `packages/server/src/OutgoingMessage.ts`
frame layout; `Document.ts:228-240` fan-out; `MessageReceiver.ts:206-212`
ack). The pure-Python codec remains the correctness reference — the
native functions are byte-identical (tests/protocol/test_frames.py).
"""

from __future__ import annotations

import time

from ..crdt.encoding import Decoder, Encoder
from ..native import get_codec
from ..observability.costs import get_cost_ledger
from .sync import MESSAGE_YJS_UPDATE


def _type_name(message_type: int) -> str:
    from ..observability.wire import message_type_name

    return message_type_name(message_type)


def parse_frame_header(data: bytes) -> tuple[str, int, int]:
    """[varString name][varUint type] -> (name, type, payload offset)."""
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        parsed = codec.parse_frame_header(data)
    else:
        decoder = Decoder(data)
        name = decoder.read_var_string()
        msg_type = decoder.read_var_uint()
        parsed = (name, msg_type, decoder.pos)
    if ledger.enabled:
        # varint_header: attribution detail inside frame_decode (the
        # header's share of the per-frame budget); bytes = header bytes
        ledger.record(
            "varint_header",
            _type_name(parsed[1]),
            time.perf_counter_ns() - t0,
            parsed[2],
        )
    return parsed


def build_update_frame(name: str, update: bytes, reply: bool = False) -> bytes:
    """[name][Sync|SyncReply][yjsUpdate][update] — the broadcast frame."""
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        frame = codec.build_update_frame(name, update, reply)
    else:
        from .message import MessageType

        encoder = Encoder()
        encoder.write_var_string(name)
        encoder.write_var_uint(MessageType.SyncReply if reply else MessageType.Sync)
        encoder.write_var_uint(MESSAGE_YJS_UPDATE)
        encoder.write_var_uint8_array(update)
        frame = encoder.to_bytes()
    if ledger.enabled:
        ledger.record(
            "frame_encode",
            "SyncReply" if reply else "Sync",
            time.perf_counter_ns() - t0,
            len(frame),
        )
    return frame


def parse_frame_headers_batch(
    frames: "list[bytes]", skip_malformed: bool = False
) -> "list[tuple[str, int, int] | None]":
    """Parse N frame headers in ONE native call (GIL released during the
    byte scan; consecutive frames for the same document share one str).

    Strict mode (default) raises ValueError on the first malformed
    header, matching :func:`parse_frame_header`. ``skip_malformed=True``
    yields ``None`` slots instead — the replication-inbox contract where
    a bad frame is dropped, not fatal. Ledger cost is amortized: one
    ``varint_header`` record advancing the frame counter by N.
    """
    if not frames:
        return []
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        parsed = codec.parse_frame_headers_batch(frames, skip_malformed)
    else:
        parsed = []
        for i, data in enumerate(frames):
            try:
                decoder = Decoder(data)
                name = decoder.read_var_string()
                msg_type = decoder.read_var_uint()
                parsed.append((name, msg_type, decoder.pos))
            except (ValueError, EOFError, IndexError) as exc:
                # normalize to the native path's error class: batch parity
                # is ValueError on BOTH paths (the scalar Python path's
                # EOFError/IndexError zoo stays as-is for compatibility)
                if not skip_malformed:
                    raise ValueError(
                        f"malformed frame header at index {i}"
                    ) from exc
                parsed.append(None)
            except TypeError:
                # non-buffer input: strict mode propagates (native raises
                # TypeError from the buffer protocol), skip mode drops
                if not skip_malformed:
                    raise
                parsed.append(None)
    if ledger.enabled:
        ok = [p for p in parsed if p is not None]
        if ok:
            ledger.record_batch(
                "varint_header",
                _type_name(ok[0][1]),
                time.perf_counter_ns() - t0,
                len(ok),
                sum(p[2] for p in ok),
            )
    return parsed


def build_update_frames_batch(
    items: "list[tuple[str, bytes] | tuple[str, bytes, bool]]",
) -> "list[bytes]":
    """Build N broadcast frames in ONE native call (frames laid out in a
    single arena with the GIL released, then cut into per-frame bytes).
    Ledger cost is amortized across the batch like the scalar path's
    per-frame ``frame_encode`` records."""
    if not items:
        return []
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        built = codec.build_update_frames_batch(
            [it if isinstance(it, tuple) else tuple(it) for it in items]
        )
    else:
        from .message import MessageType

        built = []
        for it in items:
            name, update = it[0], it[1]
            reply = bool(it[2]) if len(it) > 2 else False
            encoder = Encoder()
            encoder.write_var_string(name)
            encoder.write_var_uint(
                MessageType.SyncReply if reply else MessageType.Sync
            )
            encoder.write_var_uint(MESSAGE_YJS_UPDATE)
            encoder.write_var_uint8_array(update)
            built.append(encoder.to_bytes())
    if ledger.enabled:
        ledger.record_batch(
            "frame_encode",
            "Sync",
            time.perf_counter_ns() - t0,
            len(built),
            sum(len(f) for f in built),
        )
    return built


def build_sync_status_frame(name: str, ok: bool) -> bytes:
    """[name][SyncStatus][0|1] — the per-update durability ack."""
    codec = get_codec()
    if codec is not None:
        return codec.build_sync_status_frame(name, ok)
    from .message import MessageType

    encoder = Encoder()
    encoder.write_var_string(name)
    encoder.write_var_uint(MessageType.SyncStatus)
    encoder.write_var_uint(1 if ok else 0)
    return encoder.to_bytes()
