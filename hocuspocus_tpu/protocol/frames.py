"""Hot-path wire-frame helpers: native C++ fast path, Python fallback.

These cover the three per-message operations the server performs most:
routing (header parse), the update broadcast frame, and the per-update
durability ack (reference `packages/server/src/OutgoingMessage.ts`
frame layout; `Document.ts:228-240` fan-out; `MessageReceiver.ts:206-212`
ack). The pure-Python codec remains the correctness reference — the
native functions are byte-identical (tests/protocol/test_frames.py).
"""

from __future__ import annotations

import time

from ..crdt.encoding import Decoder, Encoder
from ..native import get_codec
from ..observability.costs import get_cost_ledger
from .sync import MESSAGE_YJS_UPDATE


def _type_name(message_type: int) -> str:
    from ..observability.wire import message_type_name

    return message_type_name(message_type)


def parse_frame_header(data: bytes) -> tuple[str, int, int]:
    """[varString name][varUint type] -> (name, type, payload offset)."""
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        parsed = codec.parse_frame_header(data)
    else:
        decoder = Decoder(data)
        name = decoder.read_var_string()
        msg_type = decoder.read_var_uint()
        parsed = (name, msg_type, decoder.pos)
    if ledger.enabled:
        # varint_header: attribution detail inside frame_decode (the
        # header's share of the per-frame budget); bytes = header bytes
        ledger.record(
            "varint_header",
            _type_name(parsed[1]),
            time.perf_counter_ns() - t0,
            parsed[2],
        )
    return parsed


def build_update_frame(name: str, update: bytes, reply: bool = False) -> bytes:
    """[name][Sync|SyncReply][yjsUpdate][update] — the broadcast frame."""
    ledger = get_cost_ledger()
    t0 = time.perf_counter_ns() if ledger.enabled else 0
    codec = get_codec()
    if codec is not None:
        frame = codec.build_update_frame(name, update, reply)
    else:
        from .message import MessageType

        encoder = Encoder()
        encoder.write_var_string(name)
        encoder.write_var_uint(MessageType.SyncReply if reply else MessageType.Sync)
        encoder.write_var_uint(MESSAGE_YJS_UPDATE)
        encoder.write_var_uint8_array(update)
        frame = encoder.to_bytes()
    if ledger.enabled:
        ledger.record(
            "frame_encode",
            "SyncReply" if reply else "Sync",
            time.perf_counter_ns() - t0,
            len(frame),
        )
    return frame


def build_sync_status_frame(name: str, ok: bool) -> bytes:
    """[name][SyncStatus][0|1] — the per-update durability ack."""
    codec = get_codec()
    if codec is not None:
        return codec.build_sync_status_frame(name, ok)
    from .message import MessageType

    encoder = Encoder()
    encoder.write_var_string(name)
    encoder.write_var_uint(MessageType.SyncStatus)
    encoder.write_var_uint(1 if ok else 0)
    return encoder.to_bytes()
