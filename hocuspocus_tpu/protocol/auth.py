"""Auth submessage codec (reference `packages/common/src/auth.ts`)."""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Optional

from ..crdt.encoding import Decoder, Encoder


class AuthMessageType(IntEnum):
    Token = 0
    PermissionDenied = 1
    Authenticated = 2


def write_authentication(encoder: Encoder, auth: str) -> None:
    encoder.write_var_uint(AuthMessageType.Token)
    encoder.write_var_string(auth)


def write_permission_denied(encoder: Encoder, reason: str) -> None:
    encoder.write_var_uint(AuthMessageType.PermissionDenied)
    encoder.write_var_string(reason)


def write_authenticated(encoder: Encoder, scope: str) -> None:
    """scope is 'readonly' or 'read-write'."""
    encoder.write_var_uint(AuthMessageType.Authenticated)
    encoder.write_var_string(scope)


def read_auth_message(
    decoder: Decoder,
    permission_denied_handler: Callable[[str], None],
    authenticated_handler: Callable[[str], None],
) -> None:
    msg_type = decoder.read_var_uint()
    if msg_type == AuthMessageType.PermissionDenied:
        permission_denied_handler(decoder.read_var_string())
    elif msg_type == AuthMessageType.Authenticated:
        authenticated_handler(decoder.read_var_string())
