"""Awareness CRDT (y-protocols/awareness equivalent).

Ephemeral per-client presence state (cursors, names) with clock-based
last-writer-wins semantics. Wire format: varUint numClients; per client:
varUint clientID, varUint clock, varString JSON state ("null" = removed).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Iterable, Optional

from ..crdt import Doc
from ..crdt.doc import Observable
from ..crdt.encoding import Decoder, Encoder

OUTDATED_TIMEOUT = 30.0  # seconds


class Awareness(Observable):
    def __init__(self, doc: Doc, outdated_timeout: float = OUTDATED_TIMEOUT) -> None:
        super().__init__()
        self.doc = doc
        self.client_id = doc.client_id
        self.states: dict[int, dict] = {}
        # client -> {"clock": int, "last_updated": float}
        self.meta: dict[int, dict] = {}
        self.outdated_timeout = outdated_timeout
        self._check_task: Optional[asyncio.Task] = None
        self.set_local_state({})
        # Periodic keepalive: renew the local state (generating awareness
        # traffic that keeps idle connections alive past the reconnect
        # timeout) and prune outdated remote clients — the y-protocols
        # Awareness check interval. Only when a loop is running.
        try:
            loop = asyncio.get_running_loop()
            self._check_task = loop.create_task(self._check_loop())
        except RuntimeError:
            pass

    async def _check_loop(self) -> None:
        interval = self.outdated_timeout / 10
        while True:
            await asyncio.sleep(interval)
            now = time.monotonic()
            local_meta = self.meta.get(self.client_id)
            if (
                self.get_local_state() is not None
                and local_meta is not None
                and self.outdated_timeout / 2 <= now - local_meta["last_updated"]
            ):
                self.set_local_state(self.get_local_state())
            remove_outdated(self, self.outdated_timeout)

    def destroy(self) -> None:
        if self._check_task is not None:
            self._check_task.cancel()
            self._check_task = None
        self.emit("destroy", self)
        self.set_local_state(None)
        self._observers = {}

    def get_local_state(self) -> Optional[dict]:
        return self.states.get(self.client_id)

    def set_local_state(self, state: Optional[dict]) -> None:
        client_id = self.client_id
        curr_meta = self.meta.get(client_id)
        clock = 0 if curr_meta is None else curr_meta["clock"] + 1
        prev_state = self.states.get(client_id)
        if state is None:
            self.states.pop(client_id, None)
        else:
            self.states[client_id] = state
        self.meta[client_id] = {"clock": clock, "last_updated": time.monotonic()}
        added, updated, filtered_updated, removed = [], [], [], []
        if state is None:
            if prev_state is not None:
                removed.append(client_id)
        elif prev_state is None:
            added.append(client_id)
        else:
            updated.append(client_id)
            if prev_state != state:
                filtered_updated.append(client_id)
        if added or filtered_updated or removed:
            self.emit("change", {"added": added, "updated": filtered_updated, "removed": removed}, "local")
        self.emit("update", {"added": added, "updated": updated, "removed": removed}, "local")

    def set_local_state_field(self, field: str, value: Any) -> None:
        state = self.get_local_state()
        if state is not None:
            new_state = dict(state)
            new_state[field] = value
            self.set_local_state(new_state)

    def get_states(self) -> dict[int, dict]:
        return self.states


def remove_awareness_states(awareness: Awareness, clients: Iterable[int], origin: Any) -> None:
    removed = []
    for client_id in clients:
        if client_id in awareness.states:
            del awareness.states[client_id]
            if client_id == awareness.client_id:
                curr_meta = awareness.meta[client_id]
                awareness.meta[client_id] = {
                    "clock": curr_meta["clock"] + 1,
                    "last_updated": time.monotonic(),
                }
            removed.append(client_id)
    if removed:
        awareness.emit("change", {"added": [], "updated": [], "removed": removed}, origin)
        awareness.emit("update", {"added": [], "updated": [], "removed": removed}, origin)


def encode_awareness_update(
    awareness: Awareness, clients: Iterable[int], states: Optional[dict[int, dict]] = None
) -> bytes:
    states = awareness.states if states is None else states
    clients = list(clients)
    encoder = Encoder()
    encoder.write_var_uint(len(clients))
    for client_id in clients:
        state = states.get(client_id)
        clock = awareness.meta.get(client_id, {"clock": 0})["clock"]
        encoder.write_var_uint(client_id)
        encoder.write_var_uint(clock)
        encoder.write_var_string(json.dumps(state, separators=(",", ":")))
    return encoder.to_bytes()


def apply_awareness_update(awareness: Awareness, update: bytes, origin: Any) -> None:
    decoder = Decoder(update)
    timestamp = time.monotonic()
    added, updated, filtered_updated, removed = [], [], [], []
    length = decoder.read_var_uint()
    for _ in range(length):
        client_id = decoder.read_var_uint()
        clock = decoder.read_var_uint()
        state = json.loads(decoder.read_var_string())
        client_meta = awareness.meta.get(client_id)
        prev_state = awareness.states.get(client_id)
        curr_clock = 0 if client_meta is None else client_meta["clock"]
        if curr_clock < clock or (
            curr_clock == clock and state is None and client_id in awareness.states
        ):
            if state is None:
                if client_id == awareness.client_id and awareness.get_local_state() is not None:
                    # never remove the local state; refresh it with a higher clock
                    clock += 1
                else:
                    awareness.states.pop(client_id, None)
            else:
                awareness.states[client_id] = state
            awareness.meta[client_id] = {"clock": clock, "last_updated": timestamp}
            if client_meta is None and state is not None:
                added.append(client_id)
            elif client_meta is not None and state is None:
                removed.append(client_id)
            elif state is not None:
                if state != prev_state:
                    filtered_updated.append(client_id)
                updated.append(client_id)
    if added or filtered_updated or removed:
        awareness.emit(
            "change", {"added": added, "updated": filtered_updated, "removed": removed}, origin
        )
    if added or updated or removed:
        awareness.emit("update", {"added": added, "updated": updated, "removed": removed}, origin)


def remove_outdated(awareness: Awareness, timeout: float = OUTDATED_TIMEOUT) -> list[int]:
    """Prune remote states not refreshed within `timeout` seconds."""
    now = time.monotonic()
    outdated = [
        client_id
        for client_id, meta in awareness.meta.items()
        if client_id != awareness.client_id
        and now - meta["last_updated"] >= timeout
        and client_id in awareness.states
    ]
    if outdated:
        remove_awareness_states(awareness, outdated, "timeout")
    return outdated


def awareness_states_to_array(states: dict[int, dict]) -> list[dict]:
    return [{"clientId": client_id, **state} for client_id, state in states.items()]
