"""WebSocket close events (reference `packages/common/src/CloseEvents.ts`)."""

from __future__ import annotations

from typing import NamedTuple


class CloseEvent(NamedTuple):
    code: int
    reason: str


MESSAGE_TOO_BIG = CloseEvent(1009, "Message Too Big")
# graceful drain (docs/guides/durability.md): 1012 is the standard
# "Service Restart" code — clients SHOULD reconnect (another instance,
# or this one after restart), unlike the 4xxx application rejections
SERVICE_RESTART = CloseEvent(1012, "Service Restart")
# overload control plane (docs/guides/overload.md): 1013 is the
# standard "Try Again Later" code — the server is shedding load, the
# client should back off and reconnect (the transport overflow policy
# and RED-state ingress enforcement both close with it)
TRY_AGAIN_LATER = CloseEvent(1013, "Try Again Later")
RESET_CONNECTION = CloseEvent(4205, "Reset Connection")
UNAUTHORIZED = CloseEvent(4401, "Unauthorized")
FORBIDDEN = CloseEvent(4403, "Forbidden")
CONNECTION_TIMEOUT = CloseEvent(4408, "Connection Timeout")


class CloseError(Exception):
    """Raised to close a connection with a specific close event."""

    def __init__(self, event: CloseEvent) -> None:
        super().__init__(event.reason)
        self.event = event
