"""Hocuspocus wire messages: [varString documentName][varUint type][payload].

Python equivalents of the reference's IncomingMessage/OutgoingMessage
wrappers (`packages/server/src/IncomingMessage.ts` / `OutgoingMessage.ts`).
"""

from __future__ import annotations

from enum import IntEnum
from typing import Any, Iterable, Optional

from ..crdt import Doc
from ..crdt.encoding import Decoder, Encoder
from .auth import write_authenticated, write_authentication, write_permission_denied
from .awareness import Awareness, encode_awareness_update
from .sync import write_sync_step1, write_sync_step2, write_update


class MessageType(IntEnum):
    Unknown = -1
    Sync = 0
    Awareness = 1
    Auth = 2
    QueryAwareness = 3
    SyncReply = 4  # same as Sync, but won't trigger another SyncStep1
    Stateless = 5
    BroadcastStateless = 6
    CLOSE = 7
    SyncStatus = 8


class IncomingMessage:
    """Decoder over a received frame, with a lazy reply encoder."""

    def __init__(self, data: bytes) -> None:
        self.decoder = Decoder(data)
        self._encoder: Optional[Encoder] = None

    @property
    def encoder(self) -> Encoder:
        if self._encoder is None:
            self._encoder = Encoder()
        return self._encoder

    def read_var_uint(self) -> int:
        return self.decoder.read_var_uint()

    def read_var_string(self) -> str:
        return self.decoder.read_var_string()

    def read_var_uint8_array(self) -> bytes:
        return self.decoder.read_var_uint8_array()

    def peek_var_uint8_array(self) -> bytes:
        pos = self.decoder.pos
        result = self.decoder.read_var_uint8_array()
        self.decoder.pos = pos
        return result

    def peek_var_string(self) -> str:
        return self.decoder.peek_var_string()

    def write_var_uint(self, value: int) -> None:
        self.encoder.write_var_uint(value)

    def write_var_string(self, value: str) -> None:
        self.encoder.write_var_string(value)

    def to_bytes(self) -> bytes:
        return self.encoder.to_bytes()

    @property
    def length(self) -> int:
        return len(self.encoder)


class OutgoingMessage:
    """Builder for an outbound frame, prefixed with the document name."""

    def __init__(self, document_name: str) -> None:
        self.encoder = Encoder()
        self.type: Optional[int] = None
        self.category: Optional[str] = None
        self.document_name = document_name
        self.encoder.write_var_string(document_name)

    def create_sync_message(self) -> "OutgoingMessage":
        self.type = MessageType.Sync
        self.encoder.write_var_uint(MessageType.Sync)
        return self

    def create_sync_reply_message(self) -> "OutgoingMessage":
        self.type = MessageType.SyncReply
        self.encoder.write_var_uint(MessageType.SyncReply)
        return self

    def create_awareness_update_message(
        self, awareness: Awareness, changed_clients: Optional[Iterable[int]] = None
    ) -> "OutgoingMessage":
        self.type = MessageType.Awareness
        self.category = "Update"
        clients = list(changed_clients) if changed_clients is not None else list(awareness.get_states().keys())
        message = encode_awareness_update(awareness, clients)
        self.encoder.write_var_uint(MessageType.Awareness)
        self.encoder.write_var_uint8_array(message)
        return self

    def write_query_awareness(self) -> "OutgoingMessage":
        self.type = MessageType.QueryAwareness
        self.category = "Update"
        self.encoder.write_var_uint(MessageType.QueryAwareness)
        return self

    def write_authentication(self, token: str) -> "OutgoingMessage":
        # client -> server (used by the provider)
        self.type = MessageType.Auth
        self.category = "Token"
        self.encoder.write_var_uint(MessageType.Auth)
        write_authentication(self.encoder, token)
        return self

    def write_authenticated(self, readonly: bool) -> "OutgoingMessage":
        self.type = MessageType.Auth
        self.category = "Authenticated"
        self.encoder.write_var_uint(MessageType.Auth)
        write_authenticated(self.encoder, "readonly" if readonly else "read-write")
        return self

    def write_permission_denied(self, reason: str) -> "OutgoingMessage":
        self.type = MessageType.Auth
        self.category = "PermissionDenied"
        self.encoder.write_var_uint(MessageType.Auth)
        write_permission_denied(self.encoder, reason)
        return self

    def write_first_sync_step_for(self, document: Doc) -> "OutgoingMessage":
        self.category = "SyncStep1"
        write_sync_step1(self.encoder, document)
        return self

    def write_second_sync_step_for(
        self, document: Doc, encoded_state_vector: Optional[bytes] = None
    ) -> "OutgoingMessage":
        self.category = "SyncStep2"
        write_sync_step2(self.encoder, document, encoded_state_vector)
        return self

    def write_update(self, update: bytes) -> "OutgoingMessage":
        self.category = "Update"
        write_update(self.encoder, update)
        return self

    def write_stateless(self, payload: str) -> "OutgoingMessage":
        self.category = "Stateless"
        self.encoder.write_var_uint(MessageType.Stateless)
        self.encoder.write_var_string(payload)
        return self

    def write_broadcast_stateless(self, payload: str) -> "OutgoingMessage":
        self.category = "Stateless"
        self.encoder.write_var_uint(MessageType.BroadcastStateless)
        self.encoder.write_var_string(payload)
        return self

    def write_sync_status(self, update_saved: bool) -> "OutgoingMessage":
        self.category = "SyncStatus"
        self.encoder.write_var_uint(MessageType.SyncStatus)
        self.encoder.write_var_uint(1 if update_saved else 0)
        return self

    def write_close_message(self, reason: str) -> "OutgoingMessage":
        self.type = MessageType.CLOSE
        self.encoder.write_var_uint(MessageType.CLOSE)
        self.encoder.write_var_string(reason)
        return self

    def to_bytes(self) -> bytes:
        return self.encoder.to_bytes()
