"""Embedding the collaboration core in your own aiohttp application.

Equivalent of reference `playground/backend/src/express.ts` /
`koa.ts` / `hono.ts`: the framework-agnostic core is driven through
`hocuspocus.handle_connection(transport, request_info, context)` —
any web framework that can hand you a websocket works.

Run: python examples/embed_aiohttp.py
"""

import asyncio

from aiohttp import WSMsgType, web

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu.server import Hocuspocus, RequestInfo  # noqa: E402
from hocuspocus_tpu.server.server import AiohttpWebSocketTransport  # noqa: E402

hocuspocus = Hocuspocus()


async def collab(request: web.Request) -> web.WebSocketResponse:
    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)
    transport = AiohttpWebSocketTransport(ws)
    request_info = RequestInfo(headers=dict(request.headers), url=str(request.rel_url))
    # anything you put in context is visible to every hook
    connection = hocuspocus.handle_connection(transport, request_info, {"via": "embedded"})
    try:
        async for msg in ws:
            if msg.type == WSMsgType.BINARY:
                await connection.handle_message(msg.data)
    finally:
        transport.abort()
        await connection.handle_transport_close(ws.close_code or 1000, "")
    return ws


async def index(request: web.Request) -> web.Response:
    return web.Response(text="my app with embedded collaboration at /collab")


async def main() -> None:
    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/collab", collab)
    runner = web.AppRunner(app)
    await runner.setup()
    await web.TCPSite(runner, "127.0.0.1", 8000).start()
    print("listening on http://127.0.0.1:8000 (ws at /collab)")
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
