"""Provider usage: connect, edit, observe, awareness.

Run examples/default.py first, then: python examples/client.py
"""

import asyncio

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu.provider import HocuspocusProvider  # noqa: E402


async def main() -> None:
    provider = HocuspocusProvider(
        name="example-document",
        url="ws://127.0.0.1:8000",
        token="my-access-token",
        on_synced=lambda data: print("synced!"),
        on_authenticated=lambda data: print("authenticated:", data["scope"]),
        on_stateless=lambda data: print("stateless message:", data["payload"]),
    )

    text = provider.document.get_text("content")
    text.observe(lambda event, tr: print("delta:", event.delta))

    while not provider.synced:
        await asyncio.sleep(0.05)

    text.insert(0, "Hello from Python! ")
    provider.set_awareness_field("user", {"name": "example", "color": "#ffcc00"})

    await asyncio.sleep(2)
    print("document is now:", text.to_string())
    provider.destroy()


if __name__ == "__main__":
    asyncio.run(main())
