"""Default server: SQLite persistence + logging on port 8000.

Equivalent of reference `playground/backend/src/default.ts`.
Run: python examples/default.py
"""

import asyncio
import logging

from hocuspocus_tpu import Configuration, Server
from hocuspocus_tpu.extensions import Logger, SQLite


async def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    server = Server(
        Configuration(
            name="playground-default",
            extensions=[Logger(), SQLite(database="playground.db")],
        )
    )
    await server.listen(port=8000)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
