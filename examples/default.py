"""Default server: SQLite persistence + logging on port 8000.

Equivalent of reference `playground/backend/src/default.ts`.
Run: python examples/default.py
"""

import asyncio
import logging

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu import Configuration, Server  # noqa: E402
from hocuspocus_tpu.extensions import Logger, SQLite  # noqa: E402


async def main() -> None:
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    server = Server(
        Configuration(
            name="playground-default",
            extensions=[Logger(), SQLite(database="playground.db")],
        )
    )
    await server.listen(port=8000)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
