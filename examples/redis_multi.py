"""Two server instances sharing documents through Redis fan-out.

Equivalent of reference `playground/backend/src/redis.ts`, with the
in-process mini-redis so the example is self-contained — point `host`/
`port` at a real Redis in production. Each instance runs a serve-mode
TPU merge plane (the production topology): local fan-out AND the
cross-instance Redis traffic ride the plane's coalesced window frames
(see docs/guides/scalability.md).

Run: python examples/redis_multi.py
"""

import asyncio

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu import Configuration, Server  # noqa: E402
from hocuspocus_tpu.extensions import Redis  # noqa: E402
from hocuspocus_tpu.net.mini_redis import MiniRedis  # noqa: E402
from hocuspocus_tpu.tpu import TpuMergeExtension  # noqa: E402


async def main() -> None:
    redis = await MiniRedis().start()
    server_a = Server(
        Configuration(
            name="instance-a",
            extensions=[
                Redis(port=redis.port, identifier="instance-a"),
                TpuMergeExtension(num_docs=1024, capacity=4096, serve=True),
            ],
        )
    )
    server_b = Server(
        Configuration(
            name="instance-b",
            extensions=[
                Redis(port=redis.port, identifier="instance-b"),
                TpuMergeExtension(num_docs=1024, capacity=4096, serve=True),
            ],
        )
    )
    await server_a.listen(port=8001)
    await server_b.listen(port=8002)
    print("connect clients to ws://127.0.0.1:8001 or ws://127.0.0.1:8002 —")
    print("edits to the same document name sync across both instances")
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
