"""Browser playground: serve examples/browser/index.html + websockets
from ONE port — the repo's answer to the reference playground frontend
(`/root/reference/playground/frontend`, Next.js + Tiptap), with a
dependency-free page speaking the wire protocol directly.

    python examples/browser_demo.py [--port 8000]

then open http://127.0.0.1:8000/ in two browser tabs: text edits sync
live through the server (the TPU merge plane serves supported docs).
The page's protocol path is pinned by
tests/server/test_browser_protocol.py.
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu.server import Configuration, Server  # noqa: E402
from hocuspocus_tpu.tpu import TpuMergeExtension  # noqa: E402

PAGE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "browser", "index.html")


async def serve_page(data) -> None:
    from aiohttp import web

    if data.request.path in ("/", "/index.html"):
        with open(PAGE, "rb") as f:
            body = f.read()
        data["response"] = web.Response(body=body, content_type="text/html")


async def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=8000)
    args = parser.parse_args()
    server = Server(
        Configuration(
            extensions=[
                TpuMergeExtension(
                    num_docs=64, capacity=8192, flush_interval_ms=2, serve=True
                )
            ],
            on_request=serve_page,
        )
    )
    await server.listen(port=args.port)
    print(f"open http://127.0.0.1:{args.port}/ in two tabs")
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
