"""Embedding the collaboration core in a Tornado application.

Same capability as the reference's alternative-host playgrounds
(`playground/backend/src/express.ts` / `koa.ts` / `hono.ts`): any web
framework that hands you a websocket drives the core through
`hocuspocus.handle_connection`. Tornado's handler methods are
callback-style; the generic `CallbackWebSocketTransport` bridges them.

Run: python examples/embed_tornado.py
"""

import asyncio

import tornado.web
import tornado.websocket

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu.server import (  # noqa: E402
    CallbackWebSocketTransport,
    Hocuspocus,
    RequestInfo,
)

hocuspocus = Hocuspocus()


class CollabHandler(tornado.websocket.WebSocketHandler):
    def open(self) -> None:
        async def send(data: bytes) -> None:
            await self.write_message(data, binary=True)

        async def close(code: int, reason: str) -> None:
            super(CollabHandler, self).close(code, reason)

        self.transport = CallbackWebSocketTransport(send, close)
        request_info = RequestInfo(
            headers=dict(self.request.headers), url=self.request.uri or "/"
        )
        self.connection = hocuspocus.handle_connection(
            self.transport, request_info, {"via": "tornado"}
        )

    async def on_message(self, message) -> None:
        if isinstance(message, bytes):
            await self.connection.handle_message(message)

    def on_close(self) -> None:
        self.transport.abort()
        asyncio.ensure_future(
            self.connection.handle_transport_close(self.close_code or 1000, "")
        )


class Index(tornado.web.RequestHandler):
    def get(self) -> None:
        self.write("my app with embedded collaboration at /collab")


async def main() -> None:
    app = tornado.web.Application([(r"/", Index), (r"/collab", CollabHandler)])
    app.listen(8000, address="127.0.0.1")
    print("listening on http://127.0.0.1:8000 (ws at /collab)")
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
