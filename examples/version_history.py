"""Document version history: checkpoint, preview, and restore.

Drives the History extension end-to-end in one process: a writer
minting explicit checkpoints across edits, and a reviewer client
listing versions, previewing an old one (client-side reconstruction
from update bytes), and restoring it — the restore propagates to every
connected client as ordinary edits. (Pass
`History(checkpoint_on_store=True)` to ALSO mint one per debounced
store.)

Run: python examples/version_history.py
"""

import asyncio
import base64
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu import Configuration, Server  # noqa: E402
from hocuspocus_tpu.crdt import Doc, apply_update  # noqa: E402
from hocuspocus_tpu.extensions import History  # noqa: E402
from hocuspocus_tpu.provider import HocuspocusProvider  # noqa: E402


async def wait(predicate, timeout=10.0):
    for _ in range(int(timeout / 0.05)):
        if predicate():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError


async def main() -> None:
    server = Server(Configuration(quiet=True, extensions=[History()]))
    await server.listen(port=0)
    url = server.web_socket_url

    writer = HocuspocusProvider(name="article", url=url)
    reviewer = HocuspocusProvider(name="article", url=url)
    events: list = []
    reviewer.on("stateless", lambda d: events.append(json.loads(d["payload"])))
    await wait(lambda: writer.synced and reviewer.synced)

    def checkpoint(label: str) -> None:
        writer.send_stateless(json.dumps({"action": "history.checkpoint", "label": label}))

    text = writer.document.get_text("body")
    text.insert(0, "Draft: collaborative editing on TPUs.")
    checkpoint("first draft")
    await wait(lambda: any(e.get("event") == "history.checkpointed" for e in events))

    text.delete(0, 6)
    text.insert(0, "Final:")
    text.format(0, 6, {"bold": True})
    checkpoint("final")
    await wait(
        lambda: sum(1 for e in events if e.get("event") == "history.checkpointed") >= 2
    )

    reviewer.send_stateless(json.dumps({"action": "history.list"}))
    await wait(lambda: any(e.get("event") == "history.versions" for e in events))
    versions = next(e for e in events if e["event"] == "history.versions")["versions"]
    print("versions:", [(v["id"], v["label"]) for v in versions])

    first = versions[0]
    reviewer.send_stateless(json.dumps({"action": "history.preview", "id": first["id"]}))
    await wait(lambda: any(e.get("event") == "history.preview" for e in events))
    preview = next(e for e in events if e["event"] == "history.preview")
    pdoc = Doc()
    apply_update(pdoc, base64.b64decode(preview["update"]), "preview")
    print("preview of", first["label"], "->", pdoc.get_text("body").to_string()[:40])

    reviewer.send_stateless(json.dumps({"action": "history.restore", "id": first["id"]}))
    await wait(lambda: writer.document.get_text("body").to_string().startswith("Draft:"))
    print("restored; writer now sees:", writer.document.get_text("body").to_string()[:40])

    writer.destroy()
    reviewer.destroy()
    await server.destroy()


if __name__ == "__main__":
    asyncio.run(main())
