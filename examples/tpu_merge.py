"""Server with the TPU merge plane as the SERVING path.

Documents live on device-resident arenas (one row per sequence — plain
and rich text, ProseMirror trees, arrays; maps host-side): updates from
all documents are integrated in micro-batched kernel steps, SyncStep2
replies are served from device state with storm-batched state-vector
triage, and fan-out rides one merged broadcast per flush. Device steps
run off the event loop; flush shapes pre-compile at listen. Any
degradation falls the affected doc back to the CPU path with no data
loss (see docs/tpu/merge-plane.md and bench.py).

Run: python examples/tpu_merge.py
Multi-chip: pass mesh=hocuspocus_tpu.tpu.sharding.make_mesh() to shard
the arenas over the available devices.
"""

import asyncio

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu import Configuration, Server  # noqa: E402
from hocuspocus_tpu.extensions import Logger  # noqa: E402
from hocuspocus_tpu.tpu import TpuMergeExtension  # noqa: E402


async def main() -> None:
    server = Server(
        Configuration(
            name="tpu-merge",
            extensions=[
                Logger(),
                TpuMergeExtension(num_docs=1024, capacity=4096, flush_interval_ms=5, serve=True),
            ],
        )
    )
    await server.listen(port=8000)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
