"""Server with the TPU batched merge plane enabled.

Every supported text document is mirrored onto device-resident arenas;
updates from all documents are integrated in micro-batched kernel steps
(see docs/tpu/merge-plane.md and bench.py).

Run: python examples/tpu_merge.py
"""

import asyncio

from hocuspocus_tpu import Configuration, Server
from hocuspocus_tpu.extensions import Logger
from hocuspocus_tpu.tpu import TpuMergeExtension


async def main() -> None:
    server = Server(
        Configuration(
            name="tpu-merge",
            extensions=[
                Logger(),
                TpuMergeExtension(num_docs=1024, capacity=4096, flush_interval_ms=5),
            ],
        )
    )
    await server.listen(port=8000)
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
