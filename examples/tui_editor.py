"""Interactive playground: a curses collaborative text editor.

The reference ships a Next.js + Tiptap frontend playground
(`playground/frontend`); this image has no node/npm and zero egress,
so the interactive-editor equivalent is a terminal UI speaking the
same wire protocol through HocuspocusProvider. Run the server first
(examples/default.py or `python -m hocuspocus_tpu.cli --port 8000`),
then open this editor in two terminals and type — keystrokes ride the
CRDT, remote edits appear live, and presence (awareness) shows who
else is in the document.

    python examples/tui_editor.py [ws://127.0.0.1:8000] [doc-name]

Keys: printable characters insert at the cursor; arrows move;
backspace deletes; Ctrl-Q quits.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import asyncio
import curses
import os
import sys


async def editor(stdscr, url: str, doc_name: str) -> None:
    from hocuspocus_tpu.provider import HocuspocusProvider

    curses.curs_set(1)
    stdscr.nodelay(True)
    stdscr.timeout(0)

    provider = HocuspocusProvider(name=doc_name, url=url)
    text = provider.document.get_text("content")
    user = f"tui-{os.getpid()}"
    cursor = 0
    status = "connecting..."

    try:
        while not provider.synced:
            height, width = stdscr.getmaxyx()
            stdscr.erase()
            stdscr.addnstr(0, 0, f"[{doc_name}] {status} (Ctrl-Q quits)", width - 1,
                           curses.A_REVERSE)
            stdscr.refresh()
            if stdscr.getch() == 17:  # Ctrl-Q while connecting
                return
            await asyncio.sleep(0.05)
        provider.set_awareness_field("user", {"name": user})
        status = f"synced as {user} — Ctrl-Q quits"

        while True:
            content = text.to_string()
            cursor = max(0, min(cursor, len(content)))

            # presence line from awareness states
            peers = []
            for client_id, state in provider.awareness.get_states().items():
                peer = (state or {}).get("user")
                name = peer.get("name") if isinstance(peer, dict) else None
                if name and name != user:
                    peers.append(name)
            presence = f"also here: {', '.join(sorted(peers))}" if peers else "alone"

            height, width = stdscr.getmaxyx()
            stdscr.erase()
            stdscr.addnstr(0, 0, f"[{doc_name}] {status} | {presence}", width - 1,
                           curses.A_REVERSE)
            # wrap content into the window body
            body_rows = height - 2
            cols = max(1, width - 1)
            lines = content.split("\n")
            row = 1
            cy, cx = 1, 0
            seen = 0
            for line in lines:
                chunks = [line[i : i + cols] for i in range(0, len(line), cols)] or [""]
                for chunk in chunks:
                    if row <= body_rows:
                        stdscr.addnstr(row, 0, chunk, width - 1)
                        if seen <= cursor <= seen + len(chunk):
                            cy, cx = row, cursor - seen
                    # offset accounting must cover off-screen chunks too,
                    # or the cursor mapping goes stale once the doc grows
                    # past the window
                    seen += len(chunk)
                    row += 1
                seen += 1  # the newline itself
            stdscr.move(min(cy, height - 1), min(cx, width - 1))
            stdscr.refresh()

            # drain pending keys, then yield to the event loop so the
            # websocket keeps pumping
            while True:
                key = stdscr.getch()
                if key == -1:
                    break
                if key == 17:  # Ctrl-Q
                    return
                if key in (curses.KEY_BACKSPACE, 127, 8):
                    if cursor > 0:
                        text.delete(cursor - 1, 1)
                        cursor -= 1
                elif key == curses.KEY_LEFT:
                    cursor = max(0, cursor - 1)
                elif key == curses.KEY_RIGHT:
                    cursor = min(len(text.to_string()), cursor + 1)
                elif key in (curses.KEY_ENTER, 10, 13):
                    text.insert(cursor, "\n")
                    cursor += 1
                elif 32 <= key < 127:
                    text.insert(cursor, chr(key))
                    cursor += 1
            await asyncio.sleep(0.03)
    finally:
        provider.destroy()


def main() -> None:
    url = sys.argv[1] if len(sys.argv) > 1 else "ws://127.0.0.1:8000"
    doc_name = sys.argv[2] if len(sys.argv) > 2 else "playground"
    curses.wrapper(lambda stdscr: asyncio.run(editor(stdscr, url, doc_name)))


if __name__ == "__main__":
    main()
