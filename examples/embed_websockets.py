"""Embedding the collaboration core under the `websockets` library.

Same capability as the reference's alternative-host playgrounds
(`playground/backend/src/express.ts` / `koa.ts` / `hono.ts` /
`deno.ts`): the framework-agnostic core is driven through
`hocuspocus.handle_connection(transport, request_info, context)` —
any server that hands you a websocket works. The generic
`CallbackWebSocketTransport` adapts the library's async send/close.

Run: python examples/embed_websockets.py
"""

import asyncio

import websockets

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hocuspocus_tpu.server import (  # noqa: E402
    CallbackWebSocketTransport,
    Hocuspocus,
    RequestInfo,
)

hocuspocus = Hocuspocus()


async def collab(ws) -> None:
    transport = CallbackWebSocketTransport(
        send_async=ws.send,
        close_async=lambda code, reason: ws.close(code=code, reason=reason),
    )
    request_info = RequestInfo(
        headers=dict(ws.request.headers), url=ws.request.path
    )
    connection = hocuspocus.handle_connection(
        transport, request_info, {"via": "websockets"}
    )
    try:
        async for message in ws:
            if isinstance(message, bytes):
                await connection.handle_message(message)
    finally:
        transport.abort()
        await connection.handle_transport_close(1000, "")


async def main() -> None:
    async with websockets.serve(collab, "127.0.0.1", 8000):
        print("listening on ws://127.0.0.1:8000")
        await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
