#!/usr/bin/env python
"""One-stop bench capture: probe once, run the scenario suite + the
headline bench, and stamp a capture-freshness manifest.

The ONE entry point for producing bench evidence (benchmarks/README.md):

    python tools/bench_capture.py                     # full capture
    python tools/bench_capture.py --no-headline       # scenarios only
    python tools/bench_capture.py --suite smoke       # subset
    python tools/bench_capture.py --allow-stale       # tunnel known dead

What it fixes about the old workflow:

- **One probe.** The backend is probed exactly once here; the result is
  handed to bench.py via the environment (`JAX_PLATFORMS=cpu` when the
  tunnel is dead skips its TPU retry ladder entirely, and bench.py's
  own per-process probe cache covers the rest) — BENCH_r03–r05 paid the
  150 s hung probe four times per round.
- **Scenario evidence.** The loadgen scenario suite runs via the
  documented `python -m hocuspocus_tpu.loadgen` CLI; per-scenario
  SLO verdicts and schedule hashes land in the manifest and in the
  headline artifact's `extra.scenario_suite` (what bench_gate gates on).
- **The gate sees the round.** The headline artifact (with the suite
  verdict folded into `extra.scenario_suite`) is written both under
  `benchmarks/results/` and as repo-root `BENCH_next.json` — the file
  `tools/bench_gate.py`'s newest-two scan picks up.
- **Staleness is first-class.** `benchmarks/results/capture_manifest.json`
  records capture time, backend, git revision and a `stale_capture`
  flag. A stale headline (bench.py re-citing an old on-chip run because
  the tunnel is down) exits 3 unless `--allow-stale` — a stale number
  can never be emitted silently again.

Exit codes: 0 fresh capture + scenario pass; 1 scenario suite failed;
2 the capture itself errored; 3 stale headline without --allow-stale.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RESULTS_DIR = os.path.join(_REPO_DIR, "benchmarks", "results")
MANIFEST_PATH = os.path.join(_RESULTS_DIR, "capture_manifest.json")


def _log(msg: str) -> None:
    print(f"[bench_capture] {msg}", file=sys.stderr, flush=True)


def _probe_codec_path() -> str:
    """native|fallback|unknown: which wire codec this host resolves."""
    try:
        sys.path.insert(0, _REPO_DIR)
        from hocuspocus_tpu.native import get_codec

        return "native" if get_codec() is not None else "fallback"
    except Exception:
        return "unknown"


def _git_rev() -> str:
    try:
        proc = subprocess.run(
            ["git", "-C", _REPO_DIR, "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=10,
        )
        return proc.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def summarize_stale_rounds() -> "str | None":
    """One LOUD line over the repo-root BENCH_*.json trajectory: which
    rounds carry a re-cited (stale_capture) headline. Evidence hygiene
    (ROADMAP 2(b)): a reader scanning the capture log must not mistake
    a re-cited on-chip number for a current-tree measurement."""
    stale_rounds: "list[str]" = []
    total = 0
    for path in sorted(glob.glob(os.path.join(_REPO_DIR, "BENCH_*.json"))):
        try:
            with open(path) as fh:
                data = json.load(fh)
        except Exception:
            continue
        if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
            data = data["parsed"]
        if not isinstance(data, dict):
            continue
        total += 1
        if (data.get("extra") or {}).get("stale_capture"):
            stale_rounds.append(os.path.basename(path))
    if not stale_rounds:
        return None
    return (
        f"!!! STALE HEADLINES: {len(stale_rounds)} of {total} BENCH rounds "
        f"re-cite an old on-chip capture ({', '.join(stale_rounds)}) — "
        "their headline values are NOT current-tree measurements"
    )


def probe_backend() -> dict:
    """Probe the accelerator ONCE (bench.py's cached probe), returning
    {"backend": str|None, "alive": bool, "probe_s": float}."""
    sys.path.insert(0, _REPO_DIR)
    import bench

    started = time.perf_counter()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the CPU probe is cheap and still yields the device count
        # (forced-host meshes report their virtual chip count)
        backend = bench._probe(None) or "cpu"
        device_count = bench.probe_device_count(None)
    else:
        backend = bench._probe(None)
        device_count = bench.probe_device_count(None)
        if backend is None:
            # the retry the JAX init error itself suggests — still just
            # one extra probe, cached for the rest of the process
            backend = bench._probe("")
            device_count = bench.probe_device_count("")
    return {
        "backend": backend,
        "alive": backend not in (None, "cpu"),
        "probe_s": round(time.perf_counter() - started, 1),
        "device_count": device_count,
    }


def run_scenarios(
    names: "list[str]", seed: int, time_scale: float, env: dict
) -> dict:
    """Run each scenario via the documented CLI; collect verdicts."""
    suite: dict = {"seed": seed, "time_scale": time_scale, "scenarios": {}}
    verdict = "pass"
    for name in names:
        _log(f"scenario {name} (seed {seed}) ...")
        artifact_path = os.path.join(
            _RESULTS_DIR,
            f"scenario_{name}_{time.strftime('%Y%m%dT%H%M%SZ', time.gmtime())}.json",
        )
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "hocuspocus_tpu.loadgen",
                    "--scenario",
                    name,
                    "--seed",
                    str(seed),
                    "--time-scale",
                    str(time_scale),
                    "--out",
                    artifact_path,
                ],
                env=env,
                capture_output=True,
                text=True,
                timeout=int(os.environ.get("CAPTURE_SCENARIO_TIMEOUT", 600)),
                cwd=_REPO_DIR,
            )
        except subprocess.TimeoutExpired:
            suite["scenarios"][name] = {"verdict": "error", "error": "timeout"}
            verdict = "fail"
            continue
        result = None
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
        if result is None:
            suite["scenarios"][name] = {
                "verdict": "error",
                "error": f"rc={proc.returncode}",
                "stderr_tail": proc.stderr[-300:],
            }
            verdict = "fail"
            continue
        entry = {
            "verdict": result.get("verdict"),
            "schedule_hash": result.get("schedule_hash"),
            "breached": (result.get("slo") or {}).get("breached_targets", []),
            # per-phase p99s land here so tools/bench_gate.py's suite
            # stages (overload_storm/edge_fanout/multi_device_storm
            # .interactive_p99) gate capture-produced rounds too
            "phase_p99_ms": {
                phase["name"]: phase.get("latency_p99_ms")
                for phase in result.get("phases") or []
                if isinstance(phase, dict) and "name" in phase
            },
            "artifact": os.path.relpath(artifact_path, _REPO_DIR),
        }
        fleet = (result.get("extra") or {}).get("fleet")
        if fleet:
            # fleet federation evidence: the digest peer count proves
            # every role published into the control channel during the
            # run, and the cross-tier p99 feeds the
            # edge_fanout.cross_tier_e2e_p99 gate stage
            entry["fleet"] = {
                "peers": fleet.get("peers"),
                "digests_ingested": fleet.get("digests_ingested"),
                "stale_peers": fleet.get("stale_peers"),
                "cross_tier_e2e_ms": fleet.get("cross_tier_e2e_ms"),
            }
        replica = (result.get("extra") or {}).get("replica")
        if replica:
            # hot-doc replication evidence: per-cell follower counts,
            # the worst observed tick lag and the resync/promotion
            # accounting — "the audience fanned out over N followers
            # without falling behind" is checkable from the manifest
            cells = replica.get("cells") or {}
            followers = {
                cell: sum(
                    len(doc.get("followers") or ())
                    for doc in (stats.get("owned") or {}).values()
                )
                for cell, stats in cells.items()
            }
            lags = [
                doc.get("lag_s")
                for stats in cells.values()
                for doc in (stats.get("following") or {}).values()
                if isinstance(doc.get("lag_s"), (int, float))
            ]
            entry["replica"] = {
                "followers": followers,
                "following_docs": sum(
                    len(stats.get("following") or {}) for stats in cells.values()
                ),
                "max_tick_lag_s": round(max(lags), 3) if lags else None,
                "resyncs": sum(
                    int((stats.get("counters") or {}).get("resyncs", 0))
                    for stats in cells.values()
                ),
                "promotions": sum(
                    int((stats.get("counters") or {}).get("promotions", 0))
                    for stats in cells.values()
                ),
            }
        multi = (result.get("extra") or {}).get("multi_device")
        if multi:
            # multichip attribution: per-device doc/work spread,
            # migration accounting and the placement-map hash — two
            # rounds with equal hashes routed docs identically
            entry["multi_device"] = {
                instance: {
                    "devices": info.get("devices"),
                    "placement_hash": info.get("placement_hash"),
                    "docs_per_device": (info.get("utilization") or {}).get(
                        "docs_per_device"
                    ),
                    "docs_migrated": (info.get("migrations") or {}).get(
                        "docs_migrated"
                    ),
                }
                for instance, info in multi.items()
            }
        wire_sat = (result.get("extra") or {}).get("wire_saturation")
        if wire_sat:
            # headroom evidence (wire_saturation scenario): achieved
            # frames/s per rung, the cost model's sustainable rate and
            # the top-5 attribution — "what the loop thread spends each
            # frame on" is checkable from the manifest alone
            entry["wire_saturation"] = {
                "sustained_frames_per_s": wire_sat.get(
                    "sustained_frames_per_s"
                ),
                "headroom_frames_per_s": wire_sat.get(
                    "headroom_frames_per_s"
                ),
                "headroom_ratio": wire_sat.get("headroom_ratio"),
                "top_costs": wire_sat.get("top_costs"),
            }
        autoscale = (result.get("extra") or {}).get("autoscale")
        if autoscale:
            # elasticity evidence: the steady-trough footprint ratio is
            # the diurnal_autoscale.steady_footprint_ratio gate stage;
            # the per-phase active-cell means + decision/migration
            # accounting make "the fleet breathed with the load" (and
            # scaled back down) checkable from the manifest alone
            controllers = autoscale.get("controllers") or []
            entry["autoscale"] = {
                "fleet_cells": autoscale.get("fleet_cells"),
                "steady_footprint_ratio": autoscale.get(
                    "steady_footprint_ratio"
                ),
                "phase_active_cells": autoscale.get("phase_active_cells"),
                "scale_ups": sum(
                    int(
                        ((c.get("counters") or {}).get("scale_ups", 0))
                    )
                    for c in controllers
                ),
                "scale_downs": sum(
                    int(
                        ((c.get("counters") or {}).get("scale_downs", 0))
                    )
                    for c in controllers
                ),
                "docs_migrated": sum(
                    int(
                        ((c.get("actuation") or {}).get("docs_migrated", 0))
                    )
                    for c in controllers
                ),
            }
        suite["scenarios"][name] = entry
        _log(f"scenario {name}: {result.get('verdict')}")
        if result.get("verdict") != "pass":
            verdict = "fail"
    suite["verdict"] = verdict
    return suite


def run_headline(env: dict, suite: dict) -> "tuple[dict | None, str | None]":
    """Run bench.py; returns (result, artifact_path). The scenario
    suite's verdict is folded into the artifact's extra so bench_gate
    sees it in the same place a plain `python bench.py` round puts it
    (the in-bench suite is skipped — it already ran here)."""
    env = dict(env)
    env["BENCH_SCENARIO"] = "0"  # no double-run inside the inner bench
    _log("headline bench (bench.py) ...")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO_DIR, "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=int(os.environ.get("CAPTURE_HEADLINE_TIMEOUT", 7200)),
            cwd=_REPO_DIR,
        )
    except subprocess.TimeoutExpired:
        return None, None
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            result.setdefault("extra", {})["scenario_suite"] = {
                "verdict": suite["verdict"],
                "seed": suite["seed"],
                "scenarios": suite["scenarios"],
            }
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            path = os.path.join(_RESULTS_DIR, f"bench_capture_{stamp}.json")
            with open(path, "w") as fh:
                json.dump(result, fh, indent=1)
            # ALSO land the round where bench_gate's default scan looks
            # (repo-root BENCH_*.json, newest-by-mtime): without this
            # bridge, a capture-produced round — and its scenario-suite
            # verdict — would be invisible to `python tools/bench_gate.py`
            with open(os.path.join(_REPO_DIR, "BENCH_next.json"), "w") as fh:
                json.dump(result, fh, indent=1)
            return result, path
    return None, None


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        description="Probe once, run scenario suite + headline bench, "
        "stamp a capture-freshness manifest."
    )
    parser.add_argument(
        "--suite",
        default=None,
        help="comma-separated scenario names (default: the bench suite)",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--time-scale", type=float, default=2.0)
    parser.add_argument(
        "--no-headline",
        action="store_true",
        help="skip bench.py (scenario suite + manifest only)",
    )
    parser.add_argument(
        "--allow-stale",
        action="store_true",
        help="exit 0 even when the headline is a stale re-cited capture",
    )
    args = parser.parse_args(argv)

    os.makedirs(_RESULTS_DIR, exist_ok=True)
    probe = probe_backend()
    _log(
        f"backend probe: {probe['backend'] or 'dead'} "
        f"({probe['probe_s']}s, alive={probe['alive']})"
    )

    env = os.environ.copy()
    env.setdefault("PYTHONPATH", _REPO_DIR)
    if not probe["alive"]:
        # dead/absent tunnel: pin every child to CPU so NOTHING
        # downstream re-pays a probe timeout
        env["JAX_PLATFORMS"] = "cpu"

    if args.suite is not None:
        names = [name for name in args.suite.split(",") if name]
    else:
        from hocuspocus_tpu.loadgen.scenarios import BENCH_SUITE

        names = list(BENCH_SUITE)
    suite = run_scenarios(names, args.seed, args.time_scale, env)

    headline = None
    headline_path = None
    if not args.no_headline:
        headline, headline_path = run_headline(env, suite)

    stale = bool(
        headline is not None and (headline.get("extra") or {}).get("stale_capture")
    )
    multi_device = {
        name: entry["multi_device"]
        for name, entry in suite["scenarios"].items()
        if isinstance(entry, dict) and entry.get("multi_device")
    }
    # fleet federation: the digest peer count per edge scenario — a
    # capture whose peer count dropped below the topology size means a
    # role went dark during the round (silent topology drift)
    fleet_peers = {
        name: (entry.get("fleet") or {}).get("peers")
        for name, entry in suite["scenarios"].items()
        if isinstance(entry, dict) and entry.get("fleet")
    }
    # hot-doc replication: per-scenario follower counts + lag evidence
    # (mega_audience lands here) — a capture whose follower count is
    # zero means the watermark never tripped and the fanout p99 was
    # measured against a single-owner topology
    replica_fanout = {
        name: entry["replica"]
        for name, entry in suite["scenarios"].items()
        if isinstance(entry, dict) and entry.get("replica")
    }
    # minimal-work merge evidence: how much of the round actually rode
    # the fast paths — the run-merge append program (fast_path_fraction
    # of integrated ops) and the on-device catch-up pack
    # (device_encode_share of SyncStep2 delete-set reads). A capture
    # whose shares are ~0 measured the classic paths, and its
    # microbatch/cold-sync p99s must be read accordingly.
    merge_path = None
    if headline is not None:
        h_extra = headline.get("extra") or {}
        gov_on = (h_extra.get("mixed_load") or {}).get("governor_on") or {}
        storm = h_extra.get("catchup_storm") or {}
        merge_path = {
            "mixed_load": {
                "fast_path_fraction": gov_on.get("fast_path_fraction"),
                "device_encode_share": gov_on.get("device_encode_share"),
                "microbatch_p99_ms": gov_on.get("microbatch_p99_ms"),
            }
            if gov_on
            else None,
            "catchup_storm": {
                "device_encode_share": storm.get("device_encode_share"),
                "cold_sync_p99_ms": storm.get("cold_sync_p99_ms"),
            }
            if storm
            else None,
        }
        if not any(merge_path.values()):
            merge_path = None
    # wire-saturation headroom evidence: the headline bench's direct-
    # drive ramp (measured saturation + model prediction + top-cost
    # attribution); falls back to the scenario's evidence when the
    # headline was skipped
    wire_saturation = None
    ws = (headline or {}).get("extra", {}).get("wire_saturation")
    if not isinstance(ws, dict) or ws.get("error"):
        ws = (suite["scenarios"].get("wire_saturation") or {}).get(
            "wire_saturation"
        )
    if isinstance(ws, dict) and not ws.get("error"):
        wire_saturation = {
            "frames_per_s": ws.get("frames_per_s")
            or ws.get("sustained_frames_per_s"),
            "headroom_frames_per_s": ws.get("headroom_frames_per_s"),
            "headroom_ratio": ws.get("headroom_ratio"),
            "headroom_within_2x": ws.get("headroom_within_2x"),
            # which codec ran the round: a native-vs-fallback mismatch
            # between rounds makes the frames/s comparison meaningless
            # (pre-codec_path artifacts fall back to a live probe of
            # this host's toolchain — same build the round used)
            "codec_path": ws.get("codec_path") or _probe_codec_path(),
            "top_costs": ws.get("top_costs"),
        }
    manifest = {
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_rev": _git_rev(),
        "backend": (headline or {}).get("extra", {}).get("backend")
        or probe["backend"],
        "probe": probe,
        # per-device attribution: the probe's visible chip count plus
        # each multi-device scenario's placement hash + per-device doc
        # spread — multichip captures are comparable round over round
        "device_count": probe.get("device_count"),
        "multi_device": multi_device or None,
        "fleet_digest_peers": fleet_peers or None,
        "replica_fanout": replica_fanout or None,
        "merge_path": merge_path,
        "wire_saturation": wire_saturation,
        "stale_capture": stale,
        "fresh": bool(headline is not None and not stale),
        "scenario_suite": suite,
        "headline": None
        if headline is None
        else {
            "metric": headline.get("metric"),
            "value": headline.get("value"),
            "unit": headline.get("unit"),
            "artifact": os.path.relpath(headline_path, _REPO_DIR)
            if headline_path
            else None,
        },
    }
    with open(MANIFEST_PATH, "w") as fh:
        json.dump(manifest, fh, indent=1)
    print(json.dumps(manifest))

    stale_line = summarize_stale_rounds()
    if stale_line:
        print(stale_line, file=sys.stderr, flush=True)

    if not args.no_headline and headline is None:
        _log("headline bench FAILED — no artifact produced")
        return 2
    if stale and not args.allow_stale:
        _log(
            "REFUSING silent stale capture: the headline re-cites an old "
            "on-chip run (tunnel down). Re-run with --allow-stale to "
            "accept it explicitly; the manifest records stale_capture=true."
        )
        return 3
    if suite["verdict"] != "pass":
        _log(f"scenario suite verdict: {suite['verdict']}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
