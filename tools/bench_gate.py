#!/usr/bin/env python
"""Latency regression gate over bench rounds.

Compares the newest two `BENCH_*.json` artifacts (or two explicit
files) on their per-stage p99s — `extra.update_e2e.<stage>.p99_ms`,
`extra.wire_load.ingress.p99_ms`,
`extra.fanout_storm.merge_to_last_write_p99_ms`,
`extra.replica_storm.merge_to_remote_broadcast_p99_ms`, the adaptive
scheduler's `extra.mixed_load.governor_on.interactive_p99_ms`
(interactive merge→broadcast under concurrent hydration+compaction
with the lane arbiter + governor on), the minimal-work merge's
`extra.mixed_load.governor_on.microbatch_p99_ms` (per-flush wall time
with the run-merge fast path engaged) and
`extra.catchup_storm.cold_sync_p99_ms` (post-storm cold-joiner
SyncStep2 through the on-device catch-up pack), the overload control
plane's
`extra.scenario_suite.scenarios.overload_storm.phase_p99_ms.storm`
(gated as `overload_storm.interactive_p99`: interactive edit p99 while
the brownout ladder is at RED and shedding), the elastic fleet's
`...scenarios.diurnal_autoscale.phase_p99_ms.peak` (gated as
`diurnal_autoscale.interactive_p99`: peak-phase p99 while the
autoscaler scales the cell fleet under the load) and
`...diurnal_autoscale.autoscale.steady_footprint_ratio` (gated as
`diurnal_autoscale.steady_footprint_ratio`: mean active cells over the
steady trough / static fleet — a fleet that stops scaling back down
regresses this even with latency green), and the durability plane's
`extra.wal_load.append_p99_ms` +
`extra.wal_load.wal_on.merge_to_last_write_p99_ms` — and exits nonzero
when any stage regressed beyond the tolerance. Wired as an OPT-IN CI/verify step
(latency on shared CPU runners is noisy; the gate is for on-chip
rounds and deliberate local runs):

    python tools/bench_gate.py                 # newest two BENCH_*.json
    python tools/bench_gate.py --tolerance 0.5 # allow +50% per stage
    python tools/bench_gate.py --current BENCH_r06.json --previous BENCH_r05.json

Safety rails (exit 0 with a SKIP note, never a false alarm):
- an empty or single-round trajectory ("no prior round — gate skipped"),
- either file unreadable/unparseable,
- the two rounds ran on different backends (a CPU-fallback round must
  not be compared against an on-chip round),
- a stage present in only one round (new stages are informational).

A stage regresses when `current_p99 > previous_p99 * (1 + tolerance) +
floor_ms` — the absolute floor keeps micro-stage jitter (fractions of a
millisecond) from tripping the relative check.

Most stages are latencies (lower is better), but the gate is
direction-aware: throughput stages listed in `HIGHER_IS_BETTER` — the
wire-saturation pass's measured sustained `wire_saturation.frames_per_s`
and the headroom model's predicted
`wire_saturation.headroom_frames_per_s` (docs/guides/observability.md,
"profiling & cost attribution") — regress when the CURRENT value drops
below `previous * (1 - tolerance)`; the ms floor does not apply to
frames/s.

Two checks look at the CURRENT round alone (they don't need a prior
round, so they run even on a fresh trajectory):
- the scenario-suite SLO verdict (`extra.scenario_suite.verdict`, from
  the loadgen burn-rate harness): a `fail`/`error` verdict fails the
  gate — a breached SLO is a regression even when every raw p99 moved
  inside tolerance;
- capture staleness (`extra.stale_capture`): a stale headline is
  reported loudly, and fails the gate under `--fail-stale` (the
  bench_capture workflow's enforcement hook).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# throughput stages: a DROP is the regression. Everything else in
# stage_p99s is a latency (or a ratio gated like one) where growth is.
HIGHER_IS_BETTER = frozenset(
    {
        "wire_saturation.frames_per_s",
        "wire_saturation.sustained_frames_per_s",
        "wire_saturation.headroom_frames_per_s",
    }
)


def stage_unit(stage: str) -> str:
    return "frames/s" if stage in HIGHER_IS_BETTER else "ms"


def _artifact_key(path: str) -> "tuple[float, int, str]":
    """Newest-last ordering by mtime (a fresh `BENCH_next.json` from the
    documented workflow MUST outrank older numbered rounds), tie-broken
    by the BENCH_r<N> round number for same-second writes."""
    match = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    round_no = int(match.group(1)) if match else -1
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0.0
    return (mtime, round_no, path)


def find_artifacts(directory: str) -> "list[str]":
    return sorted(glob.glob(os.path.join(directory, "BENCH_*.json")), key=_artifact_key)


def load_round(path: str) -> "dict | None":
    """Parse one artifact. Artifacts come in two shapes: the bench's
    own JSON line, or the driver's wrapper with the real payload under
    "parsed"."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except Exception:
        return None
    if isinstance(data, dict) and "parsed" in data and isinstance(data["parsed"], dict):
        data = data["parsed"]
    return data if isinstance(data, dict) else None


def stage_p99s(payload: dict) -> "dict[str, float]":
    """Flatten every gated p99 out of one round's extra section."""
    extra = payload.get("extra") or {}
    stages: "dict[str, float]" = {}
    update_e2e = extra.get("update_e2e")
    if isinstance(update_e2e, dict):
        for stage, stats in update_e2e.items():
            if not isinstance(stats, dict):
                continue  # scalar siblings are not stages
            p99 = stats.get("p99_ms")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages[f"update_e2e.{stage}"] = float(p99)
    wire = extra.get("wire_load")
    if isinstance(wire, dict):
        ingress = wire.get("ingress")
        if isinstance(ingress, dict) and isinstance(
            ingress.get("p99_ms"), (int, float)
        ):
            stages["wire_load.ingress"] = float(ingress["p99_ms"])
    fanout = extra.get("fanout_storm")
    if isinstance(fanout, dict):
        p99 = fanout.get("merge_to_last_write_p99_ms")
        if isinstance(p99, (int, float)) and not isinstance(p99, bool):
            stages["fanout_storm.merge_to_last_write"] = float(p99)
    replica = extra.get("replica_storm")
    if isinstance(replica, dict):
        p99 = replica.get("merge_to_remote_broadcast_p99_ms")
        if isinstance(p99, (int, float)) and not isinstance(p99, bool):
            stages["replica_storm.merge_to_remote_broadcast"] = float(p99)
    mixed = extra.get("mixed_load")
    if isinstance(mixed, dict):
        governor_on = mixed.get("governor_on")
        if isinstance(governor_on, dict):
            p99 = governor_on.get("interactive_p99_ms")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["mixed_load.interactive_p99"] = float(p99)
            # per-microbatch flush wall time under the mixed storm: the
            # minimal-work run merge keeps sequential columns off the
            # full-row integrate, so a regression here means the fast
            # path stopped engaging (or got slower than the scan)
            p99 = governor_on.get("microbatch_p99_ms")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["mixed_load.microbatch_p99"] = float(p99)
    storm = extra.get("catchup_storm")
    if isinstance(storm, dict):
        # post-storm cold-joiner SyncStep2 latency: the on-device
        # catch-up pack replaces the host serve-log walk, so a
        # regression here means cold joins fell back to host encodes
        p99 = storm.get("cold_sync_p99_ms")
        if isinstance(p99, (int, float)) and not isinstance(p99, bool):
            stages["catchup_storm.cold_sync_p99"] = float(p99)
    suite = extra.get("scenario_suite")
    if isinstance(suite, dict):
        # shed-mode interactive latency: the overload_storm scenario's
        # storm-phase p99 is measured WHILE the ladder is at RED and
        # shedding — a regression here means brownout mode stopped
        # protecting the interactive path
        storm = (suite.get("scenarios") or {}).get("overload_storm")
        if isinstance(storm, dict):
            p99 = (storm.get("phase_p99_ms") or {}).get("storm")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["overload_storm.interactive_p99"] = float(p99)
        # multi-device interactive latency: the multi_device_storm
        # scenario's storm-phase p99 is measured while one mega-doc
        # skews a chip hot and the rebalancer migrates docs off it —
        # a regression here means hot-doc skew started bleeding into
        # the small-doc interactive path again
        storm_md = (suite.get("scenarios") or {}).get("multi_device_storm")
        if isinstance(storm_md, dict):
            p99 = (storm_md.get("phase_p99_ms") or {}).get("storm")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["multi_device_storm.interactive_p99"] = float(p99)
        # hot-doc fan-out latency: the mega_audience scenario's
        # fanout-phase p99 is measured while a watermark-crossing read
        # audience is spread over follower cells — a regression here
        # means audience growth started bleeding back into the owner's
        # write→observe path (the flat-fan-out promise of
        # docs/guides/hot-doc-replication.md)
        mega = (suite.get("scenarios") or {}).get("mega_audience")
        if isinstance(mega, dict):
            p99 = (mega.get("phase_p99_ms") or {}).get("fanout")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["mega_audience.fanout_p99"] = float(p99)
        # edge-tier interactive latency: the edge_fanout scenario's
        # fanout-phase p99 is measured writer->edge->cell->edge->reader
        # under a door-admitted join storm — a regression here means
        # the split front door stopped being a constant tax
        # elastic-fleet stages (docs/guides/elastic-fleet.md): the
        # diurnal_autoscale peak-phase p99 is measured while the
        # controller scales the cell fleet under it — a regression
        # means elasticity started taxing the interactive path — and
        # the steady-trough footprint ratio (mean active cells during
        # `night` / static fleet, dimensionless but gated through the
        # same relative check) catches a fleet that stopped scaling
        # back down
        diurnal = (suite.get("scenarios") or {}).get("diurnal_autoscale")
        if isinstance(diurnal, dict):
            p99 = (diurnal.get("phase_p99_ms") or {}).get("peak")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["diurnal_autoscale.interactive_p99"] = float(p99)
            autoscale = diurnal.get("autoscale")
            if isinstance(autoscale, dict):
                ratio = autoscale.get("steady_footprint_ratio")
                if isinstance(ratio, (int, float)) and not isinstance(
                    ratio, bool
                ):
                    stages["diurnal_autoscale.steady_footprint_ratio"] = float(
                        ratio
                    )
        edge = (suite.get("scenarios") or {}).get("edge_fanout")
        if isinstance(edge, dict):
            p99 = (edge.get("phase_p99_ms") or {}).get("fanout")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["edge_fanout.interactive_p99"] = float(p99)
            # cross-tier trace latency: the fleet plane's edge→cell→edge
            # e2e p99 (extra.fleet, fed by relay trace propagation) — a
            # regression here means the relay hop or the device close
            # path grew a tail the interactive p99 alone can miss
            fleet = edge.get("fleet")
            if isinstance(fleet, dict):
                cross = fleet.get("cross_tier_e2e_ms")
                if isinstance(cross, dict):
                    p99 = cross.get("p99_ms")
                    if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                        stages["edge_fanout.cross_tier_e2e_p99"] = float(p99)
    wire_sat = extra.get("wire_saturation")
    if isinstance(wire_sat, dict):
        # higher-is-better throughput stages (HIGHER_IS_BETTER): the
        # measured saturation wall of the direct-drive ingress ramp and
        # the cost ledger's predicted sustainable rate — either one
        # dropping means the per-frame host path got more expensive
        for key, stage in (
            ("frames_per_s", "wire_saturation.frames_per_s"),
            ("sustained_frames_per_s", "wire_saturation.sustained_frames_per_s"),
            ("headroom_frames_per_s", "wire_saturation.headroom_frames_per_s"),
        ):
            value = wire_sat.get(key)
            if (
                isinstance(value, (int, float))
                and not isinstance(value, bool)
                and value > 0
            ):
                stages[stage] = float(value)
    wal = extra.get("wal_load")
    if isinstance(wal, dict):
        append_p99 = wal.get("append_p99_ms")
        if isinstance(append_p99, (int, float)) and not isinstance(append_p99, bool):
            stages["wal_load.append"] = float(append_p99)
        wal_on = wal.get("wal_on")
        if isinstance(wal_on, dict):
            p99 = wal_on.get("merge_to_last_write_p99_ms")
            if isinstance(p99, (int, float)) and not isinstance(p99, bool):
                stages["wal_load.merge_to_last_write_wal_on"] = float(p99)
    return stages


def backend_of(payload: dict) -> "str | None":
    extra = payload.get("extra") or {}
    return extra.get("backend")


def current_round_checks(payload: dict, fail_stale: bool) -> "tuple[list[str], list[str]]":
    """Checks on the newest round alone -> (failures, notes)."""
    failures: "list[str]" = []
    notes: "list[str]" = []
    extra = payload.get("extra") or {}
    suite = extra.get("scenario_suite")
    if isinstance(suite, dict):
        verdict = suite.get("verdict")
        scenarios = suite.get("scenarios") or {}
        detail = ", ".join(
            f"{name}={s.get('verdict')}" for name, s in sorted(scenarios.items())
        )
        if verdict == "pass":
            notes.append(f"OK   scenario_suite: pass ({detail})")
        elif verdict in ("fail", "error"):
            breached = [
                f"{name}:{target}"
                for name, s in sorted(scenarios.items())
                for target in (s.get("breached") or [])
            ]
            failures.append(
                f"scenario_suite verdict {verdict!r}"
                + (f" (breached: {', '.join(breached)})" if breached else f" ({detail})")
            )
        else:
            notes.append(f"NOTE scenario_suite: verdict {verdict!r}")
    wire_sat = extra.get("wire_saturation")
    if isinstance(wire_sat, dict) and "headroom_within_2x" in wire_sat:
        ratio = wire_sat.get("headroom_ratio")
        if wire_sat.get("headroom_within_2x"):
            notes.append(
                f"OK   wire_saturation: headroom model within 2x of the "
                f"measured saturation (ratio {ratio})"
            )
        else:
            # informational, not a failure: the 2x band check is owned
            # by the bench pass + tests; shared-runner noise must not
            # turn it into a gate false alarm
            notes.append(
                f"WARN wire_saturation: headroom model OUTSIDE the 2x "
                f"band (ratio {ratio}) — the cost ledger's loop-site "
                "partition may have drifted from the real loop thread"
            )
    if extra.get("stale_capture"):
        note = (
            "STALE capture: headline value is a re-cited on-chip run "
            f"({extra.get('capture_artifact', '?')}, "
            f"mtime {extra.get('capture_mtime_utc', '?')})"
        )
        if fail_stale:
            failures.append(note)
        else:
            notes.append(f"WARN {note}")
    return failures, notes


def compare(
    previous: dict,
    current: dict,
    tolerance: float,
    floor_ms: float,
) -> "tuple[list[str], list[str]]":
    """-> (regressions, notes)."""
    notes: "list[str]" = []
    prev_backend, cur_backend = backend_of(previous), backend_of(current)
    if prev_backend != cur_backend:
        notes.append(
            f"SKIP: backend changed ({prev_backend!r} -> {cur_backend!r}); "
            "cross-backend latencies are not comparable"
        )
        return [], notes
    prev_stages = stage_p99s(previous)
    cur_stages = stage_p99s(current)
    if not prev_stages or not cur_stages:
        notes.append("SKIP: per-stage p99 data missing from one or both rounds")
        return [], notes
    regressions: "list[str]" = []
    for stage in sorted(cur_stages):
        unit = stage_unit(stage)
        if stage not in prev_stages:
            notes.append(f"NEW  {stage}: {cur_stages[stage]:.3f}{unit} (no baseline)")
            continue
        prev, cur = prev_stages[stage], cur_stages[stage]
        verdict = "OK  "
        if stage in HIGHER_IS_BETTER:
            # throughput: the budget is a FLOOR, and the ms slack does
            # not apply — tolerance alone absorbs run-to-run jitter
            budget = prev * (1.0 - tolerance)
            if cur < budget:
                verdict = "FAIL"
                regressions.append(
                    f"{stage}: {prev:.3f}{unit} -> {cur:.3f}{unit} "
                    f"(floor {budget:.3f}{unit} at -{tolerance:.0%})"
                )
        else:
            budget = prev * (1.0 + tolerance) + floor_ms
            if cur > budget:
                verdict = "FAIL"
                regressions.append(
                    f"{stage}: {prev:.3f}{unit} -> {cur:.3f}{unit} "
                    f"(budget {budget:.3f}{unit} at +{tolerance:.0%} +{floor_ms:g}ms)"
                )
        notes.append(
            f"{verdict} {stage}: {prev:.3f}{unit} -> {cur:.3f}{unit}"
            f" ({'+' if cur >= prev else ''}{(cur - prev):.3f})"
        )
    return regressions, notes


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        description="Gate on per-stage p99 regressions between bench rounds."
    )
    parser.add_argument(
        "--tolerance",
        type=float,
        default=float(os.environ.get("BENCH_GATE_TOLERANCE", 0.25)),
        help="allowed relative growth per stage (default 0.25 = +25%%)",
    )
    parser.add_argument(
        "--floor-ms",
        type=float,
        default=float(os.environ.get("BENCH_GATE_FLOOR_MS", 0.25)),
        help="absolute slack added to every budget (default 0.25ms)",
    )
    parser.add_argument("--current", help="explicit current-round artifact")
    parser.add_argument("--previous", help="explicit previous-round artifact")
    parser.add_argument(
        "--dir", default=_REPO_DIR, help="directory holding BENCH_*.json"
    )
    parser.add_argument(
        "--fail-stale",
        action="store_true",
        help="treat a stale_capture headline in the current round as a failure",
    )
    args = parser.parse_args(argv)

    if bool(args.current) != bool(args.previous):
        # a half-pinned comparison would silently fall through to the
        # newest-two scan and gate a pair the user did not ask about
        parser.error("--current and --previous must be given together")
    prev_path: "str | None" = None
    if args.current and args.previous:
        prev_path, cur_path = args.previous, args.current
    else:
        artifacts = find_artifacts(args.dir)
        if not artifacts:
            # an empty trajectory is a fresh start, not an error — but
            # say so explicitly rather than silently passing
            print(f"no prior round — gate skipped (no BENCH_*.json under {args.dir})")
            return 0
        cur_path = artifacts[-1]
        if len(artifacts) >= 2:
            prev_path = artifacts[-2]

    current = load_round(cur_path)
    if current is None:
        print(f"SKIP: could not parse {os.path.basename(cur_path)}")
        return 0

    # current-round checks run regardless of trajectory depth: the
    # scenario-suite SLO verdict and capture staleness are properties of
    # THIS round, not a comparison
    failures, cur_notes = current_round_checks(current, args.fail_stale)

    if prev_path is None:
        print(
            f"bench_gate: {os.path.basename(cur_path)} "
            "(no prior round — pairwise p99 gate skipped)"
        )
        notes = cur_notes
        regressions: "list[str]" = []
    else:
        previous = load_round(prev_path)
        if previous is None:
            print(
                f"bench_gate: {os.path.basename(cur_path)} "
                f"(previous round unreadable — pairwise p99 gate skipped)"
            )
            notes = cur_notes
            regressions = []
        else:
            print(
                f"bench_gate: {os.path.basename(prev_path)} -> "
                f"{os.path.basename(cur_path)}"
            )
            regressions, notes = compare(
                previous, current, args.tolerance, args.floor_ms
            )
            notes = notes + cur_notes
    for note in notes:
        print(f"  {note}")
    problems = regressions + failures
    if problems:
        print(f"REGRESSION: {len(problems)} check(s) failed")
        for problem in problems:
            print(f"  FAIL {problem}")
        return 1
    print("PASS: no stage regressed beyond tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
