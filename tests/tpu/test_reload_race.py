"""Unload/reload lifecycle races against the plane registration.

The unload teardown runs asynchronously (it serializes with executor-
side flushes), so a client can re-load a document while the hooks are
still in flight. These tests pin the three outcomes:

- a rejoin racing the unload keeps the (reused) plane registration
  serving — a late release must not silently detach the new
  incarnation to the CPU path for the rest of its life;
- when no rejoin happens, EVERYTHING drains: plane rows, queues,
  logs, serving caches (the cold-sync cache holds a strong ref to the
  PlaneDoc, so a missed eviction is an unbounded leak under doc-name
  churn);
- a rejoin whose load FAILS still drains (a failed load never enters
  the document registry, so no further after_unload ever fires for
  that name — the teardown must clean up on the failed load's behalf).

Reference lifecycle being mirrored: unload on last disconnect +
onLoadDocument failure closing connections
(`packages/server/src/Hocuspocus.ts:206-235,373-377,489-505`).
"""

import asyncio

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, wait_synced


async def _wait(cond, timeout: float = 10.0) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while not cond():
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError
        await asyncio.sleep(0.01)


async def test_rejoin_racing_unload_keeps_plane_serving():
    """Disconnect-all then immediately rejoin: the doc must still be
    plane-served (sync_serves advances, registration intact)."""
    ext = TpuMergeExtension(num_docs=8, capacity=2048, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(
        extensions=[ext], unload_immediately=False, debounce=30, max_debounce=60
    )
    provider = new_provider(server, name="racer")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "survives the race")
        await asyncio.sleep(0.2)
        provider.destroy()
        # rejoin as fast as possible while unload hooks are in flight
        await _wait(lambda: "racer" not in server.documents)
        rejoin = new_provider(server, name="racer")
        await wait_synced(rejoin)
        assert rejoin.document.get_text("t").to_string() == "survives the race"
        # settle any late teardown, then prove the plane still serves
        await asyncio.sleep(0.3)
        assert ext.plane.is_supported("racer"), dict(ext.plane.counters)
        assert "racer" in ext._docs
        before = ext.plane.counters["sync_serves"]
        third = new_provider(server, name="racer")
        await wait_synced(third)
        assert ext.plane.counters["sync_serves"] > before
        third.destroy()
        rejoin.destroy()
    finally:
        provider.destroy()
        await server.destroy()


async def test_unload_drains_plane_rows_and_serving_caches():
    ext = TpuMergeExtension(num_docs=8, capacity=2048, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(
        extensions=[ext], unload_immediately=False, debounce=30, max_debounce=60
    )
    writer = new_provider(server, name="transient")
    try:
        await wait_synced(writer)
        writer.document.get_text("t").insert(0, "short-lived")
        await asyncio.sleep(0.2)
        # cold joiner populates the cold-sync byte cache
        joiner = new_provider(server, name="transient")
        await wait_synced(joiner)
        assert "transient" in ext.serving._sync_cache
        writer.destroy()
        joiner.destroy()
        await _wait(
            lambda: not ext.plane.docs and not ext.serving._sync_cache
        )
        assert len(ext.plane.free) == 8
        assert not ext.plane.queues and not ext.plane.unit_logs
        assert not ext.serving._tombstone_cache
        assert "transient" not in ext.serving.broadcast_cursor
    finally:
        await server.destroy()


async def test_failed_reload_during_unload_still_drains():
    """Rejoin races the unload but its load hook FAILS: the teardown
    must still run (no later after_unload will) — no leaked rows."""
    ext = TpuMergeExtension(num_docs=8, capacity=2048, flush_interval_ms=1, serve=True)
    fail = {"on": False}

    async def on_load_document(data):
        if fail["on"]:
            raise RuntimeError("persistence down")

    server = await new_hocuspocus(
        extensions=[ext],
        unload_immediately=False,
        debounce=30,
        max_debounce=60,
        on_load_document=on_load_document,
    )
    provider = new_provider(server, name="doomed")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        await asyncio.sleep(0.15)
        fail["on"] = True
        provider.destroy()
        await _wait(lambda: "doomed" not in server.documents)
        # the racing rejoin's load fails (connection just closes)
        rejoin = new_provider(server, name="doomed")
        await asyncio.sleep(0.5)
        rejoin.destroy()
        await _wait(lambda: not ext.plane.docs, 10)
        assert len(ext.plane.free) == 8
        assert not ext.serving._sync_cache
    finally:
        fail["on"] = False
        provider.destroy()
        await server.destroy()
