"""TPU merge plane as the SERVING path (serve=True).

Proves the promotion from shadow mirror to serving substrate:
- a fresh client's SyncStep2 reply is produced from device state (the
  CPU encode path is poisoned for the test, so success is proof);
- steady-state broadcasts are batched per device flush, not per update;
- degradation (unsupported content, forced desync) falls back to the
  CPU path without losing data, and is counted.

Reference behavior being replaced: readSyncStep1 reply + per-update
broadcast in `packages/server/src/MessageReceiver.ts:137-213` and
`packages/server/src/Document.ts:228-240`.
"""

import asyncio

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_sync_reply_served_from_device_state(monkeypatch):
    """A late joiner syncs entirely from plane state: the CPU SyncStep2
    encoder is poisoned, so a successful sync proves device serving."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="served")
    try:
        await wait_synced(provider_a)
        provider_a.document.get_text("body").insert(0, "from the device")
        await retryable_assertion(
            lambda: _assert(ext.plane.text("served") == "from the device")
        )

        # poison the CPU fallback: if the server builds SyncStep2 from the
        # CPU document, the late joiner can never sync
        import hocuspocus_tpu.server.message_receiver as mr

        def poisoned(encoder, doc, sv=None):
            raise AssertionError("CPU write_sync_step2 used for a plane-served doc")

        monkeypatch.setattr(mr, "write_sync_step2", poisoned)

        provider_b = new_provider(server, name="served")
        await wait_synced(provider_b)
        assert provider_b.document.get_text("body").to_string() == "from the device"
        assert ext.plane.counters["sync_serves"] >= 1
        provider_b.destroy()
    finally:
        provider_a.destroy()
        await server.destroy()


async def test_broadcast_is_batched_through_coalescing_window():
    """With a long broadcast window, edits reach peers only when the
    window closes — proof the per-update CPU fan-out was suppressed and
    replaced by the plane's merged (coalesced) broadcast. The device
    flush runs on its own cadence and does not gate delivery."""
    ext = TpuMergeExtension(
        num_docs=8,
        capacity=1024,
        flush_interval_ms=1,
        broadcast_interval_ms=1500,
        serve=True,
    )
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="batched")
    provider_b = new_provider(server, name="batched")
    try:
        await wait_synced(provider_a, provider_b)
        text_b = provider_b.document.get_text("body")
        # primer: the FIRST edit after idle broadcasts on the next tick
        # (idle fast path); the window applies under sustained traffic
        provider_a.document.get_text("body").insert(0, "now:")
        await retryable_assertion(lambda: _assert(text_b.to_string() == "now:"))
        provider_a.document.get_text("body").insert(4, "deferred")
        # the update reaches the server well before the 1.5 s window
        # closes, and must NOT have been fan-out broadcast immediately
        # (generous margins so a loaded CI host can't blur the two paths)
        await asyncio.sleep(0.3)
        assert text_b.to_string() == "now:"
        await retryable_assertion(
            lambda: _assert(text_b.to_string() == "now:deferred")
        )
        assert ext.plane.counters["plane_broadcasts"] >= 2
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_broadcast_latency_independent_of_device_flush_time(monkeypatch):
    """The whole point of the optimistic host-log broadcast: a slow
    device step (remote-attached chips pay ~a full RTT per transfer)
    must not sit on the edit->observe path. The integrate step is
    slowed to 300ms; edits must still reach peers in well under that."""
    import time as _time

    import hocuspocus_tpu.tpu.merge_plane as mp

    real_flush = mp.MergePlane._flush_locked

    def slow_flush(self, max_batches=None):
        _time.sleep(0.3)  # runs in the executor, like a real device RTT
        return real_flush(self, max_batches)

    monkeypatch.setattr(mp.MergePlane, "_flush_locked", slow_flush)
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="fastpath")
    provider_b = new_provider(server, name="fastpath")
    try:
        await wait_synced(provider_a, provider_b)
        text_b = provider_b.document.get_text("body")
        latencies = []
        expected = ""
        for i in range(5):
            token = f"e{i};"
            expected += token
            t0 = _time.perf_counter()
            provider_a.document.get_text("body").insert(
                len(expected) - len(token), token
            )
            await retryable_assertion(
                lambda: _assert(text_b.to_string() == expected)
            )
            latencies.append(_time.perf_counter() - t0)
        # each edit beats a single slowed flush cycle by a wide margin
        assert sorted(latencies)[len(latencies) // 2] < 0.25, latencies
        assert ext.plane.counters["cpu_fallbacks"] == 0
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_read_only_connection_with_serve_mode():
    """Read-only rejection composes with plane serving: the read-only
    client's writes are refused (SyncStatus false, nothing applied or
    broadcast), while it still RECEIVES plane broadcasts and syncs
    from device state. Mirrors the reference's read-only path
    (`MessageReceiver.ts:157-179`) on the serve plane."""

    async def on_authenticate(data):
        if data.token == "viewer":
            data.connection_config.read_only = True

    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext], on_authenticate=on_authenticate)
    writer = new_provider(server, name="ro", token="editor")
    viewer = new_provider(server, name="ro", token="viewer")
    try:
        await wait_synced(writer, viewer)
        writer.document.get_text("t").insert(0, "from the writer")
        await retryable_assertion(
            lambda: _assert(viewer.document.get_text("t").to_string() == "from the writer")
        )
        # the viewer's write must not reach the writer or the server doc
        viewer.document.get_text("t").insert(0, "REJECTED ")
        await asyncio.sleep(0.3)
        assert writer.document.get_text("t").to_string() == "from the writer"
        assert server.documents["ro"].get_text("t").to_string() == "from the writer"
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert "ro" in ext._docs  # still plane-served
        # the rejection must not wedge the viewer's subscription: it
        # still observes writer edits via plane broadcasts afterwards
        # (the viewer's LOCAL doc legitimately keeps its own rejected
        # edit — read-only is server-side refusal, not local undo)
        writer.document.get_text("t").insert(0, "still flowing: ")
        await retryable_assertion(
            lambda: _assert(
                "still flowing: " in viewer.document.get_text("t").to_string()
            )
        )
        assert (
            server.documents["ro"].get_text("t").to_string()
            == "still flowing: from the writer"
        )
    finally:
        writer.destroy()
        viewer.destroy()
        await server.destroy()


async def test_direct_connection_edits_ride_the_plane():
    """Server-side edits (openDirectConnection.transact) on a
    plane-served doc broadcast through the plane like client edits —
    the reference's embedded-editing path (`DirectConnection.ts:24`)
    composed with serve mode."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider = new_provider(server, name="direct")
    direct = None
    try:
        await wait_synced(provider)
        direct = await server.hocuspocus.open_direct_connection("direct")
        await direct.transact(
            lambda doc: doc.get_text("t").insert(0, "from the server")
        )
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text("t").to_string() == "from the server"
            )
        )
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert ext.plane.counters["plane_broadcasts"] >= 1
        assert "direct" in ext._docs
    finally:
        if direct is not None:
            await direct.disconnect()  # idempotent
        provider.destroy()
        await server.destroy()


async def test_concurrent_edits_converge_through_plane():
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="conv")
    provider_b = new_provider(server, name="conv")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("body").insert(0, "alpha ")
        provider_b.document.get_text("body").insert(0, "beta ")

        def converged():
            a = provider_a.document.get_text("body").to_string()
            b = provider_b.document.get_text("body").to_string()
            cpu = server.documents["conv"].get_text("body").to_string()
            assert a == b == cpu and len(cpu) == 11

        await retryable_assertion(converged)
        # deletes flow through the plane's device tombstones
        provider_a.document.get_text("body").delete(0, 5)

        def deleted():
            a = provider_a.document.get_text("body").to_string()
            b = provider_b.document.get_text("body").to_string()
            assert a == b and len(a) == 6

        await retryable_assertion(deleted)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_map_content_served_from_plane():
    """Map edits are host-side LWW records on the plane (round-2 verdict:
    BASELINE config-4 shapes must not retire) — the doc STAYS served,
    broadcasts ride the plane, and late joiners sync from it."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="mapdoc")
    provider_b = new_provider(server, name="mapdoc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_map("m").set("k", "v")
        await retryable_assertion(
            lambda: _assert(provider_b.document.get_map("m").get("k") == "v")
        )
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert "mapdoc" in ext._docs  # serving still attached
        # the first map op demotes the doc off the native text lane (it
        # rides the per-update CPU fan-out while the in-place rebuild
        # runs); once rebuilt on the Python plane path, map traffic
        # broadcasts through plane windows again
        await retryable_assertion(
            lambda: _assert(
                (doc := ext.plane.docs.get("mapdoc")) is not None
                and not doc.retired
            )
        )
        # LWW overwrite + a second key keep flowing through the plane
        provider_b.document.get_map("m").set("k", "v2")
        provider_b.document.get_map("m").set("k2", "w")
        await retryable_assertion(
            lambda: _assert(
                provider_a.document.get_map("m").get("k") == "v2"
                and provider_a.document.get_map("m").get("k2") == "w"
            )
        )
        # a map-tombstone-ONLY update (key deletion, no inserts) must
        # still dirty the doc and broadcast through the plane — the
        # deletion's serve-log record is the whole payload
        provider_a.document.get_map("m").delete("k2")
        await retryable_assertion(
            lambda: _assert(provider_b.document.get_map("m").get("k2") is None)
        )
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert "mapdoc" in ext._docs
        # late joiner syncs from the plane
        serves_before = ext.plane.counters["sync_serves"]
        provider_c = new_provider(server, name="mapdoc")
        await wait_synced(provider_c)
        assert provider_c.document.get_map("m").get("k") == "v2"
        assert provider_c.document.get_map("m").get("k2") is None
        assert ext.plane.counters["sync_serves"] > serves_before
        provider_c.destroy()
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_forced_desync_detected_and_recovered():
    """Forcibly desync the host char log from the device arena: the next
    flush detects it, retires the doc (counted), ships the full CPU
    state so receivers stay whole, and serving detaches."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="desynced")
    provider_b = new_provider(server, name="desynced")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("body").insert(0, "healthy")
        await retryable_assertion(
            lambda: _assert(provider_b.document.get_text("body").to_string() == "healthy")
        )
        # corrupt: the host dispatch tally claims a unit the device
        # never integrated (the shape of a device-side op rejection —
        # the next flush's validated snapshot adopts the lie and the
        # health check sees device length != validated units)
        (slot,) = ext.plane.docs["desynced"].seqs.values()
        ext.plane.dispatched_units[slot] += 1

        provider_a.document.get_text("body").insert(7, " again")

        def recovered():
            assert ext.plane.counters["docs_retired_desync"] == 1
            assert ext.plane.counters["cpu_fallbacks"] == 1
            assert "desynced" not in ext._docs
            assert provider_b.document.get_text("body").to_string() == "healthy again"

        await retryable_assertion(recovered)
        # steady state continues via CPU
        provider_b.document.get_text("body").insert(0, ">> ")
        await retryable_assertion(
            lambda: _assert(
                provider_a.document.get_text("body").to_string() == ">> healthy again"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_device_fault_between_capture_and_flush_loses_nothing():
    """Kill the device step AFTER updates were captured for plane
    broadcast (CPU fan-out suppressed) but BEFORE the flush integrates
    them: the extension must degrade every served doc to the CPU path
    with a full-state broadcast so no captured update is ever lost
    (round-2 verdict item 8 — merge_plane claims this; only the
    desync/unsupported degradations were tested)."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="faulty")
    provider_b = new_provider(server, name="faulty")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("body").insert(0, "before fault")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("body").to_string() == "before fault"
            )
        )

        # arm the fault: the NEXT device flush dies (transient Mosaic /
        # runtime failure), after try_capture has already claimed the
        # in-flight update for plane broadcast
        real_flush = ext.plane.flush
        fired = {"n": 0}

        def dying_flush(max_batches=None):
            fired["n"] += 1
            raise RuntimeError("simulated device fault mid-flush")

        ext.plane.flush = dying_flush
        provider_a.document.get_text("body").insert(12, " + captured edit")

        def degraded_whole():
            assert fired["n"] >= 1
            assert ext.plane.counters["cpu_fallbacks"] == 1
            assert ext.plane.counters["docs_retired_fallback"] == 1
            assert "faulty" not in ext._docs  # serving detached
            # the captured-but-never-flushed edit reached the peer via
            # the full-state CPU fallback broadcast
            assert (
                provider_b.document.get_text("body").to_string()
                == "before fault + captured edit"
            )

        await retryable_assertion(degraded_whole)
        ext.plane.flush = real_flush

        # steady state continues on the CPU path in both directions
        provider_b.document.get_text("body").insert(0, "b: ")
        await retryable_assertion(
            lambda: _assert(
                provider_a.document.get_text("body").to_string()
                == "b: before fault + captured edit"
            )
        )
        # and a late joiner syncs the complete doc via CPU
        provider_c = new_provider(server, name="faulty")
        await wait_synced(provider_c)
        assert (
            provider_c.document.get_text("body").to_string()
            == "b: before fault + captured edit"
        )
        provider_c.destroy()
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_catchup_storm_batches_sync_triage_on_device():
    """Concurrent reconnect SyncStep1s must share state_vector_diff
    kernel calls (round-2 verdict item 6: the storm triage runs on
    device, batched across docs — not one host diff per reconnect)."""
    import hocuspocus_tpu.tpu.kernels as kernels_mod

    ext = TpuMergeExtension(num_docs=32, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    num_docs, joiners_per_doc = 4, 4
    seeders = [new_provider(server, name=f"storm-{d}") for d in range(num_docs)]
    try:
        await wait_synced(*seeders)
        for d, p in enumerate(seeders):
            p.document.get_text("body").insert(0, f"doc {d} content before the storm")
        await retryable_assertion(
            lambda: _assert(ext.plane.counters["plane_broadcasts"] >= 1)
        )

        calls = {"n": 0}
        real_diff = kernels_mod.state_vector_diff

        def counted(a, b):
            calls["n"] += 1
            return real_diff(a, b)

        kernels_mod.state_vector_diff = counted
        try:
            serves_before = ext.plane.counters["sync_serves"]
            storm = [
                new_provider(server, name=f"storm-{d}")
                for d in range(num_docs)
                for _ in range(joiners_per_doc)
            ]
            await wait_synced(*storm)
            for d in range(num_docs):
                for j in range(joiners_per_doc):
                    assert (
                        storm[d * joiners_per_doc + j]
                        .document.get_text("body")
                        .to_string()
                        == f"doc {d} content before the storm"
                    )
            served = ext.plane.counters["sync_serves"] - serves_before
            assert served >= num_docs * joiners_per_doc
            assert calls["n"] >= 1  # the device triage actually ran
            # batching: strictly fewer kernel calls than reconnects
            assert calls["n"] < num_docs * joiners_per_doc, calls
            for p in storm:
                p.destroy()
        finally:
            kernels_mod.state_vector_diff = real_diff
    finally:
        for p in seeders:
            p.destroy()
        await server.destroy()


async def test_serve_mode_survives_doc_churn_under_load():
    """Load/unload churn concurrent with edits and executor-side
    flushes: the new off-loop flush pipeline must never crash a flush
    on registry mutation (queues dict changing mid-iteration degrades
    EVERY served doc) nor lose an edit. Stresses the flush-lock
    serialization added with the executor move."""
    ext = TpuMergeExtension(num_docs=32, capacity=512, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    stable_a = new_provider(server, name="stable")
    stable_b = new_provider(server, name="stable")
    try:
        await wait_synced(stable_a, stable_b)
        text = stable_a.document.get_text("body")
        expect = []
        for wave in range(6):
            # churn: short-lived docs load, edit once, unload — while
            # the stable doc keeps editing through the plane
            churners = [
                new_provider(server, name=f"churn-{wave}-{i}") for i in range(4)
            ]
            token = f"w{wave};"
            expect.append(token)
            text.insert(len(text.to_string()), token)
            await wait_synced(*churners)
            for i, p in enumerate(churners):
                p.document.get_text("t").insert(0, f"c{wave}-{i}")
            # edits must actually be in the pipeline before destroy, or
            # the unload races nothing and the test goes vacuous
            def _known(name):
                doc = ext.plane.docs.get(name)
                if doc is None:
                    return False
                ext.plane.materialize_lane(doc)  # lane docs keep known in C++
                return bool(doc.lowerer.known)

            await retryable_assertion(
                lambda: _assert(
                    sum(_known(f"churn-{wave}-{i}") for i in range(4)) == 4
                )
            )
            for p in churners:
                p.destroy()  # triggers unloads racing in-flight flushes

        def converged():
            assert stable_b.document.get_text("body").to_string() == "".join(expect)

        await retryable_assertion(converged)
        # the stable doc must still be plane-served: churn never
        # triggered the degrade-all path
        assert "stable" in ext._docs, {
            k: v for k, v in ext.plane.counters.items() if v
        }
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert ext.plane.counters["docs_retired_desync"] == 0
    finally:
        stable_a.destroy()
        stable_b.destroy()
        await server.destroy()


async def test_stale_cutoff_mid_surrogate_pair_widens_by_one_unit():
    """A stale client whose per-client cutoff lands between the two
    UTF-16 units of a surrogate pair must NOT be served a payload whose
    first unit is a lone low surrogate (units_to_text would bake U+FFFD
    into the wire while the CPU document holds the real pair). The serve
    widens the cutoff by one unit — re-sending the already-known high
    surrogate, which struct integration skips — so the plane-served
    bytes stay faithful. Mirrors yjs ContentString surrogate handling
    (reference peer dep yjs ^13.6.8)."""
    from hocuspocus_tpu.crdt import Doc, encode_state_as_update
    from hocuspocus_tpu.tpu.merge_plane import MergePlane
    from hocuspocus_tpu.tpu.serving import PlaneServing

    source = Doc()
    source.client_id = 9
    text = source.get_text("t")
    text.insert(0, "ab\U0001f600cd")  # units: a b D83D DE00 c d

    plane = MergePlane(num_docs=4, capacity=256)
    serving = PlaneServing(plane)
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]

    # cutoff 3 = between the high (clock 2) and low (clock 3) surrogate
    served_mid_pair = serving._encode_from_sm(doc, {9: 3})
    served_widened = serving._encode_from_sm(doc, {9: 2})
    assert served_mid_pair == served_widened
    assert "�".encode("utf-8") not in served_mid_pair

    # a cutoff at a clean boundary is untouched
    served_clean = serving._encode_from_sm(doc, {9: 4})
    assert served_clean != served_widened
    assert "\U0001f600".encode("utf-8") not in served_clean

    # pair split across TWO serve-log records: the unit AT the cutoff
    # and the one BEFORE it live in different records and must still
    # resolve as a pair. Unreachable from yjs-compatible wire bytes
    # (ContentString.splice and TextEncoder both U+FFFD mid-pair
    # splits), so exercised synthetically at the helper level as
    # defense in depth.
    from hocuspocus_tpu.tpu.lowering import DenseOp
    from hocuspocus_tpu.tpu.merge_plane import LogRec
    from hocuspocus_tpu.tpu.kernels import KIND_INSERT

    plane.unit_logs[7] = [0x61, 0x62, 0xD83D, 0xDE00, 0x63, 0x64]
    records = [
        LogRec(
            op=DenseOp(kind=KIND_INSERT, client=9, clock=0, run_len=3),
            slot=7,
            unit_off=0,
        ),
        LogRec(
            op=DenseOp(kind=KIND_INSERT, client=9, clock=3, run_len=3),
            slot=7,
            unit_off=3,
        ),
    ]
    sm = {9: 3}  # boundary of the second record = the low half
    serving._widen_surrogate_cutoffs(records, sm)
    assert sm == {9: 2}
    sm = {9: 4}  # clean boundary inside the second record: untouched
    serving._widen_surrogate_cutoffs(records, sm)
    assert sm == {9: 4}


async def test_filter_healthy_vectorized_matches_per_doc_semantics():
    """The batched drain's fast health path must flag exactly what
    check_doc_health would: healthy current rows fast-OK, a forced
    desync lands in needs_check (and doc_healthy then retires it),
    stale-generation rows fast-OK (snapshot predates the binding)."""
    from hocuspocus_tpu.crdt import Doc, encode_state_as_update
    from hocuspocus_tpu.tpu.merge_plane import MergePlane
    from hocuspocus_tpu.tpu.serving import PlaneServing

    plane = MergePlane(num_docs=8, capacity=256)
    serving = PlaneServing(plane)
    names = [f"d{i}" for i in range(4)]
    for i, name in enumerate(names):
        src = Doc()
        src.client_id = 100 + i
        src.get_text("t").insert(0, f"content {i}")
        plane.register(name)
        plane.enqueue_update(name, encode_state_as_update(src))
    plane.flush()
    serving.refresh()

    fast_ok, needs_check = serving.filter_healthy(names)
    assert sorted(fast_ok) == sorted(names)
    assert needs_check == []

    # force a desync on one doc: validated tally drifts from the row
    bad_slot = plane.docs["d1"].seqs[("root", "t")]
    plane.validated_units[bad_slot] += 5
    serving.refresh()
    fast_ok, needs_check = serving.filter_healthy(names)
    assert "d1" in needs_check and "d1" not in fast_ok
    assert sorted(fast_ok + needs_check) == sorted(names)
    assert serving.doc_healthy("d1") is None  # retires via the full path
    assert plane.docs["d1"].retire_reason == "desync"

    # stale generation: bump a slot's binding gen after the snapshot —
    # the cached row describes the previous tenant, so it fast-OKs
    slot2 = plane.docs["d2"].seqs[("root", "t")]
    plane.slot_gen[slot2] += 1
    fast_ok, needs_check = serving.filter_healthy(["d2"])
    assert fast_ok == ["d2"]
