"""TPU merge plane correctness: device kernel vs CPU CRDT reference.

Runs on the virtual CPU backend (conftest forces JAX_PLATFORMS=cpu with
8 devices); the same code paths run on real TPU in bench.py.
"""

import random

import numpy as np

from hocuspocus_tpu.crdt import Doc, encode_state_as_update
from hocuspocus_tpu.tpu.kernels import make_empty_state
from hocuspocus_tpu.tpu.merge_plane import MergePlane


def mirror_doc_updates(plane: MergePlane, name: str, doc: Doc):
    """Wire a CPU doc's update events into the plane (as the extension does)."""
    plane.register(name)
    doc.on("update", lambda update, *rest: plane.enqueue_update(name, update))


def test_single_doc_insert_matches_cpu():
    plane = MergePlane(num_docs=4, capacity=256)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    text = doc.get_text("t")
    text.insert(0, "hello")
    text.insert(5, " world")
    text.insert(5, ",")
    plane.flush()
    assert plane.text("d") == text.to_string() == "hello, world"


def test_delete_matches_cpu():
    plane = MergePlane(num_docs=4, capacity=256)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    text = doc.get_text("t")
    text.insert(0, "hello world")
    text.delete(2, 5)
    plane.flush()
    assert plane.text("d") == text.to_string()


def test_concurrent_edits_converge_on_device():
    """Two CPU docs edit concurrently; device mirrors the merged doc."""
    plane = MergePlane(num_docs=4, capacity=512)
    a, b = Doc(), Doc()
    from hocuspocus_tpu.crdt import apply_update

    a.get_text("t").insert(0, "base")
    apply_update(b, encode_state_as_update(a))
    # concurrent same-position inserts (conflict resolution on device)
    a.get_text("t").insert(4, "-AA")
    b.get_text("t").insert(4, "-BB")
    merged = Doc()
    mirror_doc_updates(plane, "d", merged)
    apply_update(merged, encode_state_as_update(a))
    apply_update(merged, encode_state_as_update(b))
    plane.flush()
    assert plane.text("d") == merged.get_text("t").to_string()


def test_many_docs_batched():
    plane = MergePlane(num_docs=16, capacity=256)
    docs = {}
    for i in range(10):
        doc = Doc()
        name = f"doc-{i}"
        mirror_doc_updates(plane, name, doc)
        docs[name] = doc
        doc.get_text("t").insert(0, f"content {i}")
    plane.flush()
    for name, doc in docs.items():
        assert plane.text(name) == doc.get_text("t").to_string()


def test_fuzz_random_edits_match_cpu():
    random.seed(7)
    plane = MergePlane(num_docs=4, capacity=2048)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    text = doc.get_text("t")
    alphabet = "abcdefghij😀é"
    for _ in range(120):
        if random.random() < 0.7 or len(text) == 0:
            pos = random.randint(0, len(text))
            text.insert(pos, random.choice(alphabet) * random.randint(1, 20))
        else:
            pos = random.randrange(len(text))
            text.delete(pos, min(random.randint(1, 8), len(text) - pos))
        if random.random() < 0.2:
            plane.flush()
    plane.flush()
    assert plane.text("d") == text.to_string()


def test_fuzz_concurrent_multi_client_matches_cpu():
    random.seed(13)
    from hocuspocus_tpu.crdt import apply_update

    docs = [Doc() for _ in range(3)]
    queues = {i: [] for i in range(3)}
    for i, d in enumerate(docs):
        d.on(
            "update",
            lambda update, origin, dd, tr, i=i: [
                queues[j].append(update) for j in range(3) if j != i
            ],
        )
    merged = Doc()
    plane = MergePlane(num_docs=2, capacity=4096)
    mirror_doc_updates(plane, "d", merged)
    for _ in range(150):
        i = random.randrange(3)
        t = docs[i].get_text("t")
        if random.random() < 0.75 or len(t) == 0:
            t.insert(random.randint(0, len(t)), random.choice("xyz") * random.randint(1, 4))
        else:
            pos = random.randrange(len(t))
            t.delete(pos, min(random.randint(1, 3), len(t) - pos))
        if random.random() < 0.4:
            j = random.randrange(3)
            while queues[j]:
                apply_update(docs[j], queues[j].pop(0))
    for j in range(3):
        while queues[j]:
            apply_update(docs[j], queues[j].pop(0))
    # everyone converged on CPU
    assert len({d.get_text("t").to_string() for d in docs}) == 1
    apply_update(merged, encode_state_as_update(docs[0]))
    plane.flush()
    assert plane.text("d") == docs[0].get_text("t").to_string()


def test_map_content_stays_on_plane():
    """Map entries are host-side LWW records — they no longer retire the
    doc (round-2 verdict item: BASELINE config-4 shapes on the plane)."""
    plane = MergePlane(num_docs=4, capacity=256)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    doc.get_map("m").set("k", 1)
    plane.flush()
    assert plane.is_supported("d")
    assert plane.counters["docs_retired_unsupported"] == 0
    # map items land in the serve log, not the device queue
    rec = plane.docs["d"].serve_log[-1]
    assert rec.slot is None and rec.op.parent_sub == "k"


def test_gc_structs_stay_on_plane():
    """GC structs (collected subtrees) are pure clock ranges: recorded
    host-side and re-encoded verbatim — the doc stays plane-served
    (reloaded ProseMirror docs with deleted paragraphs hit this)."""
    from hocuspocus_tpu.crdt.encoding import Encoder

    enc = Encoder()
    enc.write_var_uint(1)  # sections
    enc.write_var_uint(1)  # structs
    enc.write_var_uint(9)  # client
    enc.write_var_uint(0)  # clock
    enc.write_uint8(0x00)  # GC ref
    enc.write_var_uint(3)  # gc length
    enc.write_var_uint(0)  # ds clients
    plane = MergePlane(num_docs=4, capacity=256)
    plane.register("d")
    assert plane.enqueue_update("d", enc.to_bytes()) == 1
    assert plane.is_supported("d")
    assert plane.docs["d"].lowerer.known == {9: 3}
    rec = plane.docs["d"].serve_log[-1]
    assert rec.op.gc and rec.op.run_len == 3


def test_skip_content_falls_back():
    """Skip structs (partial-update placeholders) stay host-only."""
    from hocuspocus_tpu.crdt.encoding import Encoder

    enc = Encoder()
    enc.write_var_uint(1)  # sections
    enc.write_var_uint(1)  # structs
    enc.write_var_uint(9)  # client
    enc.write_var_uint(0)  # clock
    enc.write_uint8(0x0A)  # Skip ref
    enc.write_var_uint(3)  # skip length
    enc.write_var_uint(0)  # ds clients
    plane = MergePlane(num_docs=4, capacity=256)
    plane.register("d")
    plane.enqueue_update("d", enc.to_bytes())
    assert not plane.is_supported("d")
    assert plane.counters["docs_retired_unsupported"] == 1
    assert plane.text("d") is None


def test_slot_release_and_reuse():
    plane = MergePlane(num_docs=2, capacity=64)
    doc = Doc()
    mirror_doc_updates(plane, "a", doc)
    doc.get_text("t").insert(0, "aaa")
    plane.flush()
    assert plane.text("a") == "aaa"
    plane.release("a")
    doc2 = Doc()
    mirror_doc_updates(plane, "b", doc2)
    doc2.get_text("t").insert(0, "bbb")
    plane.flush()
    assert plane.text("b") == "bbb"


def test_sharded_step_multichip():
    """Full merge step jitted over a (doc, unit) mesh on 8 virtual devices."""
    import jax

    from hocuspocus_tpu.tpu.sharding import (
        make_mesh,
        make_sharded_state,
        make_sharded_step,
        ops_sharding,
    )
    from hocuspocus_tpu.tpu.kernels import OpBatch

    n = len(jax.devices())
    assert n == 8, f"expected 8 virtual devices, got {n}"
    mesh = make_mesh(doc_axis=4)  # 4-way doc parallel × 2-way unit parallel
    state = make_sharded_state(mesh, num_docs=8, capacity=64)
    step = make_sharded_step(mesh)

    import jax.numpy as jnp
    import numpy as np

    d, k = 8, 2
    from hocuspocus_tpu.tpu.kernels import NONE_CLIENT

    kind = np.zeros((k, d), np.int32)
    client = np.zeros((k, d), np.uint32)
    clock = np.zeros((k, d), np.int32)
    run_len = np.zeros((k, d), np.int32)
    left_client = np.full((k, d), NONE_CLIENT, np.uint32)
    left_clock = np.zeros((k, d), np.int32)
    right_client = np.full((k, d), NONE_CLIENT, np.uint32)
    right_clock = np.zeros((k, d), np.int32)
    for doc_i in range(d):
        kind[0, doc_i] = 1  # insert
        client[0, doc_i] = 42
        run_len[0, doc_i] = 3
        kind[1, doc_i] = 2  # delete one unit
        client[1, doc_i] = 42
        clock[1, doc_i] = 1
        run_len[1, doc_i] = 1
    ops = OpBatch(
        kind=jnp.asarray(kind),
        client=jnp.asarray(client),
        clock=jnp.asarray(clock),
        run_len=jnp.asarray(run_len),
        left_client=jnp.asarray(left_client),
        left_clock=jnp.asarray(left_clock),
        right_client=jnp.asarray(right_client),
        right_clock=jnp.asarray(right_clock),
    )
    op_shards = ops_sharding(mesh)
    ops = OpBatch(*(jax.device_put(f, s) for f, s in zip(ops, op_shards)))
    new_state, count = step(state, ops)
    assert int(count) == 2 * d
    lengths = np.asarray(new_state.length)
    assert (lengths == 3).all()
    deleted = np.asarray(new_state.deleted)
    assert deleted[:, 1].all() and not deleted[:, 0].any()


def test_overflow_stops_queueing_and_logging():
    """Once a doc can't fit the arena, the plane stops retaining payloads."""
    plane = MergePlane(num_docs=2, capacity=32)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    text = doc.get_text("t")
    text.insert(0, "x" * 16)
    plane.flush()
    assert plane.text("d") == text.to_string()
    (slot,) = plane.docs["d"].seqs.values()
    text.insert(0, "y" * 64)  # exceeds capacity
    assert not plane.is_supported("d")
    assert plane.queues[slot] == []
    log_len = len(plane.unit_logs[slot])
    text.insert(0, "z" * 100)  # further edits must not grow host state
    assert len(plane.unit_logs[slot]) == log_len
    assert plane.queues[slot] == []
    plane.flush()
    assert plane.text("d") is None


def test_overlapping_snapshot_emits_tail():
    """A re-enqueued snapshot whose merged items span the known boundary
    must contribute exactly the unseen tail units (yjs offset splice)."""
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "abc")
    u1 = encode_state_as_update(d)
    t.insert(3, "def")
    full = encode_state_as_update(d)  # items may merge into one struct
    plane = MergePlane(num_docs=2, capacity=64)
    plane.register("d")
    plane.enqueue_update("d", u1)
    plane.flush()
    assert plane.text("d") == "abc"
    plane.enqueue_update("d", full)
    plane.flush()
    assert plane.text("d") == "abcdef"
    # and a pure duplicate is a no-op
    plane.enqueue_update("d", full)
    plane.flush()
    assert plane.text("d") == "abcdef"


def test_partial_delete_range_applies_known_prefix():
    """A delete set covering a partially-known range must tombstone the
    known prefix immediately (CPU _read_and_apply_delete_set parity) —
    deferring the whole range would let a sync serve omit deletions the
    CPU document already applied."""
    from hocuspocus_tpu.crdt.encoding import Encoder
    from hocuspocus_tpu.tpu.kernels import KIND_DELETE
    from hocuspocus_tpu.tpu.lowering import DocLowerer

    # hand-built update: client 9 structs "abc" (clocks 0-2), plus a
    # delete set claiming (client 9, clock 0, len 5) — clocks 3-4 unknown
    enc = Encoder()
    enc.write_var_uint(1)  # sections
    enc.write_var_uint(1)  # structs
    enc.write_var_uint(9)  # client
    enc.write_var_uint(0)  # clock
    enc.write_uint8(0x04)  # ContentString, no origins
    enc.write_var_uint(1)  # parent isYKey
    enc.write_var_string("t")
    enc.write_var_string("abc")
    enc.write_var_uint(1)  # ds clients
    enc.write_var_uint(9)
    enc.write_var_uint(1)  # ranges
    enc.write_var_uint(0)  # clock
    enc.write_var_uint(5)  # len
    lowerer = DocLowerer()
    seq_ops, _, _ = lowerer.lower_update(enc.to_bytes())
    ops = [op for ops in seq_ops.values() for op in ops]
    deletes = [op for op in ops if op.kind == KIND_DELETE]
    assert [(d.clock, d.run_len) for d in deletes] == [(0, 3)]
    assert lowerer.pending_deletes == [(9, 3, 2)]

    # once clocks 3-4 arrive, the remainder of the range applies
    enc2 = Encoder()
    enc2.write_var_uint(1)
    enc2.write_var_uint(1)
    enc2.write_var_uint(9)
    enc2.write_var_uint(3)
    enc2.write_uint8(0x84)  # origin present
    enc2.write_var_uint(9)
    enc2.write_var_uint(2)
    enc2.write_var_string("de")
    enc2.write_var_uint(0)  # empty ds
    seq_ops2, _, _ = lowerer.lower_update(enc2.to_bytes())
    ops2 = [op for ops in seq_ops2.values() for op in ops]
    deletes2 = [op for op in ops2 if op.kind == KIND_DELETE]
    assert [(d.clock, d.run_len) for d in deletes2] == [(3, 2)]
    assert lowerer.pending_deletes == []


def test_broadcast_delete_sets_are_window_sized():
    """Broadcast ds carries the WINDOW's delete ranges only: with N
    delete rounds, broadcast sizes must stay bounded instead of growing
    with the doc's full tombstone history (previously every broadcast
    containing a delete shipped the complete device delete set)."""
    from hocuspocus_tpu.crdt import apply_update
    from hocuspocus_tpu.tpu.serving import PlaneServing

    plane = MergePlane(num_docs=4, capacity=4096)
    serving = PlaneServing(plane)
    doc = Doc()
    mirror_doc_updates(plane, "d", doc)
    text = doc.get_text("t")
    text.insert(0, "x" * 1024)
    plane.flush()
    serving.refresh()
    assert serving.build_broadcast("d")  # drain the seed window

    sizes = []
    peer = Doc()
    apply_update(peer, encode_state_as_update(doc))
    for round_no in range(30):
        text.delete(0, 4)  # steadily accumulate tombstones
        plane.flush()
        serving.refresh()
        payload = serving.build_broadcast("d")
        assert payload is not None
        apply_update(peer, payload)
        sizes.append(len(payload))
        assert peer.get_text("t").to_string() == text.to_string(), round_no
    # each round deletes the same amount; payloads must not trend up
    # with tombstone history (allow codec jitter from varint widths)
    assert max(sizes) <= min(sizes) + 8, sizes
