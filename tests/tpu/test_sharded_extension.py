"""Doc-partitioned serving: ShardedTpuMergeExtension e2e.

The router must be behaviorally identical to a single plane from the
clients' point of view while each shard sweeps only its own arena —
the product answer to the 100k-doc microbatch-latency budget
(reference scale-out doctrine: `docs/guides/scalability.md` "split
users by a document identifier", here applied in-process).
"""

import asyncio

from hocuspocus_tpu.tpu import ShardedTpuMergeExtension
from tests.utils import (
    assert_on_update,
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_synced,
)


def _assert(cond):
    assert cond


async def test_docs_spread_over_shards_and_serve():
    ext = ShardedTpuMergeExtension(
        shards=4, num_docs=8, capacity=1024, flush_interval_ms=1, serve=True
    )
    server = await new_hocuspocus(extensions=[ext])
    writers = {}
    readers = {}
    try:
        for d in range(12):
            name = f"sharded-{d}"
            writers[name] = new_provider(server, name=name)
        await wait_synced(*writers.values())
        for name, p in writers.items():
            p.document.get_text("body").insert(0, f"content of {name}")
        for name in writers:
            readers[name] = new_provider(server, name=name)
        await wait_synced(*readers.values())
        for name, p in readers.items():
            await retryable_assertion(
                lambda p=p, name=name: _assert(
                    p.document.get_text("body").to_string() == f"content of {name}"
                )
            )
        # docs actually landed on MULTIPLE shards with their planes serving
        populated = [s for s in ext.shards if s._docs]
        assert len(populated) >= 2, [len(s._docs) for s in ext.shards]
        assert ext.served_docs() == 12
        totals = ext.counters
        assert totals["cpu_fallbacks"] == 0, totals
        assert totals["plane_broadcasts"] >= 1
        assert totals["sync_serves"] >= 1
        for name in writers:
            assert ext.is_served(name), name
    finally:
        for p in list(writers.values()) + list(readers.values()):
            p.destroy()
        await server.destroy()


async def test_sharded_unload_reload_roundtrip():
    from hocuspocus_tpu.extensions import SQLite

    ext = ShardedTpuMergeExtension(
        shards=2, num_docs=8, capacity=1024, flush_interval_ms=1, serve=True
    )
    server = await new_hocuspocus(
        extensions=[SQLite(), ext], debounce=50, max_debounce=100
    )
    try:
        a = new_provider(server, name="roundtrip")
        await wait_synced(a)
        a.document.get_text("body").insert(0, "survives unload")
        # the edit must actually REACH the server before the disconnect,
        # or there is nothing to store
        await retryable_assertion(
            lambda: _assert(
                "roundtrip" in server.documents
                and server.documents["roundtrip"].get_text("body").to_string()
                == "survives unload"
            )
        )
        a.destroy()
        # unload completion (doc leaves the registry only after the
        # final store ran — save mutex gating) + plane release
        await retryable_assertion(
            lambda: _assert(
                "roundtrip" not in server.documents
                and not ext.shard_for("roundtrip").plane.docs
            )
        )
        b = new_provider(server, name="roundtrip")
        await wait_synced(b)
        assert b.document.get_text("body").to_string() == "survives unload"
        assert ext.is_served("roundtrip")
        b.destroy()
    finally:
        await server.destroy()


async def test_sharded_concurrent_edits_converge():
    ext = ShardedTpuMergeExtension(
        shards=3, num_docs=8, capacity=2048, flush_interval_ms=1, serve=True
    )
    server = await new_hocuspocus(extensions=[ext])
    try:
        a = new_provider(server, name="conc-doc")
        b = new_provider(server, name="conc-doc")
        await wait_synced(a, b)
        ta, tb = a.document.get_text("body"), b.document.get_text("body")
        expected_len = 0
        for i in range(20):
            ta.insert(len(ta), f"a{i};")
            tb.insert(0, f"b{i};")
            expected_len += len(f"a{i};") + len(f"b{i};")
            if i % 5 == 4:
                await asyncio.sleep(0.01)
        await retryable_assertion(
            lambda: _assert(
                ta.to_string() == tb.to_string() and len(ta) == expected_len
            )
        )
        assert ext.counters["cpu_fallbacks"] == 0
        a.destroy()
        b.destroy()
    finally:
        await server.destroy()


async def test_sharded_planes_with_redis_fanout():
    """The full production combo: doc-partitioned shard planes on TWO
    instances behind (mini-)Redis — cross-instance window fan-out and
    late joins must work per shard."""
    from hocuspocus_tpu.extensions import Redis
    from hocuspocus_tpu.net.mini_redis import MiniRedis

    redis = await MiniRedis().start()
    ext_a = ShardedTpuMergeExtension(
        shards=2, num_docs=8, capacity=1024, flush_interval_ms=1, serve=True
    )
    ext_b = ShardedTpuMergeExtension(
        shards=2, num_docs=8, capacity=1024, flush_interval_ms=1, serve=True
    )
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="sha", disconnect_delay=100), ext_a]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="shb", disconnect_delay=100), ext_b]
    )
    try:
        writers = {}
        readers = {}
        for d in range(4):
            name = f"xdoc-{d}"
            writers[name] = new_provider(server_a, name=name)
            readers[name] = new_provider(server_b, name=name)
        # event-driven: timeouts here are liveness bounds only — the
        # waits resolve on synced/update events, not interval polls
        await wait_synced(*writers.values(), *readers.values(), timeout=60)
        for name, w in writers.items():
            w.document.get_text("t").insert(0, f"payload {name}")
        for name, r in readers.items():
            await assert_on_update(
                r.document,
                lambda r=r, name=name: _assert(
                    r.document.get_text("t").to_string() == f"payload {name}"
                ),
                timeout=30,
            )
        assert ext_a.counters["cpu_fallbacks"] == 0
        assert ext_b.counters["cpu_fallbacks"] == 0
        assert ext_a.counters["plane_broadcasts"] >= 1
        # late joiner on B pulls one of the docs from B's shard plane
        late = new_provider(server_b, name="xdoc-2")
        await wait_synced(late, timeout=30)
        await assert_on_update(
            late.document,
            lambda: _assert(
                late.document.get_text("t").to_string() == "payload xdoc-2"
            ),
        )
        late.destroy()
        for p in list(writers.values()) + list(readers.values()):
            p.destroy()
    finally:
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()
