"""Sparse busy-doc dispatch: differential + regression coverage.

The flush engine ships compact (K, B) batches over only the busy doc
slots, routed by an int32 slot vector, instead of dense (K, D) sweeps
(docs/guides/tpu-merge-pipeline.md). These suites pin:

- kernel equivalence: sparse gather/integrate/scatter == the dense
  sweep, padding sentinel included (unit + RLE arenas);
- the live plane path: random busy subsets with interleaved flushes
  serve state equal to CPU ground-truth docs;
- staging reuse: per-flush staging buffers are reused, not
  re-allocated;
- the (K, B) warmup grid + the sparse canary probe;
- a CPU-backend flush-pipeline smoke (tier-1): sparse and dense cycles
  through the server-facing flush() API.
"""

import numpy as np
import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.tpu.kernels import (
    KIND_INSERT,
    NONE_CLIENT,
    OpBatch,
    integrate_op_slots,
    integrate_op_slots_sparse,
    make_empty_state,
)
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.serving import PlaneServing

D, N, K = 16, 128, 4


def _append_ops(rng, clocks, busy):
    """Dense (K, D) field arrays holding a K-deep append run for each
    busy doc (id-chained inserts, the typing-burst shape)."""
    kind = np.zeros((K, D), np.int32)
    client = np.zeros((K, D), np.uint32)
    clock = np.zeros((K, D), np.int32)
    run = np.zeros((K, D), np.int32)
    lc = np.full((K, D), NONE_CLIENT, np.uint32)
    lk = np.zeros((K, D), np.int32)
    rc = np.full((K, D), NONE_CLIENT, np.uint32)
    rk = np.zeros((K, D), np.int32)
    for d in busy:
        for k in range(K):
            kind[k, d] = KIND_INSERT
            client[k, d] = 7
            clock[k, d] = clocks[d]
            run[k, d] = 3
            if clocks[d] > 0:
                lc[k, d] = 7
                lk[k, d] = clocks[d] - 1
            clocks[d] += 3
    return (kind, client, clock, run, lc, lk, rc, rk)


def _sparse_view(fields, busy):
    """Slice the busy columns out and pad to the power-of-two bucket
    with noops + the out-of-range sentinel slot."""
    b = 1
    while b < len(busy):
        b *= 2
    pad = b - len(busy)
    sparse = []
    for i, field in enumerate(fields):
        pad_value = NONE_CLIENT if i in (4, 6) else 0
        sparse.append(
            np.concatenate(
                [field[:, busy], np.full((K, pad), pad_value, field.dtype)], axis=1
            )
        )
    slots = np.asarray(list(busy) + [D] * pad, np.int32)
    return OpBatch(*sparse), slots


def test_sparse_kernel_matches_dense_unit_arena():
    rng = np.random.default_rng(2)
    clocks = np.zeros(D, np.int64)
    dense_state = make_empty_state(D, N)
    sparse_state = make_empty_state(D, N)
    for _round in range(4):
        busy = sorted(rng.choice(D, size=int(rng.integers(1, 6)), replace=False))
        fields = _append_ops(rng, clocks, busy)
        dense_state, dense_count = integrate_op_slots(
            dense_state, OpBatch(*fields)
        )
        ops, slots = _sparse_view(fields, busy)
        sparse_state, sparse_count = integrate_op_slots_sparse(
            sparse_state, ops, slots
        )
        assert int(dense_count) == int(sparse_count) + (D - len(busy)) * 0
    for dense_field, sparse_field in zip(dense_state, sparse_state):
        np.testing.assert_array_equal(
            np.asarray(dense_field), np.asarray(sparse_field)
        )


def test_sparse_kernel_matches_dense_rle_arena():
    from hocuspocus_tpu.tpu.kernels_rle import (
        integrate_op_slots_rle,
        integrate_op_slots_rle_sparse,
        make_empty_rle_state,
    )

    rng = np.random.default_rng(3)
    clocks = np.zeros(D, np.int64)
    dense_state = make_empty_rle_state(D, N)
    sparse_state = make_empty_rle_state(D, N)
    for _round in range(4):
        busy = sorted(rng.choice(D, size=int(rng.integers(1, 6)), replace=False))
        fields = _append_ops(rng, clocks, busy)
        dense_state, _ = integrate_op_slots_rle(dense_state, OpBatch(*fields))
        ops, slots = _sparse_view(fields, busy)
        sparse_state, _ = integrate_op_slots_rle_sparse(sparse_state, ops, slots)
    for dense_field, sparse_field in zip(dense_state, sparse_state):
        np.testing.assert_array_equal(
            np.asarray(dense_field), np.asarray(sparse_field)
        )


# -- live plane differential fuzz --------------------------------------------

WORDS = ["alpha ", "bb", "c", "delta-", "ee ", "zz"]


def _edit(rng, doc: Doc) -> None:
    text = doc.get_text("t")
    kind = rng.integers(0, 4)
    if kind == 0 or len(text) == 0:
        text.insert(int(rng.integers(0, len(text) + 1)), WORDS[rng.integers(0, 6)])
    elif kind == 1:
        pos = int(rng.integers(0, len(text)))
        text.delete(pos, min(int(rng.integers(1, 3)), len(text) - pos))
    elif kind == 2 and len(text) > 1:
        pos = int(rng.integers(0, len(text) - 1))
        text.format(pos, 1, {"bold": bool(rng.integers(0, 2))})
    else:
        text.insert(len(text), WORDS[rng.integers(0, 6)])


@pytest.mark.parametrize("arena", ["unit", "rle"])
@pytest.mark.parametrize("seed", [1, 7])
def test_sparse_dispatch_fuzz_random_busy_subsets(seed, arena):
    """Random busy subsets + interleaved flushes vs CPU ground truth:
    every flush cycle dispatches a different busy width (different
    (K, B) buckets, sparse and dense), and after every cycle each doc
    must still serve bytes that rebuild its CPU double."""
    rng = np.random.default_rng(seed)
    plane = MergePlane(num_docs=32, capacity=2048, arena=arena)
    serving = PlaneServing(plane)
    population = 8
    docs, pending = {}, {}
    for i in range(population):
        name = f"doc-{i}"
        plane.register(name)
        doc = Doc()
        queue: list = []
        doc.on("update", lambda update, *rest, queue=queue: queue.append(update))
        docs[name], pending[name] = doc, queue
    for _round in range(14):
        subset = rng.choice(
            population, size=int(rng.integers(1, population + 1)), replace=False
        )
        for i in subset:
            name = f"doc-{i}"
            for _ in range(int(rng.integers(1, 4))):
                _edit(rng, docs[name])
            for update in pending[name]:
                plane.enqueue_update(name, update)
            pending[name].clear()
        # interleaved flushes: sometimes one batch per cycle (serving
        # cadence), sometimes a full drain (sync-serve cadence)
        if rng.integers(0, 2):
            plane.flush(max_batches=1)
            plane.flush()
        else:
            plane.flush()
        serving.refresh()
        assert plane.pending_ops() == 0
    for i in range(population):
        name = f"doc-{i}"
        assert plane.is_supported(name), (seed, arena, plane.counters)
        served = serving.encode_state_as_update(name, docs[name], None)
        assert served is not None, (seed, arena, name)
        rebuilt = Doc()
        apply_update(rebuilt, served)
        assert (
            rebuilt.get_text("t").to_delta() == docs[name].get_text("t").to_delta()
        ), (seed, arena, name)
    assert plane.counters["flush_batches_sparse"] > 0


# -- staging reuse regression -------------------------------------------------


def test_staging_reused_not_reallocated():
    """The per-flush staging buffers are allocated once (two sets,
    double buffering) and every subsequent batch reuses them — a
    regression here silently reintroduces the 8x(K, D)-fresh-allocs-
    per-batch host cost the pipeline removed."""
    plane = MergePlane(num_docs=16, capacity=512)
    # pin the FULL-INTEGRATE staging path: with the run-merge
    # classifier on, pure tail appends ship through the append staging
    # instead (covered by the twin test below)
    plane.run_merge_enabled = False
    plane.register("doc")
    source = Doc()
    updates: list = []
    source.on("update", lambda update, *rest: updates.append(update))
    text = source.get_text("t")
    cycles = 6
    for cycle in range(cycles):
        text.insert(len(text), f"cycle {cycle} ")
        for update in updates:
            plane.enqueue_update("doc", update)
        updates.clear()
        plane.flush()
    assert plane.counters["flush_staging_allocs"] == 2
    assert plane.counters["flush_staging_reuses"] == cycles - 1
    first_ids = [id(field) for field in plane._staging[0].fields] + [
        id(field) for field in plane._staging[1].fields
    ]
    text.insert(len(text), "tail")
    for update in updates:
        plane.enqueue_update("doc", update)
    updates.clear()
    plane.flush()
    assert plane.counters["flush_staging_allocs"] == 2  # still the same two
    assert [id(field) for field in plane._staging[0].fields] + [
        id(field) for field in plane._staging[1].fields
    ] == first_ids
    assert plane.text("doc") == source.get_text("t").to_string()


def test_append_staging_reused_not_reallocated():
    """Fast-path twin: sequential appends route through the run-merge
    append staging (two sets, double buffering), which must also be
    allocated once and reused — and never allocate the full-integrate
    staging at all on a pure-sequential workload."""
    plane = MergePlane(num_docs=16, capacity=512)
    plane.register("doc")
    source = Doc()
    updates: list = []
    source.on("update", lambda update, *rest: updates.append(update))
    text = source.get_text("t")
    cycles = 6
    for cycle in range(cycles):
        text.insert(len(text), f"cycle {cycle} ")
        for update in updates:
            plane.enqueue_update("doc", update)
        updates.clear()
        plane.flush()
    assert plane.counters["flush_batches_fast"] == cycles
    assert plane.counters["flush_fast_ops"] > 0
    assert plane.counters["flush_slow_ops"] == 0
    assert plane.flush_stats["fast_path_fraction"] == 1.0
    assert plane._staging is None  # the slow path never ran
    assert plane.counters["flush_staging_allocs"] == 2
    assert plane.counters["flush_staging_reuses"] == cycles - 1
    first_ids = [
        id(plane._append_staging[0].client),
        id(plane._append_staging[1].client),
    ]
    text.insert(len(text), "tail")
    for update in updates:
        plane.enqueue_update("doc", update)
    updates.clear()
    plane.flush()
    assert plane.counters["flush_staging_allocs"] == 2
    assert [
        id(plane._append_staging[0].client),
        id(plane._append_staging[1].client),
    ] == first_ids
    assert plane.text("doc") == source.get_text("t").to_string()


# -- warmup grid + canary ------------------------------------------------------


def test_warmup_grid_covers_sparse_and_dense_shapes():
    plane = MergePlane(num_docs=8, capacity=128, max_slots_per_flush=4)
    shapes = plane.warmup_shapes()
    # (K_max, 1) first: the canary probe's shape compiles before the
    # first watchdog tick on a warmed plane
    assert shapes[0] == (4, 1)
    assert (4, 8) in shapes  # the dense fallback shape
    # sparse shapes pin K to the top bucket: the grid is |K| + |B|
    assert all(k == 4 for k, b in shapes if b < plane.num_docs)
    assert all(b <= plane.num_docs for _k, b in shapes)
    assert all(k & (k - 1) == 0 and b & (b - 1) == 0 for k, b in shapes)
    plane.warmup_compiles((1, 1))
    plane.warmup_compiles((2, 4))
    plane.warmup_compiles(2)  # legacy int form: dense (2, num_docs)
    latency = plane.canary_probe()
    assert latency >= 0.0
    # warmups + canaries integrate nothing
    assert plane.total_integrated == 0
    assert int(np.asarray(plane.state.length).sum()) == 0


# -- CPU-backend flush-pipeline smoke (tier-1) --------------------------------


def test_flush_pipeline_smoke_mixed_widths():
    """Build→upload→step→readback smoke across the widths the engine
    dispatches: one busy doc (sparse B=1), a few (sparse bucket), all
    busy (dense fallback), and a multi-batch backlog drain."""
    plane = MergePlane(num_docs=8, capacity=512, max_slots_per_flush=2)
    # classic-path smoke: first-ever inserts into empty docs would
    # otherwise route through the run-merge append program (see
    # test_mixed_fast_slow_flush_smoke for the classifier's widths)
    plane.run_merge_enabled = False
    serving = PlaneServing(plane)
    population = 8
    docs, pending = {}, {}
    for i in range(population):
        name = f"doc-{i}"
        plane.register(name)
        doc = Doc()
        queue: list = []
        doc.on("update", lambda update, *rest, queue=queue: queue.append(update))
        docs[name], pending[name] = doc, queue

    def touch(indices, burst=1):
        for i in indices:
            name = f"doc-{i}"
            for n in range(burst):
                docs[name].get_text("t").insert(0, f"w{n} ")
            for update in pending[name]:
                plane.enqueue_update(name, update)
            pending[name].clear()

    # one busy doc -> sparse (B=1)
    touch([0])
    plane.flush()
    assert plane.counters["flush_batches_sparse"] >= 1
    assert plane.flush_stats["batch_b"] == 1
    assert plane.flush_stats["busy_slots"] == 1
    # three busy docs -> sparse bucket B=4
    touch([1, 2, 3])
    plane.flush()
    assert plane.flush_stats["batch_b"] == 4
    assert plane.flush_stats["busy_fraction"] == pytest.approx(3 / 8)
    # every doc busy -> dense fallback, no routing overhead
    touch(range(population))
    plane.flush()
    assert plane.counters["flush_batches_dense"] >= 1
    assert plane.flush_stats["batch_b"] == plane.num_docs
    # backlog deeper than max_slots_per_flush drains over multiple
    # batches; max_batches=1 leaves a remainder, a full flush clears it
    touch([4], burst=6)
    assert plane.pending_ops() > 2
    plane.flush(max_batches=1)
    assert plane.pending_ops() > 0
    plane.flush()
    assert plane.pending_ops() == 0
    # stage gauges populated
    for key in ("build_ms", "upload_ms", "device_sync_ms", "upload_bytes"):
        assert plane.flush_stats[key] >= 0
    assert plane.flush_stats["upload_bytes"] > 0
    # served state equals ground truth after the mixed cycles
    serving.refresh()
    for i in range(population):
        name = f"doc-{i}"
        assert plane.text(name) == docs[name].get_text("t").to_string(), name
        served = serving.encode_state_as_update(name, docs[name], None)
        rebuilt = Doc()
        apply_update(rebuilt, served)
        assert (
            rebuilt.get_text("t").to_string()
            == docs[name].get_text("t").to_string()
        )


def test_mixed_fast_slow_flush_smoke():
    """Run-merge classifier smoke: one flush cycle carrying both
    all-sequential columns (tail appends -> append program) and
    concurrent columns (prepends -> full integrate) dispatches both
    paths, splits the op accounting per path, and still serves state
    equal to the CPU ground truth."""
    plane = MergePlane(num_docs=8, capacity=512)
    serving = PlaneServing(plane)
    docs, pending = {}, {}
    for i in range(4):
        name = f"doc-{i}"
        plane.register(name)
        doc = Doc()
        queue: list = []
        doc.on("update", lambda update, *rest, queue=queue: queue.append(update))
        docs[name], pending[name] = doc, queue

    def push(name):
        for update in pending[name]:
            plane.enqueue_update(name, update)
        pending[name].clear()

    # seed every doc (first insert into an empty row: fast)
    for i in range(4):
        docs[f"doc-{i}"].get_text("t").insert(0, "seed ")
        push(f"doc-{i}")
    plane.flush()
    assert plane.counters["flush_batches_fast"] >= 1
    assert plane.counters["flush_slow_ops"] == 0
    # docs 0/1 keep appending (fast), docs 2/3 prepend (slow) — one
    # cycle must split the columns across both dispatch paths
    for i in (0, 1):
        text = docs[f"doc-{i}"].get_text("t")
        text.insert(len(text), "tail")
        push(f"doc-{i}")
    for i in (2, 3):
        docs[f"doc-{i}"].get_text("t").insert(0, "head ")
        push(f"doc-{i}")
    fast_before = plane.counters["flush_fast_ops"]
    plane.flush()
    assert plane.counters["flush_fast_ops"] > fast_before
    assert plane.counters["flush_slow_ops"] > 0
    assert plane.counters["flush_batches_sparse"] >= 1
    assert 0.0 < plane.flush_stats["fast_path_fraction"] < 1.0
    # a slow column's tail re-arms via the probe: the NEXT append to a
    # prepended doc goes fast again
    text = docs["doc-2"].get_text("t")
    text.insert(len(text), "end")
    push("doc-2")
    fast_before = plane.counters["flush_fast_ops"]
    plane.flush()
    assert plane.counters["flush_fast_ops"] > fast_before
    # served state equals ground truth across both paths
    serving.refresh()
    for i in range(4):
        name = f"doc-{i}"
        assert plane.text(name) == docs[name].get_text("t").to_string(), name
        served = serving.encode_state_as_update(name, docs[name], None)
        rebuilt = Doc()
        apply_update(rebuilt, served)
        assert (
            rebuilt.get_text("t").to_string()
            == docs[name].get_text("t").to_string()
        ), name


def test_pending_ops_tracks_busy_set_exactly():
    """pending_ops walks the nonempty-slot set (O(busy)); it must stay
    exact through enqueue/drain/retire transitions."""
    plane = MergePlane(num_docs=8, capacity=256)
    plane.register("a")
    plane.register("b")
    source = Doc()
    updates: list = []
    source.on("update", lambda update, *rest: updates.append(update))
    source.get_text("t").insert(0, "hello")
    for update in updates:
        plane.enqueue_update("a", update)
        plane.enqueue_update("b", update)
    queued = sum(len(q) for q in plane.queues.values())
    assert plane.pending_ops() == queued > 0
    assert plane._busy_slots
    plane.flush()
    assert plane.pending_ops() == 0
    assert not plane._busy_slots
    # a retired doc's cleared queue leaves the busy set immediately
    updates.clear()
    source.get_text("t").insert(0, "more")
    for update in updates:
        plane.enqueue_update("a", update)
    assert plane.pending_ops() > 0
    plane.retire_doc("a", "fallback")
    assert plane.pending_ops() == 0
    assert not plane._busy_slots
