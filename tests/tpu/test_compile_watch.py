"""Compile-event tracker: warm-grid differentials, cache-hit
classification, recompile-storm detection, HBM/stall stats.

Acceptance (ISSUE 6): the tracker must PROVABLY distinguish warm-grid
cache hits from fresh compiles — the warm grid pays every compile once,
and live flushes at warmed shapes never register as fresh again.
"""

from __future__ import annotations

from hocuspocus_tpu.crdt import Doc, encode_state_as_update
from hocuspocus_tpu.observability import get_flight_recorder
from hocuspocus_tpu.observability.device_watch import (
    CompileTracker,
    pytree_nbytes,
    shape_label,
)
from hocuspocus_tpu.tpu.merge_plane import MergePlane


def _make_update(text: str) -> bytes:
    doc = Doc()
    doc.get_text("t").insert(0, text)
    return encode_state_as_update(doc)


def test_warm_grid_compiles_once_then_only_hits():
    """warmup_compiles() pays one fresh compile per (k, b) grid shape;
    a second warmup over the same grid is all cache hits — the
    differential against the existing warmup-grid behavior."""
    plane = MergePlane(num_docs=8, capacity=256, max_slots_per_flush=4)
    watch = plane.compile_watch
    # the full warm grid: integrate (k, b) pairs plus the tagged
    # run-append / tail-probe aux shapes
    grid = plane.warmup_shapes() + plane.warmup_aux_shapes()
    assert watch.fresh_compiles == 0

    plane.warmup_compiles()
    assert watch.fresh_compiles == len(grid)
    assert watch.cache_hits == 0
    assert watch._warmed is True  # full grid -> warmed

    plane.warmup_compiles()
    assert watch.fresh_compiles == len(grid)  # nothing new
    assert watch.cache_hits == len(grid)  # every shape re-dispatched warm
    # re-warming warmed shapes never counts as a storm
    assert watch.snapshot()["warmed"] is True


def test_live_flush_at_warmed_shape_is_a_cache_hit():
    plane = MergePlane(num_docs=8, capacity=256, max_slots_per_flush=4)
    plane.warmup_compiles()
    fresh_after_warmup = plane.compile_watch.fresh_compiles
    hits_after_warmup = plane.compile_watch.cache_hits

    plane.register("hot")
    plane.enqueue_update("hot", _make_update("hello"))
    assert plane.flush() > 0
    assert plane.compile_watch.fresh_compiles == fresh_after_warmup
    assert plane.compile_watch.cache_hits > hits_after_warmup


def test_canary_probe_shape_is_covered_by_the_warm_grid():
    """The canary's (K_max, 1) program is the warm grid's first entry:
    a warmed plane's probes never pay a compile."""
    plane = MergePlane(num_docs=8, capacity=256, max_slots_per_flush=4)
    plane.warmup_compiles()
    fresh = plane.compile_watch.fresh_compiles
    plane.canary_probe()
    assert plane.compile_watch.fresh_compiles == fresh


def test_compile_event_labels_and_exposition():
    tracker = CompileTracker()
    before_compile = tracker.compile_events.value(
        kind="compile", site="test_site", shape="4x2"
    )
    assert tracker.observe("test_site", (4, 2), 1.25) == "compile"
    assert tracker.observe("test_site", (4, 2), 0.001) == "hit"
    assert tracker.observe("test_site", (4, 8), 0.9) == "compile"
    assert (
        tracker.compile_events.value(kind="compile", site="test_site", shape="4x2")
        == before_compile + 1
    )
    assert tracker.compile_events.value(kind="hit", site="test_site", shape="4x2") >= 1
    assert tracker.seen("test_site", (4, 2))
    assert not tracker.seen("test_site", (16, 2))
    assert shape_label((16, 4)) == "16x4"


def test_recompile_storm_logged_and_recorded():
    """Fresh compiles past the warm grid raise the storm alarm: a
    structured log plus a compile_storm flight-recorder event under
    __plane__."""
    recorder = get_flight_recorder()
    recorder.forget("__plane__")
    tracker = CompileTracker(storm_window_s=60.0, storm_threshold=3)
    storms_before = sum(tracker.storms._values.values())

    # pre-warm phase: grid compiles never count toward the storm
    tracker.observe("integrate_sparse", (4, 1), 0.5, warmup=True)
    tracker.mark_warmed()

    tracker.observe("integrate_sparse", (4, 2), 0.5)
    tracker.observe("integrate_sparse", (4, 4), 0.5)
    assert sum(tracker.storms._values.values()) == storms_before  # under threshold
    tracker.observe("integrate_sparse", (4, 16), 0.5)  # third unexpected compile
    assert sum(tracker.storms._values.values()) == storms_before + 1
    events = [e for e in recorder.events("__plane__") if e["event"] == "compile_storm"]
    assert events
    assert events[-1]["compiles"] == 3
    # the detector re-arms: the burst was consumed
    tracker.observe("integrate_sparse", (4, 32), 0.5)
    assert sum(tracker.storms._values.values()) == storms_before + 1


def test_memory_stats_report_arena_and_staging_bytes():
    plane = MergePlane(num_docs=8, capacity=256, max_slots_per_flush=4)
    stats = plane.memory_stats()
    assert stats["arena_bytes"] > 0
    assert stats["staging_bytes"] == 0  # no flush yet -> no staging
    assert stats["readback_stall_ms_total"] == 0.0

    plane.register("mem")
    plane.enqueue_update("mem", _make_update("bytes"))
    plane.flush()
    stats = plane.memory_stats()
    assert stats["staging_bytes"] > 0  # double-buffered staging allocated
    assert stats["upload_bytes_peak"] > 0
    assert stats["readback_stall_ms_total"] > 0.0
    assert stats["readback_stalls"] >= 1


def test_pytree_nbytes_walks_nested_structures():
    import numpy as np

    tree = {
        "a": np.zeros((4, 4), np.int32),
        "b": (np.zeros(8, np.int64), [np.zeros(2, np.uint8)]),
        "c": "not an array",
    }
    assert pytree_nbytes(tree) == 4 * 4 * 4 + 8 * 8 + 2
