"""Run-length arena as the SERVING substrate (MergePlane(arena="rle")).

The unit arena spends one device slot per UTF-16 unit forever, so a
long-lived busy doc exhausts cumulative capacity no matter its live
size — the round-3 verdict's documented limit. The RLE arena's cost is
O(ops + fragmentation), which is what lets churny docs STAY
device-served: the device-side replacement for yjs GC semantics
(reference `packages/server/src/types.ts:152-155` yDocOptions.gc).
"""

import asyncio

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def _churn(provider, cycles: int, burst: int = 16) -> None:
    """Insert a burst at the end, then delete it — live size stays tiny
    while cumulative unit count grows without bound."""
    text = provider.document.get_text("body")
    for i in range(cycles):
        base = len(text)
        text.insert(base, "x" * burst)
        text.delete(base, burst)
        if i % 4 == 3:
            await asyncio.sleep(0.01)  # let flush cycles interleave


async def test_churn_retires_unit_arena_but_not_rle():
    """Same 30-cycle churn on both arenas at matched capacity=256:
    the unit arena takes a capacity incident (480 cumulative units),
    the RLE arena serves the whole run without a single degradation
    (~30 run entries + tombstones).  VERDICT r3 item 3's acceptance
    test."""
    results = {}
    for arena in ("unit", "rle"):
        ext = TpuMergeExtension(
            num_docs=8, capacity=256, flush_interval_ms=1, serve=True, arena=arena
        )
        server = await new_hocuspocus(extensions=[ext])
        try:
            provider = new_provider(server, name="churny")
            await wait_synced(provider)
            await _churn(provider, cycles=30)
            await retryable_assertion(
                lambda: _assert(ext.plane.pending_ops() == 0)
            )
            results[arena] = {
                "retired_capacity": ext.plane.counters["docs_retired_capacity"],
                "overflow": ext.plane.counters["docs_retired_overflow"],
                "cpu_fallbacks": ext.plane.counters["cpu_fallbacks"],
                "still_served": "churny" in ext._docs,
            }
            provider.destroy()
        finally:
            await server.destroy()
    assert results["unit"]["retired_capacity"] > 0, results
    assert results["rle"]["retired_capacity"] == 0, results
    assert results["rle"]["overflow"] == 0, results
    assert results["rle"]["cpu_fallbacks"] == 0, results
    assert results["rle"]["still_served"], results


async def test_rle_serve_mode_live_server_e2e():
    """RLE plane through the real server: concurrent editors converge,
    a late joiner cold-syncs from device state, churn keeps serving."""
    from hocuspocus_tpu.extensions import SQLite

    ext = TpuMergeExtension(
        num_docs=8, capacity=512, flush_interval_ms=1, serve=True, arena="rle"
    )
    server = await new_hocuspocus(
        extensions=[SQLite(), ext], debounce=50, max_debounce=100
    )
    try:
        a = new_provider(server, name="rle-doc")
        b = new_provider(server, name="rle-doc")
        await wait_synced(a, b)
        a.document.get_text("body").insert(0, "from-a \U0001f600 ")
        b.document.get_map("meta").set("owner", "b")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("body").to_string() == "from-a \U0001f600 "
                and a.document.get_map("meta").get("owner") == "b"
            )
        )
        # churn, then a late joiner cold-syncs the merged state
        await _churn(a, cycles=12, burst=8)
        a.document.get_text("body").insert(0, "tail ")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("body").to_string()
                == a.document.get_text("body").to_string()
            )
        )
        c = new_provider(server, name="rle-doc")
        await wait_synced(c)
        assert (
            c.document.get_text("body").to_string()
            == a.document.get_text("body").to_string()
        )
        assert c.document.get_map("meta").get("owner") == "b"
        assert "rle-doc" in ext._docs, "degraded off the RLE plane"
        assert ext.plane.counters["cpu_fallbacks"] == 0, ext.plane.counters
        assert ext.plane.counters["plane_broadcasts"] > 0
        assert ext.plane.counters["sync_serves"] > 0
        final = a.document.get_text("body").to_string()
        for p in (a, b, c):
            p.destroy()
        # unload releases the RLE rows (regression: _clear_slot must
        # rebuild RleState, not DocState) and a reload serves again
        await retryable_assertion(lambda: _assert(not ext.plane.docs))
        d = new_provider(server, name="rle-doc")
        await wait_synced(d)
        assert d.document.get_text("body").to_string() == final
        d.destroy()
    finally:
        await server.destroy()


async def test_rle_row_exhaustion_recycles_back_onto_plane():
    """An RLE doc can exhaust entries either via the host projection
    ("capacity") or via split costs only the DEVICE sees ("overflow" —
    `fits = num_runs + 2 <= r`, caught by the health sweep where no
    capture seam runs). Both must route through the recycle seam: the
    doc re-onboards from its live snapshot instead of degrading to CPU
    forever, and a declined recycle must NOT thrash (one snapshot
    re-lower per verdict, not one per update)."""
    ext = TpuMergeExtension(
        num_docs=8, capacity=24, flush_interval_ms=1, serve=True, arena="rle"
    )
    server = await new_hocuspocus(extensions=[ext])
    try:
        p = new_provider(server, name="splitty")
        await wait_synced(p)
        text = p.document.get_text("body")
        text.insert(0, "keep me. ")
        # burst-churn until the 24-entry arena exhausts by either
        # detector (host "capacity" projection or device "overflow"):
        # each cycle leaves a tombstoned run behind, but the LIVE
        # snapshot stays tiny (deleted bursts GC to host-side ranges),
        # so this is exactly the doc class recycling must rescue
        exhausted = lambda: (
            ext.plane.counters["docs_retired_overflow"]
            + ext.plane.counters["docs_retired_capacity"]
        )
        i = 0
        while exhausted() == 0 and i < 60:
            base = len(text)
            text.insert(base, "burst!" + str(i))
            text.delete(base, len("burst!" + str(i)))
            i += 1
            await asyncio.sleep(0.005)
        assert exhausted() >= 1, ext.plane.counters
        # nudge SPARSELY while waiting: the recycle queues behind
        # listen-time warmup compiles (~6s on CPU) and piled flush
        # cycles, and every nudge grows the live snapshot — a tight
        # insert loop would outgrow the 24-entry arena before the
        # attempt ever takes the lock, turning a legitimate recycle
        # into a legitimate decline
        for _ in range(40):
            if ext.plane.counters["docs_recycled"]:
                break
            text.insert(0, "z")
            await asyncio.sleep(2.0)
        assert ext.plane.counters["docs_recycled"] >= 1, ext.plane.counters
        await retryable_assertion(lambda: _assert("splitty" in ext._docs))
        # the recycled registration still converges to a fresh peer
        q = new_provider(server, name="splitty")
        await wait_synced(q)
        assert q.document.get_text("body").to_string() == text.to_string()
        p.destroy()
        q.destroy()
    finally:
        await server.destroy()


async def test_overflow_reason_routes_to_recycle_and_decline_sticks():
    """Pin the routing table deterministically: an 'overflow' retire
    schedules a recycle; 'unsupported' and 'desync' never do; a
    declined doc is not retried (thrash guard) until unload clears it."""
    from types import SimpleNamespace

    ext = TpuMergeExtension(num_docs=4, capacity=64, serve=True, arena="rle")
    spawned = []
    ext._spawn_tracked = lambda coro: (spawned.append(coro), coro.close())
    doc = SimpleNamespace(name="d")
    for reason, expect in (
        ("overflow", 1),
        ("capacity", 2),
        ("plane_full", 3),
        ("unsupported", 3),
        ("desync", 3),
        (None, 3),
    ):
        ext._maybe_recycle(doc, reason)
        assert len(spawned) == expect, reason
    ext._recycle_declined.add("d")
    ext._maybe_recycle(doc, "overflow")
    assert len(spawned) == 3, "declined doc must not be retried"
