"""Plane supervisor: fault-tolerant TPU runtime lifecycle (tpu/supervisor.py).

The round-5 verdict found the defect these tests pin down: a server
configured with the TPU merge plane hung at boot, serving nothing,
whenever the TPU runtime was wedged — exactly the failure mode of a
dead device tunnel. The supervisor inverts the ownership: the plane is
an accelerator the server may acquire, never a boot dependency.

Chaos scenarios covered, with the invariant "hardware absence degrades
throughput, never availability" checked in each:
- wedged init: the server boots within the init deadline, accepts
  WebSocket connections and syncs documents on the CPU path
- late init: the plane hot-attaches and takes over serving
- failed init: BROKEN is terminal, the server keeps serving
- mid-flight wedge: the watchdog canary overruns, the breaker opens,
  served docs drain to the CPU path with zero request loss (including
  sync waiters stranded behind the wedged flush)
- flapping recovery: wedge -> recover -> wedge again, with the breaker
  and transition counters accounting for every swing
"""

import asyncio
import threading

from hocuspocus_tpu.tpu import SupervisedTpuMergeExtension
from hocuspocus_tpu.tpu.supervisor import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    STATE_BROKEN,
    STATE_DEGRADED,
    STATE_INITIALIZING,
    STATE_READY,
    CircuitBreaker,
)
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond, detail=None):
    assert cond, detail


def _fast_ext(**overrides):
    """A supervised serve-mode extension tuned for test cadence."""
    kwargs = dict(
        serve=True,
        num_docs=8,
        capacity=512,
        flush_interval_ms=1,
        init_timeout=60.0,
        watchdog_interval=0.1,
        breaker_threshold=2,
        canary_deadline=0.25,
    )
    kwargs.update(overrides)
    return SupervisedTpuMergeExtension(**kwargs)


class _WedgeableStep:
    """Swappable step factory: pass-through until wedge() is called;
    wedged steps block on the gate, then run the real step — modeling a
    hung device that later completes the in-flight launch. Covers ALL
    THREE device entry points (the dense step, the sparse busy-doc step
    and the run-merge append step — flushes and the canary dispatch
    through one of them). `entered` latches once a dispatch is
    physically blocked on the gate: its caller (timer flush, drain or
    canary) holds the plane flush lock at that point, so tests can wait
    on it before asserting wedge-dependent behavior. Call recover() in
    the test's finally — a blocked executor thread outliving the test
    deadlocks the event-loop teardown."""

    def __init__(self, plane) -> None:
        self.plane = plane
        self.real = plane._step_fn
        self.real_sparse = plane._sparse_step_fn
        self.real_append = plane._append_step_fn
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.wedged = False
        plane._step_fn = self._factory
        plane._sparse_step_fn = self._sparse_factory
        plane._append_step_fn = self._append_factory

    def _factory(self):
        real_step = self.real()
        if not self.wedged:
            return real_step

        def blocked(state, ops):
            self.entered.set()
            self.gate.wait()
            return real_step(state, ops)

        return blocked

    def _sparse_factory(self):
        real_step = self.real_sparse()
        if not self.wedged:
            return real_step

        def blocked(state, ops, slots):
            self.entered.set()
            self.gate.wait()
            return real_step(state, ops, slots)

        return blocked

    def _append_factory(self):
        real_step = self.real_append()
        if not self.wedged:
            return real_step

        def blocked(state, *args):
            self.entered.set()
            self.gate.wait()
            return real_step(state, *args)

        return blocked

    def wedge(self) -> None:
        self.wedged = True
        self.gate.clear()

    def recover(self) -> None:
        self.wedged = False
        self.gate.set()


# -- breaker unit behavior ---------------------------------------------------


def test_circuit_breaker_state_machine():
    breaker = CircuitBreaker(threshold=3)
    assert breaker.state == BREAKER_CLOSED
    assert not breaker.record_failure()
    assert not breaker.record_failure()
    assert breaker.record_failure(), "threshold-th consecutive failure trips"
    assert breaker.state == BREAKER_OPEN
    # half-open probe fails: back to open, no re-trip signal
    assert breaker.try_half_open()
    assert not breaker.record_failure()
    assert breaker.state == BREAKER_OPEN
    # half-open probe passes: closed, recovery signalled
    assert breaker.try_half_open()
    assert breaker.record_success()
    assert breaker.state == BREAKER_CLOSED
    assert breaker.consecutive_failures == 0
    # a lone failure after recovery does not trip
    assert not breaker.record_failure()
    assert breaker.transitions["closed->open"] == 1
    assert breaker.transitions["half_open->closed"] == 1


# -- wedged / late / failed init ---------------------------------------------


async def test_wedged_init_boots_and_serves_within_deadline():
    """THE round-5 defect: a TPU runtime that never initializes must
    not keep the server from serving. Boot completes immediately, a
    provider connects and syncs well within the init deadline, and the
    supervisor lands in DEGRADED (CPU-merge mode) once the deadline
    passes."""
    gate = threading.Event()

    def wedged_factory():
        gate.wait()  # blocks forever: simulated wedged device discovery
        raise AssertionError("never reached in this test")

    ext = SupervisedTpuMergeExtension(
        runtime_factory=wedged_factory, init_timeout=0.5, watchdog_interval=0.05
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="wedged-init")
    b = new_provider(server, name="wedged-init")
    try:
        assert ext.supervisor.state == STATE_INITIALIZING
        # sync completes while init is still wedged (CPU path)
        await wait_synced(a, b, timeout=10)
        a.document.get_text("t").insert(0, "cpu serves")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "cpu serves")
        )
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED)
        )
        assert ext.supervisor.counters["init_timeouts"] == 1
        health = ext.health_status()
        assert health["degraded"] and health["init"]["pending"]
    finally:
        gate.set()  # unblock the daemon thread before teardown
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_late_init_hot_attaches_live_documents():
    """Init completes AFTER the deadline: the plane hot-attaches,
    documents loaded during the degraded window are re-onboarded from
    their CPU snapshots, and serving switches to the plane with no
    content loss in either direction."""
    from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension

    gate = threading.Event()

    def late_factory():
        gate.wait()
        return TpuMergeExtension(
            serve=True, num_docs=8, capacity=512, flush_interval_ms=1
        )

    ext = SupervisedTpuMergeExtension(
        runtime_factory=late_factory, init_timeout=0.2, watchdog_interval=0.05
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="late-doc")
    b = new_provider(server, name="late-doc")
    try:
        await wait_synced(a, b)
        a.document.get_text("t").insert(0, "before;")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "before;")
        )
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED)
        )
        gate.set()  # the runtime finally comes up
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and ext.runtime.is_served("late-doc"),
                ext.supervisor.snapshot(),
            )
        )
        broadcasts_before = ext.plane.counters["plane_broadcasts"]
        a.document.get_text("t").insert(0, "plane;")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "plane;before;")
        )
        # the post-attach frame really rode the plane
        await retryable_assertion(
            lambda: _assert(
                ext.plane.counters["plane_broadcasts"] > broadcasts_before
            )
        )
        # a cold joiner syncs the full state from the plane
        c = new_provider(server, name="late-doc")
        try:
            await wait_synced(c)
            assert c.document.get_text("t").to_string() == "plane;before;"
        finally:
            c.destroy()
        assert ext.supervisor.transitions.get("degraded->ready") == 1
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_failed_init_is_broken_but_server_serves():
    def dead_factory():
        raise RuntimeError("INTERNAL: no TPU platform found (injected)")

    ext = SupervisedTpuMergeExtension(
        runtime_factory=dead_factory, init_timeout=5.0, watchdog_interval=0.05
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="broken-doc")
    b = new_provider(server, name="broken-doc")
    try:
        await retryable_assertion(lambda: _assert(ext.supervisor.state == STATE_BROKEN))
        assert ext.supervisor.counters["init_failures"] == 1
        await wait_synced(a, b)
        a.document.get_text("t").insert(0, "still serving")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "still serving")
        )
        # BROKEN is terminal: no canary probes, no runtime
        assert ext.runtime is None
        health = ext.health_status()
        assert health["state"] == "broken" and not health["init"]["pending"]
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


# -- mid-flight wedge --------------------------------------------------------


async def test_midflight_wedge_trips_breaker_and_drains_to_cpu():
    """The device wedges while docs are plane-served and traffic is in
    flight. The canary overruns its deadline, the breaker opens, served
    docs degrade via the full-state CPU broadcast, sync waiters caught
    behind the wedged flush resolve to the CPU path, and no edit made
    at ANY point is lost."""
    ext = _fast_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="wedge-doc")
    b = new_provider(server, name="wedge-doc")
    joiners = []
    wedge = None
    try:
        await wait_synced(a, b)
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and ext.runtime.is_served("wedge-doc")
            )
        )
        a.document.get_text("t").insert(0, "pre;")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "pre;")
        )
        wedge = _WedgeableStep(ext.plane)
        wedge.wedge()
        # edits DURING the wedge: broadcasts build host-side, and after
        # the trip they ride the CPU fan-out — either way they arrive
        a.document.get_text("t").insert(0, "mid;")
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED),
            timeout=15,
        )
        assert ext.supervisor.breaker.state == BREAKER_OPEN
        assert ext.plane.counters["cpu_fallbacks"] >= 1
        # cold joiners during the wedge sync via the CPU path — the
        # exact "stalled document" scenario the drain prevents
        for _ in range(2):
            c = new_provider(server, name="wedge-doc")
            joiners.append(c)
        await wait_synced(*joiners, timeout=15)
        for c in joiners:
            await retryable_assertion(
                lambda c=c: _assert(
                    c.document.get_text("t").to_string() == "mid;pre;"
                )
            )
        # steady-state edits keep flowing on the CPU path, both ways
        b.document.get_text("t").insert(0, "cpu;")
        await retryable_assertion(
            lambda: _assert(a.document.get_text("t").to_string() == "cpu;mid;pre;")
        )
    finally:
        if wedge is not None:
            wedge.recover()  # let the blocked device thread finish cleanly
        for c in joiners:
            c.destroy()
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_flapping_wedge_recover_wedge_is_accounted():
    """Wedge -> recover (hot re-attach) -> wedge again. Every swing is
    visible in the transition counters, content converges after each
    phase, and the second degradation drains cleanly too."""
    ext = _fast_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="flap-doc")
    b = new_provider(server, name="flap-doc")
    wedge = None
    try:
        await wait_synced(a, b)
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and ext.runtime.is_served("flap-doc")
            )
        )
        wedge = _WedgeableStep(ext.plane)
        expected = ""
        for cycle in range(2):
            # wedge: breaker opens, doc drains to CPU
            wedge.wedge()
            await retryable_assertion(
                lambda: _assert(ext.supervisor.state == STATE_DEGRADED),
                timeout=15,
            )
            frag = f"down{cycle};"
            expected = frag + expected
            a.document.get_text("t").insert(0, frag)
            await retryable_assertion(
                lambda: _assert(b.document.get_text("t").to_string() == expected)
            )
            # recover: half-open canary passes, plane re-attaches
            wedge.recover()
            await retryable_assertion(
                lambda: _assert(
                    ext.supervisor.state == STATE_READY
                    and ext.runtime.is_served("flap-doc"),
                    ext.supervisor.snapshot(),
                ),
                timeout=20,
            )
            frag = f"up{cycle};"
            expected = frag + expected
            a.document.get_text("t").insert(0, frag)
            await retryable_assertion(
                lambda: _assert(b.document.get_text("t").to_string() == expected)
            )
        transitions = ext.supervisor.transitions
        assert transitions.get("ready->degraded") == 2, transitions
        assert transitions.get("degraded->ready") == 2, transitions
        assert ext.supervisor.counters["degrades"] == 2
        # initial attach + two recoveries
        assert ext.supervisor.counters["attaches"] == 3
        breaker_moves = ext.supervisor.breaker.transitions
        assert breaker_moves.get("closed->open") == 2, breaker_moves
        assert breaker_moves.get("half_open->closed") == 2, breaker_moves
        # a late joiner after the flapping sees the complete history
        c = new_provider(server, name="flap-doc")
        try:
            await wait_synced(c)
            await retryable_assertion(
                lambda: _assert(c.document.get_text("t").to_string() == expected)
            )
        finally:
            c.destroy()
    finally:
        if wedge is not None:
            wedge.recover()
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_breaker_open_parks_lane_classes_and_resume_restores():
    """Scheduler-vs-supervisor interaction (tpu/scheduler.py): tripping
    the breaker must PARK the device lane — every queued or new
    flush/hydration/compaction admission defers instead of stacking
    blocked tasks onto the wedged device, while pause-exempt canary
    probes still pass (half-open recovery needs the chip). Recovery
    resumes the lane and admissions flow again."""
    from hocuspocus_tpu.tpu.scheduler import (
        CLASS_BACKGROUND,
        CLASS_CANARY,
        CLASS_CATCHUP,
        CLASS_INTERACTIVE,
        DeviceLane,
        LaneDeferred,
    )

    lane = DeviceLane()
    ext = _fast_ext(lane=lane)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="lane-park-doc")
    b = new_provider(server, name="lane-park-doc")
    wedge = None
    try:
        await wait_synced(a, b)
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and ext.runtime.is_served("lane-park-doc")
            )
        )
        a.document.get_text("t").insert(0, "pre;")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "pre;")
        )
        wedge = _WedgeableStep(ext.plane)
        wedge.wedge()
        a.document.get_text("t").insert(0, "mid;")
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED),
            timeout=15,
        )
        # the trip parked the lane: every non-exempt class defers at the
        # door — flush timers, hydration rounds and compaction sweeps
        # all reschedule instead of queueing against the wedge
        assert lane.paused, "breaker-open must park the device lane"
        deferrals_before = lane.counters["deferrals"]
        for cls in (CLASS_INTERACTIVE, CLASS_CATCHUP, CLASS_BACKGROUND):
            try:
                ticket = await lane.admit(cls, site="test")
            except LaneDeferred:
                continue
            ticket.release()
            raise AssertionError(f"class {cls} admitted through a parked lane")
        assert lane.counters["deferrals"] >= deferrals_before + 3
        # deferred flushes surface in the plane's flight recorder so
        # /debug/docs explains scheduling-induced latency
        from hocuspocus_tpu.observability.flight_recorder import (
            get_flight_recorder,
        )

        b.document.get_text("t").insert(0, "cpu;")  # CPU path keeps flowing
        await retryable_assertion(
            lambda: _assert(a.document.get_text("t").to_string() == "cpu;mid;pre;")
        )
        # recovery: the wedge clears, the half-open canary passes
        # (pause-exempt admission), the lane resumes with serving
        wedge.recover()
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY and not lane.paused,
                ext.supervisor.snapshot(),
            ),
            timeout=20,
        )
        assert lane.class_admissions[CLASS_CANARY] > 0, "canary rode the lane"
        ticket = await lane.admit(CLASS_INTERACTIVE, site="test")
        ticket.release()
        a.document.get_text("t").insert(0, "back;")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "back;cpu;mid;pre;"
            )
        )
        # __plane__ carries the park's paper trail for operators
        events = [
            e["event"] for e in get_flight_recorder().events("__plane__")
        ]
        assert "supervisor.transition" in events
    finally:
        if wedge is not None:
            wedge.recover()
        a.destroy()
        b.destroy()
        await server.destroy()
    # teardown must never leave a (possibly process-global) lane parked
    assert not lane.paused


async def test_abort_pending_resolves_stranded_sync_waiters():
    """A batched sync waiter stranded behind a wedged flush must not
    stall its client: abort_pending resolves it to None (CPU fallback)
    and the later (post-unwedge) drain resolution is a guarded no-op."""
    ext = _fast_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="strand-doc")
    wedge = None
    try:
        await wait_synced(a)
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and ext.runtime.is_served("strand-doc")
            )
        )
        serving = ext.runtime.serving
        # wedge FIRST, then edit: the flush timer (or the canary) takes
        # the dispatch into the gate while holding the plane flush lock,
        # so the batched sync below deterministically strands behind it
        # — editing before wedging races the 1ms timer, which can land
        # the op pre-wedge and let the drain serve real bytes
        wedge = _WedgeableStep(ext.plane)
        wedge.wedge()
        a.document.get_text("t").insert(0, "content")
        await retryable_assertion(
            lambda: _assert(wedge.entered.is_set()), timeout=15
        )
        waiter = asyncio.ensure_future(
            serving.batched_sync("strand-doc", server.documents["strand-doc"], None)
        )
        await asyncio.sleep(0.05)
        assert not waiter.done() or waiter.result() is None
        serving.paused = True
        serving.abort_pending()
        result = await asyncio.wait_for(waiter, 5)
        assert result is None, "stranded waiter must degrade to CPU, not hang"
        # while paused, new sync requests short-circuit to CPU fallback
        assert (
            await serving.batched_sync(
                "strand-doc", server.documents["strand-doc"], None
            )
            is None
        )
    finally:
        if wedge is not None:
            wedge.recover()
        a.destroy()
        await server.destroy()


async def test_healthz_endpoint_reports_plane_state():
    import json

    import aiohttp

    ext = _fast_ext()
    server = await new_hocuspocus(extensions=[ext])
    wedge = None
    try:
        await retryable_assertion(lambda: _assert(ext.supervisor.state == STATE_READY))
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/healthz") as response:
                assert response.status == 200
                body = json.loads(await response.text())
        assert body["status"] == "ok"
        plane = body["extensions"]["SupervisedTpuMergeExtension"]
        assert plane["state"] == "ready" and plane["serving_from_plane"]
        # degrade and re-check: still HTTP 200 (the server serves), but
        # marked degraded so balancers can steer
        wedge = _WedgeableStep(ext.plane)
        wedge.wedge()
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED), timeout=15
        )
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/healthz") as response:
                assert response.status == 200
                body = json.loads(await response.text())
        assert body["status"] == "degraded"
        assert body["extensions"]["SupervisedTpuMergeExtension"]["breaker"][
            "state"
        ] == "open"
    finally:
        if wedge is not None:
            wedge.recover()
        await server.destroy()


async def test_sharded_runtime_under_supervision():
    """shards>1 builds the doc-partitioned router under the same
    supervisor: canaries probe every shard plane, docs on different
    shards serve from their planes, and a wedge in ONE shard still
    degrades (the canary sweep is serving-wide by design — a sick chip
    is a sick chip)."""
    ext = SupervisedTpuMergeExtension(
        shards=2,
        serve=True,
        num_docs=8,
        capacity=512,
        flush_interval_ms=1,
        init_timeout=60.0,
        watchdog_interval=0.1,
        breaker_threshold=2,
        canary_deadline=0.25,
    )
    server = await new_hocuspocus(extensions=[ext])
    writers = []
    readers = []
    wedge = None
    try:
        for d in range(4):
            writers.append(new_provider(server, name=f"shard-sup-{d}"))
            readers.append(new_provider(server, name=f"shard-sup-{d}"))
        await wait_synced(*writers, *readers)
        await retryable_assertion(
            lambda: _assert(
                ext.supervisor.state == STATE_READY
                and all(
                    ext.runtime.is_served(f"shard-sup-{d}") for d in range(4)
                ),
                ext.supervisor.snapshot(),
            )
        )
        for d in range(4):
            writers[d].document.get_text("t").insert(0, f"doc{d};")
        await retryable_assertion(
            lambda: _assert(
                all(
                    readers[d].document.get_text("t").to_string() == f"doc{d};"
                    for d in range(4)
                )
            )
        )
        # wedge one shard's plane: the sweep canary overruns, all docs
        # drain to CPU, edits keep flowing
        wedge = _WedgeableStep(ext.runtime.shards[0].plane)
        wedge.wedge()
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == STATE_DEGRADED), timeout=15
        )
        for d in range(4):
            writers[d].document.get_text("t").insert(0, "cpu;")
        await retryable_assertion(
            lambda: _assert(
                all(
                    readers[d].document.get_text("t").to_string() == f"cpu;doc{d};"
                    for d in range(4)
                )
            )
        )
    finally:
        if wedge is not None:
            wedge.recover()
        for p in writers + readers:
            p.destroy()
        await server.destroy()
