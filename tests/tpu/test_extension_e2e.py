"""TpuMergeExtension in the live server: device mirror tracks clients."""

import asyncio

import numpy as np

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_extension_mirrors_live_documents():
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1)
    server = await new_hocuspocus(extensions=[ext])
    provider_a = new_provider(server, name="mirrored")
    provider_b = new_provider(server, name="mirrored")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "hello ")
        provider_b.document.get_text("t").insert(0, "world ")

        def mirrored():
            ext.plane.flush()
            device = ext.plane.text("mirrored")
            cpu = server.documents["mirrored"].get_text("t").to_string()
            assert device == cpu and len(cpu) == 12

        await retryable_assertion(mirrored)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_extension_releases_slot_on_unload():
    ext = TpuMergeExtension(num_docs=2, capacity=256, flush_interval_ms=1)
    server = await new_hocuspocus(extensions=[ext])
    provider = new_provider(server, name="transient")
    try:
        await wait_synced(provider)
        assert "transient" in ext.plane.docs
        provider.destroy()
        await retryable_assertion(lambda: _assert("transient" not in ext.plane.docs))
    finally:
        await server.destroy()


def test_state_vector_diff_kernel():
    """Catch-up storm primitive (BASELINE config 5): batched SV diff."""
    import jax.numpy as jnp

    from hocuspocus_tpu.tpu.kernels import state_vector_diff

    # 4 docs, 3 client slots
    server_clocks = jnp.asarray(
        [[100, 50, 0], [10, 0, 0], [7, 7, 7], [0, 0, 0]], jnp.int32
    )
    client_clocks = jnp.asarray(
        [[80, 50, 0], [10, 0, 0], [0, 9, 7], [0, 0, 0]], jnp.int32
    )
    missing_from, missing_len = state_vector_diff(server_clocks, client_clocks)
    np.testing.assert_array_equal(
        np.asarray(missing_len),
        [[20, 0, 0], [0, 0, 0], [7, 0, 0], [0, 0, 0]],
    )
    np.testing.assert_array_equal(
        np.asarray(missing_from),
        [[80, 50, 0], [10, 0, 0], [0, 7, 7], [0, 0, 0]],
    )
