"""Pallas RLE integrate kernel vs the vmapped XLA reference path.

Runs in Pallas interpret mode on the virtual CPU backend (conftest);
the identical kernel code compiles via Mosaic on real TPU (bench.py
RLE section). Exact array equality is required: both paths apply the
same op sequence with the same append discipline, so every entry lane
must match, not just the expanded unit order.
"""

import numpy as np

from hocuspocus_tpu.tpu.kernels import NONE_CLIENT, OpBatch
from hocuspocus_tpu.tpu.kernels_rle import (
    integrate_op_slots_rle,
    make_empty_rle_state,
)
from hocuspocus_tpu.tpu.pallas_kernels_rle import (
    _pick_block_rle,
    integrate_op_slots_rle_pallas,
)

from tests.tpu.test_pallas_kernels import _CLIENTS, _random_stream


def test_pallas_rle_matches_xla_scan_fuzz():
    rng = np.random.default_rng(11)
    num_docs, entries, num_slots = 16, 128, 6
    next_clock = np.zeros((len(_CLIENTS), num_docs), np.int64)
    state_a = make_empty_rle_state(num_docs, entries)
    state_b = make_empty_rle_state(num_docs, entries)
    for _ in range(3):
        ops = _random_stream(rng, num_docs, num_slots, next_clock)
        state_a, ca = integrate_op_slots_rle(state_a, ops)
        state_b, cb = integrate_op_slots_rle_pallas(state_b, ops, interpret=True)
        assert int(ca) == int(cb)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pallas_rle_overflow_and_deps():
    """Entry-capacity overflow and missing-origin ops behave identically."""
    import jax.numpy as jnp

    num_docs, entries = 8, 4
    state_a = make_empty_rle_state(num_docs, entries)
    state_b = make_empty_rle_state(num_docs, entries)
    mk = lambda arr, dt: jnp.asarray(np.asarray(arr, dt))
    # slots: 3 tail appends fit the 4-entry arena (num_runs+2<=4 holds
    # through num_runs=2); the 4th op then fails BOTH the capacity
    # margin (3+2>4 => sticky overflow) and its unknown left origin
    kind = mk([[1] * num_docs] * 4, np.int32)
    client = mk([[7] * num_docs] * 4, np.uint32)
    clock = mk([[0] * num_docs, [8] * num_docs, [16] * num_docs, [99] * num_docs], np.int32)
    run_len = mk([[8] * num_docs, [8] * num_docs, [8] * num_docs, [1] * num_docs], np.int32)
    lc = mk(
        [[NONE_CLIENT] * num_docs, [7] * num_docs, [7] * num_docs, [12345] * num_docs],
        np.uint32,
    )
    lk = mk([[0] * num_docs, [7] * num_docs, [15] * num_docs, [0] * num_docs], np.int32)
    rc = mk([[NONE_CLIENT] * num_docs] * 4, np.uint32)
    rk = mk([[0] * num_docs] * 4, np.int32)
    ops = OpBatch(kind, client, clock, run_len, lc, lk, rc, rk)
    state_a, _ = integrate_op_slots_rle(state_a, ops)
    state_b, _ = integrate_op_slots_rle_pallas(state_b, ops, interpret=True)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    assert bool(np.asarray(state_b.overflow).all())  # 4th insert overflowed
    assert (np.asarray(state_b.total_units) == 24).all()  # 3 applied, 4th skipped
    assert (np.asarray(state_b.num_runs) == 3).all()


def test_pick_block_rle_respects_vmem():
    from hocuspocus_tpu.tpu.pallas_kernels_rle import _LIVE_BUFFERS, _VMEM_BUDGET

    assert _pick_block_rle(8192, 1024) == 64
    assert _pick_block_rle(7, 1024) == 0
    for docs, entries in ((8192, 1024), (100_000, 2048), (2048, 16384)):
        db = _pick_block_rle(docs, entries)
        if db:
            assert _LIVE_BUFFERS * db * entries * 4 <= _VMEM_BUDGET


def test_pallas_rle_compile_failure_falls_back(monkeypatch):
    import hocuspocus_tpu.tpu.pallas_kernels_rle as pkr

    calls = {"pallas": 0}

    def boom(state, ops, interpret):
        calls["pallas"] += 1
        raise RuntimeError("Mosaic says no (simulated)")

    monkeypatch.setattr(pkr, "_integrate_pallas_rle", boom)
    monkeypatch.setattr(pkr, "_pallas_rle_broken_shapes", set())
    num_docs, entries = 64, 64
    state = make_empty_rle_state(num_docs, entries)
    ops = OpBatch(
        kind=np.ones((2, num_docs), np.int32),
        client=np.full((2, num_docs), 7, np.uint32),
        clock=np.asarray([[0] * num_docs, [4] * num_docs], np.int32),
        run_len=np.full((2, num_docs), 4, np.int32),
        left_client=np.asarray([[NONE_CLIENT] * num_docs, [7] * num_docs], np.uint32),
        left_clock=np.asarray([[0] * num_docs, [3] * num_docs], np.int32),
        right_client=np.full((2, num_docs), NONE_CLIENT, np.uint32),
        right_clock=np.zeros((2, num_docs), np.int32),
    )
    state, count = pkr.integrate_op_slots_rle_pallas(state, ops)
    assert int(count) == 2 * num_docs
    assert (np.asarray(state.total_units) == 8).all()
    assert calls["pallas"] == 1
    state, _ = pkr.integrate_op_slots_rle_pallas(state, ops)
    assert calls["pallas"] == 1  # broken shape not retried
