"""Fuzz the sequence-granular plane against the CPU engine.

Random mixed-content edit streams (plain/rich text, maps, arrays, XML
trees, nested types) are applied as wire updates to BOTH a CPU doc and
a MergePlane; after every flush the plane must (a) stay healthy with
zero unsupported retires, and (b) serve SyncStep2 bytes that rebuild a
doc equal to the CPU doc (json/delta comparison). This hammers the new
routing paths (wire parents, origin-id lookup, map successor chains,
delete splitting across sequences) far beyond the hand-written cases.
"""

import numpy as np
import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.serving import PlaneServing

WORDS = ["alpha", "béta", "γ", "𝕕elta", "e", "zz "]


def _pair_align(text, pos: int) -> int:
    """Snap a UTF-16 position out of the middle of a surrogate pair.

    Real editors never emit mid-pair positions; a boundary inside a
    pair triggers the yjs ContentString.splice U+FFFD replacement on
    the editing doc, which wire-replaying peers (including the
    reference's own remote yjs docs) do NOT reproduce — see
    test_surrogate_split_wart_matches_reference_semantics."""
    # build the COUNTABLE unit stream (embeds occupy one indexable unit
    # but are invisible in to_string(), so to_string()-based alignment
    # reads the wrong unit once embeds exist)
    units: list[int] = []
    for op in text.to_delta():
        ins = op.get("insert")
        if isinstance(ins, str):
            data = ins.encode("utf-16-le")
            units.extend(
                int.from_bytes(data[i : i + 2], "little")
                for i in range(0, len(data), 2)
            )
        else:
            units.append(-1)  # embed: one countable, non-surrogate unit
    if 0 < pos < len(units):
        if 0xD800 <= units[pos - 1] <= 0xDBFF:  # boundary splits a pair
            return pos + 1
    return pos


def _random_edit(rng, doc: Doc, step: int) -> None:
    kind = rng.integers(0, 8)
    if kind == 0:  # plain text insert
        text = doc.get_text("t")
        pos = _pair_align(text, int(rng.integers(0, len(text) + 1)))
        text.insert(pos, WORDS[rng.integers(0, len(WORDS))])
    elif kind == 1:  # text delete
        text = doc.get_text("t")
        if len(text) > 0:
            pos = _pair_align(text, int(rng.integers(0, len(text))))
            if pos < len(text):
                end = _pair_align(
                    text, min(pos + int(rng.integers(1, 4)), len(text))
                )
                if end > pos:
                    text.delete(pos, end - pos)
    elif kind == 2:  # rich format
        text = doc.get_text("t")
        if len(text) > 1:
            pos = _pair_align(text, int(rng.integers(0, len(text) - 1)))
            end = _pair_align(
                text, min(pos + int(rng.integers(1, 5)), len(text))
            )
            if end > pos:
                attr = ["bold", "em"][rng.integers(0, 2)]
                text.format(pos, end - pos, {attr: bool(rng.integers(0, 2))})
    elif kind == 3:  # map set (LWW churn on few keys)
        doc.get_map("m").set(f"k{rng.integers(0, 3)}", int(rng.integers(0, 100)))
    elif kind == 4:  # map delete
        key = f"k{rng.integers(0, 3)}"
        if doc.get_map("m").get(key) is not None:
            doc.get_map("m").delete(key)
    elif kind == 5:  # array ops
        arr = doc.get_array("a")
        if rng.integers(0, 3) == 0 and len(arr) > 0:
            pos = int(rng.integers(0, len(arr)))
            arr.delete(pos, min(int(rng.integers(1, 3)), len(arr) - pos))
        else:
            pos = int(rng.integers(0, len(arr) + 1))
            arr.insert(pos, [int(step), f"s{step}"])
    elif kind == 6:  # xml tree growth
        from hocuspocus_tpu.crdt import YXmlElement, YXmlText

        frag = doc.get_xml_fragment("x")
        if rng.integers(0, 2) == 0 or len(frag) == 0:
            element = YXmlElement("p")
            frag.insert(int(rng.integers(0, len(frag) + 1)), [element])
        else:
            element = frag.get(int(rng.integers(0, len(frag))))
            if rng.integers(0, 2) == 0:
                element.set_attribute(f"a{rng.integers(0, 2)}", f"v{step}")
            else:
                if len(element) == 0:
                    element.insert(0, [YXmlText(f"w{step}")])
                else:
                    child = element.get(0)
                    child.insert(int(rng.integers(0, len(child) + 1)), "y")
    else:  # embed
        text = doc.get_text("t")
        pos = _pair_align(text, int(rng.integers(0, len(text) + 1)))
        text.insert_embed(pos, {"n": int(step)})


def _doc_fingerprint(doc: Doc):
    def xml_shape(frag):
        out = []
        for i in range(len(frag)):
            node = frag.get(i)
            if hasattr(node, "node_name"):
                out.append((node.node_name, node.get_attributes(), xml_shape(node)))
            else:
                out.append(node.to_string())
        return out

    return (
        doc.get_text("t").to_delta(),
        dict(doc.get_map("m").to_json()),
        doc.get_array("a").to_json(),
        xml_shape(doc.get_xml_fragment("x")),
    )


@pytest.mark.parametrize("arena", ["unit", "rle"])
@pytest.mark.parametrize("seed", [1, 7, 23])
def test_plane_fuzz_mixed_content_serves_cpu_equal(seed, arena):
    rng = np.random.default_rng(seed)
    cpu = Doc()
    updates = []
    cpu.on("update", lambda update, *rest: updates.append(update))

    plane = MergePlane(num_docs=64, capacity=2048, arena=arena)
    serving = PlaneServing(plane)
    plane.register("fuzz")

    for step in range(120):
        _random_edit(rng, cpu, step)
        while updates:
            plane.enqueue_update("fuzz", updates.pop(0))
        if step % 10 == 9:
            plane.flush()
            serving.refresh()
            assert plane.is_supported("fuzz"), (
                seed,
                step,
                {k: v for k, v in plane.counters.items() if v},
            )
            served = serving.encode_state_as_update("fuzz", cpu, None)
            assert served is not None, (seed, step)
            rebuilt = Doc()
            apply_update(rebuilt, served)
            assert _doc_fingerprint(rebuilt) == _doc_fingerprint(cpu), (seed, step)

    # final: a fresh peer applying the CPU snapshot equals one applying
    # the served bytes (cross-validates our own encoder too)
    plane.flush()
    serving.refresh()
    served = serving.encode_state_as_update("fuzz", cpu, None)
    direct = Doc()
    apply_update(direct, encode_state_as_update(cpu))
    via_plane = Doc()
    apply_update(via_plane, served)
    assert _doc_fingerprint(via_plane) == _doc_fingerprint(direct)


def test_surrogate_split_wart_matches_reference_semantics():
    """Documents an inherited yjs wart, and pins which side the plane is
    on: when an edit boundary lands INSIDE a surrogate pair and leaves
    no wire anchor at the split point, the EDITING doc replaces both
    halves with U+FFFD (yjs ContentString.splice, faithfully mirrored
    by our CPU engine) while every wire-replaying peer — a remote yjs
    doc in the reference deployment, or our plane — keeps the intact
    pair. This is a CPU-vs-CPU divergence in the reference ecosystem
    itself (editors avoid mid-pair positions); the plane serves what a
    remote peer would compute."""
    editor = Doc()
    updates = []
    editor.on("update", lambda update, *rest: updates.append(update))
    text = editor.get_text("t")
    text.insert(0, "x𝕕")
    text.format(0, 2, {})  # boundary at UTF-16 index 2: inside the pair

    # the editing doc took the U+FFFD replacement...
    assert "�" in editor.get_text("t").to_string()

    # ...a wire-replaying CPU peer did not (reference remote semantics)
    peer = Doc()
    for update in updates:
        apply_update(peer, update)
    assert peer.get_text("t").to_string() == "x𝕕"

    # the plane sides with the remote peer: healthy, intact pair,
    # and its served bytes rebuild the peer's content
    plane = MergePlane(num_docs=4, capacity=256)
    serving = PlaneServing(plane)
    plane.register("d")
    for update in updates:
        plane.enqueue_update("d", update)
    plane.flush()
    serving.refresh()
    assert plane.text("d") == "x𝕕"
    served = serving.encode_state_as_update("d", peer, None)
    rebuilt = Doc()
    apply_update(rebuilt, served)
    assert rebuilt.get_text("t").to_string() == "x𝕕"


@pytest.mark.parametrize("arena", ["unit", "rle"])
@pytest.mark.parametrize("seed", [3, 11])
def test_plane_fuzz_concurrent_editors_converge(seed, arena):
    """Two editors mutate independent replicas; updates cross-apply in
    randomized order (buffering out-of-causal-order arrivals), and the
    plane — fed the same interleaved stream the server would see — must
    serve bytes that rebuild the converged doc. Stresses YATA conflict
    windows, same-origin sibling ordering, and pending-op buffering in
    the lowerer far beyond the single-editor fuzz."""
    rng = np.random.default_rng(seed)
    a, b = Doc(), Doc()
    out_a, out_b = [], []
    a.on("update", lambda update, *rest: out_a.append(update))
    b.on("update", lambda update, *rest: out_b.append(update))

    plane = MergePlane(num_docs=64, capacity=4096, arena=arena)
    serving = PlaneServing(plane)
    plane.register("conc")

    def cross_deliver():
        """Flush pending updates between replicas + the plane, DRAINING
        cascades: applying a remote update can itself emit a new update
        (the formatting-hygiene pass deletes redundant markers in a
        nested transaction) — a real provider broadcasts those, so the
        relay must not snapshot-and-drop them."""
        for _ in range(8):
            if not out_a and not out_b:
                break
            batch_a, batch_b = out_a[:], out_b[:]
            out_a.clear()
            out_b.clear()
            # the plane sees BOTH clients' updates in arbitrary interleave
            pending = batch_a + batch_b
            rng.shuffle(pending)
            for update in pending:
                plane.enqueue_update("conc", update)
            for update in batch_a:
                apply_update(b, update)
            for update in batch_b:
                apply_update(a, update)
        assert not out_a and not out_b, "cleanup cascade did not settle"

    for round_no in range(12):
        # each round: both editors make a few INDEPENDENT edits (true
        # concurrency: neither has seen the other's round yet)
        for doc in (a, b):
            for step in range(int(rng.integers(1, 5))):
                _random_edit(rng, doc, round_no * 100 + step)
        cross_deliver()
        assert a.store.get_state_vector() == b.store.get_state_vector()
        assert _doc_fingerprint(a) == _doc_fingerprint(b), (seed, round_no)

        plane.flush()
        serving.refresh()
        assert plane.is_supported("conc"), (
            seed,
            round_no,
            {k: v for k, v in plane.counters.items() if v},
        )
        served = serving.encode_state_as_update("conc", a, None)
        assert served is not None, (seed, round_no)
        rebuilt = Doc()
        apply_update(rebuilt, served)
        assert _doc_fingerprint(rebuilt) == _doc_fingerprint(a), (seed, round_no)


@pytest.mark.parametrize("seed", [5, 17])
def test_plane_fuzz_reload_from_gc_snapshot(seed):
    """Simulates the server reload path mid-stream: every ~30 steps a
    FRESH plane loads the doc from a snapshot (which may contain GC
    structs once tree deletions ran) and must keep serving the ongoing
    edit stream. Covers GC lowering, snapshot overlap dedup, and
    routing continuity across reloads."""
    rng = np.random.default_rng(seed)
    cpu = Doc()
    updates = []
    cpu.on("update", lambda update, *rest: updates.append(update))

    def tree_delete(step):
        frag = cpu.get_xml_fragment("x")
        if len(frag) > 0:
            frag.delete(int(rng.integers(0, len(frag))), 1)

    plane = MergePlane(num_docs=64, capacity=4096)
    serving = PlaneServing(plane)
    plane.register("r")

    for step in range(90):
        if step % 7 == 6:
            tree_delete(step)  # creates gc'd subtrees in later snapshots
        else:
            _random_edit(rng, cpu, step)
        while updates:
            plane.enqueue_update("r", updates.pop(0))
        if step % 30 == 29:
            # "server restart": fresh plane, loaded from the snapshot
            plane = MergePlane(num_docs=64, capacity=4096)
            serving = PlaneServing(plane)
            plane.register("r")
            plane.enqueue_update("r", encode_state_as_update(cpu))
        if step % 10 == 9:
            plane.flush()
            serving.refresh()
            assert plane.is_supported("r"), (
                seed,
                step,
                {k: v for k, v in plane.counters.items() if v},
            )
            served = serving.encode_state_as_update("r", cpu, None)
            assert served is not None, (seed, step)
            rebuilt = Doc()
            apply_update(rebuilt, served)
            assert _doc_fingerprint(rebuilt) == _doc_fingerprint(cpu), (seed, step)


@pytest.mark.parametrize("seed", [8, 21])
async def test_plane_fuzz_recycle_churn_with_concurrent_editors(seed):
    """Randomized paragraph churn from two live editors over a small
    serve-mode plane: recycles, plane_full retires and CPU fallbacks
    interleave with live traffic, and every replica must converge.

    Seed 8 of this harness found the collected-parent integration crash
    (an item whose wire parent was concurrently deleted and collected
    raised instead of integrating parentless, silently diverging the
    sender's peer — see tests/crdt/test_core.py regression).
    """
    import asyncio
    import random

    from hocuspocus_tpu.crdt import YXmlElement, YXmlText
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, wait_synced

    rng = random.Random(seed)
    ext = TpuMergeExtension(
        num_docs=rng.choice([16, 24]),
        capacity=rng.choice([256, 512]),
        flush_interval_ms=1,
        serve=True,
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="rf")
    b = new_provider(server, name="rf")
    try:
        await wait_synced(a, b)
        for wave in range(rng.randint(8, 16)):
            for who, p in (("a", a), ("b", b)):
                frag = p.document.get_xml_fragment("x")
                if rng.random() < 0.9:
                    el = YXmlElement("paragraph")
                    frag.push([el])
                    text = YXmlText()
                    el.push([text])
                    text.insert(0, f"{who}{wave} " * rng.randint(2, 12))
                while len(frag) > rng.randint(2, 4):
                    frag.delete(0, 1)
            await asyncio.sleep(rng.choice([0.0, 0.01, 0.03]))

        from tests.utils import retryable_assertion

        def converged():
            fa = a.document.get_xml_fragment("x").to_string()
            fb = b.document.get_xml_fragment("x").to_string()
            fs = server.documents["rf"].get_xml_fragment("x").to_string()
            assert fa == fb == fs, (
                seed,
                {k: v for k, v in ext.plane.counters.items() if v},
                len(fa),
                len(fb),
                len(fs),
            )

        await retryable_assertion(converged, timeout=30)
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


@pytest.mark.parametrize("seed", [3, 17])
async def test_plane_fuzz_concurrent_mixed_map_array_text_live_server(seed):
    """Two live editors racing LWW map writes/deletes, array inserts
    and text edits on ONE doc through the serve-mode server: all three
    replicas (both editors + server doc) converge on every root type.
    Complements the single-editor mixed-content fuzz (above) with the
    concurrent case, config-4's content shape."""
    import asyncio
    import random

    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import (
        new_hocuspocus,
        new_provider,
        retryable_assertion,
        wait_synced,
    )

    rng = random.Random(seed)
    ext = TpuMergeExtension(num_docs=16, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="mixed")
    b = new_provider(server, name="mixed")
    try:
        await wait_synced(a, b)
        keys = [f"k{i}" for i in range(6)]
        for step in range(rng.randint(20, 40)):
            for who, p in (("a", a), ("b", b)):
                r = rng.random()
                m = p.document.get_map("mm")
                arr = p.document.get_array("aa")
                t = p.document.get_text("tt")
                if r < 0.35:
                    m.set(rng.choice(keys), f"{who}{step}-{rng.randint(0, 99)}")
                elif r < 0.45 and len(m.keys()) > 0:
                    m.delete(rng.choice(list(m.keys())))
                elif r < 0.7:
                    arr.insert(rng.randint(0, len(arr)), [f"{who}{step}"])
                elif r < 0.8 and len(arr) > 0:
                    arr.delete(rng.randrange(len(arr)), 1)
                elif r < 0.95:
                    t.insert(rng.randint(0, len(t)), f"{who}{step} ")
                elif len(t) > 4:
                    t.delete(0, 3)
            if rng.random() < 0.4:
                await asyncio.sleep(rng.choice([0.0, 0.005, 0.02]))

        def converged():
            sdoc = server.documents["mixed"]
            for x in (a.document, b.document):
                assert dict(x.get_map("mm").to_json()) == dict(
                    sdoc.get_map("mm").to_json()
                )
                assert x.get_array("aa").to_json() == sdoc.get_array("aa").to_json()
                assert x.get_text("tt").to_string() == sdoc.get_text("tt").to_string()

        await retryable_assertion(converged, timeout=30)
        assert ext.plane.counters["cpu_fallbacks"] == 0
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
