"""Join-storm sync cache (tpu/serving.SyncFrameCache).

N clients joining the same doc with the same state vector between
flushes must pay ONE encode; any state change — integrated ops, a
flush-epoch bump, compaction, eviction/unload — must invalidate.
"""

import asyncio

from hocuspocus_tpu.crdt import Doc, encode_state_as_update, encode_state_vector
from hocuspocus_tpu.tpu import TpuMergeExtension
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.serving import PlaneServing, SyncFrameCache
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


def _plane_with_doc(name="cached", text="the cached payload "):
    plane = MergePlane(num_docs=4, capacity=512)
    serving = PlaneServing(plane)
    ref = Doc()
    ref.get_text("t").insert(0, text)
    plane.register(name)
    plane.enqueue_update(name, encode_state_as_update(ref))
    return plane, serving, ref


def test_join_storm_pays_one_encode():
    plane, serving, ref = _plane_with_doc()
    payloads = [
        serving.encode_state_as_update("cached", ref, None) for _ in range(8)
    ]
    assert payloads[0] is not None
    assert all(p == payloads[0] for p in payloads)
    assert plane.counters["sync_cache_misses"] == 1
    assert plane.counters["sync_cache_hits"] == 7


def test_stale_sv_joiners_share_an_entry_distinct_from_cold():
    """The cache keys on the cutoff map, not just 'cold': N stale
    reconnects with the same SV share one encode, and don't collide
    with cold joiners."""
    plane, serving, ref = _plane_with_doc()
    stale_sv = encode_state_vector(ref)  # fully current -> empty diff
    ref.get_text("t").insert(0, "tail ")
    plane.enqueue_update("cached", encode_state_as_update(ref, stale_sv))
    cold = [serving.encode_state_as_update("cached", ref, None) for _ in range(3)]
    stale = [
        serving.encode_state_as_update("cached", ref, stale_sv) for _ in range(3)
    ]
    assert all(p == cold[0] for p in cold)
    assert all(p == stale[0] for p in stale)
    assert cold[0] != stale[0], "different SVs must not share bytes"
    assert plane.counters["sync_cache_misses"] == 2  # one per distinct SV
    assert plane.counters["sync_cache_hits"] == 4


def test_cache_invalidates_on_new_ops_and_flush_epoch_bump():
    plane, serving, ref = _plane_with_doc()
    first = serving.encode_state_as_update("cached", ref, None)
    assert plane.counters["sync_cache_misses"] == 1

    # integrated ops + the flush they ride bump the epoch: next serve
    # must re-encode (and carry the new content)
    ref.get_text("t").insert(0, "fresh ")
    tail = encode_state_as_update(ref)
    plane.enqueue_update("cached", tail)
    epoch_before = plane.flush_epoch
    second = serving.encode_state_as_update("cached", ref, None)
    assert plane.flush_epoch > epoch_before
    assert second != first
    assert plane.counters["sync_cache_misses"] == 2

    # a pure epoch bump (no log change) also invalidates: the key is
    # epoch-scoped by construction
    plane.flush_epoch += 1
    third = serving.encode_state_as_update("cached", ref, None)
    assert third == second  # same bytes, but re-encoded
    assert plane.counters["sync_cache_misses"] == 3


def test_forget_drops_doc_entries_eviction_path():
    """serving.forget — the eviction/unload/degrade teardown — must
    drop the doc's cache entries (and count them as evictions)."""
    plane, serving, ref = _plane_with_doc()
    serving.encode_state_as_update("cached", ref, None)
    assert "cached" in serving._sync_cache
    serving.forget("cached", plane.docs.get("cached"))
    assert "cached" not in serving._sync_cache
    assert not serving._sync_cache
    assert serving._sync_cache.evictions == 1


def test_per_doc_lru_bound():
    cache = SyncFrameCache()
    doc = object()
    for i in range(cache.PER_DOC_CAP + 5):
        cache.put("doc", doc, ("epoch",), (("sv", i),), b"payload-%d" % i)
    assert len(cache) == cache.PER_DOC_CAP
    assert cache.evictions == 5
    # oldest evicted, newest retained
    assert cache.get("doc", doc, ("epoch",), (("sv", 0),)) is None
    assert cache.get("doc", doc, ("epoch",), (("sv", cache.PER_DOC_CAP + 4),)) is not None


def test_stale_doc_identity_misses():
    """A re-registered doc (fresh PlaneDoc) must never serve the old
    registration's bytes."""
    cache = SyncFrameCache()
    old_doc, new_doc = object(), object()
    cache.put("doc", old_doc, ("e",), (), b"old")
    assert cache.get("doc", new_doc, ("e",), ()) is None
    assert cache.get("doc", old_doc, ("e",), ()) is None, "stale entry dropped"


async def test_cache_invalidates_on_compaction():
    """On-device compaction rebuilds the serve log and re-binds slots:
    the post-compaction serve must re-encode, not replay cached bytes
    from the pre-compaction layout."""
    from hocuspocus_tpu.tpu.residency import ResidencyManager

    plane = MergePlane(num_docs=4, capacity=64)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving)
    ref = Doc()
    text = ref.get_text("t")
    text.insert(0, "abcdefghij" * 3)
    plane.register("compactee")
    plane.enqueue_update("compactee", encode_state_as_update(ref))
    # tombstone most of the row so compaction has something to reclaim
    text.delete(0, 25)
    plane.enqueue_update("compactee", encode_state_as_update(ref))
    plane.flush()
    serving.refresh()
    before = serving.encode_state_as_update("compactee", ref, None)
    assert before is not None
    assert "compactee" in serving._sync_cache
    compacted = await mgr.compact_doc_locked("compactee")
    assert compacted, "test setup: compaction should have run"
    assert "compactee" not in serving._sync_cache, "compaction must forget"
    serving.refresh()
    after = serving.encode_state_as_update("compactee", ref, None)
    assert after is not None
    applied = Doc()
    from hocuspocus_tpu.crdt import apply_update

    apply_update(applied, after)
    assert applied.get_text("t").to_string() == text.to_string()


async def test_e2e_join_storm_hits_cache(monkeypatch):
    """Through the real server: concurrent cold joiners of one served
    doc share the cached SyncStep2 payload."""
    ext = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    writer = new_provider(server, name="stormed")
    joiners = []
    try:
        await wait_synced(writer)
        writer.document.get_text("body").insert(0, "storm payload")
        await retryable_assertion(
            lambda: _assert(ext.plane.text("stormed") == "storm payload")
        )
        misses_before = ext.plane.counters["sync_cache_misses"]
        joiners = [new_provider(server, name="stormed") for _ in range(6)]
        await wait_synced(*joiners)
        for joiner in joiners:
            assert joiner.document.get_text("body").to_string() == "storm payload"
        assert ext.plane.counters["sync_cache_hits"] >= 3
        # one encode per distinct state the storm observed, not per joiner
        assert ext.plane.counters["sync_cache_misses"] - misses_before <= 3
    finally:
        writer.destroy()
        for joiner in joiners:
            joiner.destroy()
        await server.destroy()
