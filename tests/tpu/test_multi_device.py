"""Multi-device merge cells (tpu/cells.py): one arena + lane + governor
per chip, rendezvous doc placement, load-aware rebalancing over the
evict-snapshot→hydrate migration rail, and per-cell breaker scope.

Runs on the conftest's 8-device forced-host CPU mesh, so placement,
migration and per-device lane accounting are exercised with REAL
distinct jax devices. The acceptance invariants pinned here:

- docs spread across all devices (no device owns >2x the mean after
  rebalance under hot-doc skew);
- doc migration loses zero acknowledged updates under concurrent edits
  and never disconnects a client;
- per-device lane dispatch accounting shows zero bypass across the
  whole serving pipeline;
- the multi-device plane's served state is byte-identical to the
  single-device plane's under a fuzzed mixed workload;
- a sick chip degrades its cell only (supervisor per-device breakers).
"""

import asyncio

import jax
import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.tpu.cells import (
    DevicePlacement,
    MultiDeviceMergeExtension,
    plan_migrations,
)
from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension
from hocuspocus_tpu.tpu.scheduler import DeviceLane
from tests.tpu.test_scheduler import _scripted_workload
from tests.utils import (
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_synced,
)


def _assert(cond, detail=None):
    assert cond, detail


@pytest.fixture(autouse=True)
def _fresh_lanes():
    """Per-device lanes are process-global (`get_device_lane(i)`):
    without a reset, one test's teardown dispatches pollute the next
    test's lane accounting."""
    from hocuspocus_tpu.tpu.scheduler import reset_device_lane

    reset_device_lane()
    yield
    reset_device_lane()


def _cells_ext(devices=4, **kwargs) -> MultiDeviceMergeExtension:
    kwargs.setdefault("num_docs", 16)
    kwargs.setdefault("capacity", 2048)
    kwargs.setdefault("flush_interval_ms", 1)
    kwargs.setdefault("rebalance_interval_s", 0)  # tests tick manually
    return MultiDeviceMergeExtension(devices=devices, **kwargs)


# -- placement ----------------------------------------------------------------


def test_placement_spreads_docs_and_moves_minimally():
    placement = DevicePlacement(8)
    names = [f"doc-{i}" for i in range(400)]
    owners = {name: placement.place(name) for name in names}
    counts = [0] * 8
    for owner in owners.values():
        counts[owner] += 1
    mean = len(names) / 8
    assert max(counts) < 2 * mean, counts
    assert min(counts) > 0, counts
    # minimal movement: marking one cell down moves ONLY its docs
    placement.mark_down(3)
    moved = {n for n in names if placement.place(n) != owners[n]}
    assert moved == {n for n, o in owners.items() if o == 3}
    assert all(placement.place(n) != 3 for n in moved)
    placement.mark_up(3)
    assert all(placement.place(n) == owners[n] for n in names)
    # override precedence: wins while healthy, falls through when down
    placement.set_override("doc-0", 5)
    assert placement.place("doc-0") == 5
    placement.mark_down(5)
    assert placement.place("doc-0") == owners["doc-0"]
    placement.mark_up(5)
    before = placement.placement_hash()
    placement.clear_override("doc-0")
    assert placement.placement_hash() != before  # hash tracks the map


def _projected(cell_work, moves, doc_work):
    work = [float(w) for w in cell_work]
    for name, src, dst in moves:
        weight = doc_work[src][name]
        work[src] -= weight
        work[dst] += weight
    return work


def test_plan_migrations_moves_small_docs_not_an_unimprovable_mega():
    # cell 0 hot: one mega doc + small docs; peers carry real load, so
    # relocating the mega could not improve anything — the small docs
    # stacked under it move instead
    doc_work = [
        {"mega": 5000.0, "s1": 60.0, "s2": 50.0, "s3": 40.0},
        {"a": 300.0},
        {"b": 250.0},
        {"c": 280.0},
    ]
    cell_work = [sum(w.values()) for w in doc_work]
    moves = plan_migrations(
        cell_work, doc_work, healthy={0, 1, 2, 3}, ratio=1.5,
        min_excess=10.0, batch=8,
    )
    assert moves, "hot cell must shed"
    moved_docs = {name for name, _src, _dst in moves}
    assert "mega" not in moved_docs, moves
    assert moved_docs <= {"s1", "s2", "s3"}
    assert all(src == 0 for _n, src, _d in moves)
    # every plan strictly improves the skew
    projected = _projected(cell_work, moves, doc_work)
    assert max(projected) <= max(cell_work)


def test_plan_migrations_spreads_stacked_hot_docs():
    # two hot docs STACKED on one chip: at least one moves to a cold
    # chip — "hot docs spread across chips instead of stacking"
    doc_work = [{"hot-a": 1000.0, "hot-b": 900.0}, {"x": 10.0}, {"y": 5.0}]
    cell_work = [1900.0, 10.0, 5.0]
    moves = plan_migrations(
        cell_work, doc_work, healthy={0, 1, 2}, ratio=1.2,
        min_excess=10.0, batch=4,
    )
    moved = {name: dst for name, _src, dst in moves}
    assert "hot-a" in moved or "hot-b" in moved, moves
    projected = _projected(cell_work, moves, doc_work)
    # the stacked pair ends up split: no chip carries both hot docs
    assert max(projected) < max(cell_work)
    assert max(projected) <= 1100.0, projected


def test_rebalance_plan_sheds_rows_when_occupancy_is_the_hot_signal():
    """Finding from review: occupancy/HBM pressure must drive
    migrations even when dispatched WORK is balanced — the plan flips
    to rows attribution (freeing rows is what those signals need)."""
    ext = _cells_ext(devices=4, num_docs=16)
    try:
        stats = []
        for i in range(4):
            hot = i == 0
            stats.append(
                {
                    "cell": i,
                    "device": str(i),
                    "healthy": True,
                    "docs": 8 if hot else 2,
                    "rows_in_use": 14 if hot else 2,
                    "occupancy": 0.875 if hot else 0.125,
                    "pending_ops": 0,
                    "lane_queue_depth": 0,
                    # work BALANCED: the old work-only plan returns []
                    "work_units": 100.0,
                    "hbm_bytes": 1000,
                    "doc_work": {f"d{i}-{j}": 12.5 for j in range(8 if hot else 2)},
                    "doc_rows": {
                        f"d{i}-{j}": (2.0 if hot else 1.0)
                        for j in range(8 if hot else 2)
                    },
                }
            )
        moves = ext.rebalance_plan(stats)
        assert moves, "occupancy-hot cell must shed by rows"
        assert all(src == 0 for _n, src, _d in moves)
    finally:
        ext.cancel_timers()


async def test_rebalance_timer_never_rearms_after_teardown():
    """Finding from review: an in-flight tick's reschedule must respect
    cancel_timers/on_destroy — no immortal timer over destroyed cells."""
    ext = _cells_ext(devices=2, num_docs=8, rebalance_interval_s=0.01)
    ext._schedule_rebalance()
    assert ext._rebalance_handle is not None
    ext.cancel_timers()
    assert ext._rebalance_handle is None
    # a late reschedule (what the tick's finally does) is now inert
    ext._schedule_rebalance()
    assert ext._rebalance_handle is None


# -- device pinning -----------------------------------------------------------


def test_cells_pin_arenas_to_distinct_devices():
    assert len(jax.devices()) == 8  # conftest's forced-host mesh
    ext = _cells_ext(devices=8, num_docs=8, capacity=256)
    try:
        lanes = {id(cell.lane) for cell in ext.cells}
        assert len(lanes) == 8, "one arbiter per chip"
        for i, cell in enumerate(ext.cells):
            assert cell.plane.device is ext.devices[i]
            assert cell.plane.state.id_client.devices() == {ext.devices[i]}
        # a flush keeps the state on its chip
        cell = ext.cells[5]
        source = Doc()
        source.get_text("t").insert(0, "pinned")
        cell.plane.register("pin-doc")
        cell.plane.enqueue_update("pin-doc", encode_state_as_update(source))
        cell.plane.flush(None)
        assert cell.plane.state.id_client.devices() == {ext.devices[5]}
        assert cell.plane.text("pin-doc") == "pinned"
    finally:
        ext.cancel_timers()


# -- multi vs single differential ---------------------------------------------


async def _run_workload_cells(extension, names, updates):
    from hocuspocus_tpu.server.types import Payload
    from tests.tpu.test_scheduler import _ServedDoc

    docs = {}
    for name in names:
        doc = _ServedDoc(name)
        docs[name] = doc
        await extension.after_load_document(
            Payload(instance=None, document_name=name, document=doc)
        )
    for i, (name, update) in enumerate(updates):
        doc = docs[name]
        apply_update(doc, update)
        cell = extension.cell_for(name)
        captured = cell.try_capture(doc, update, origin=None)
        assert captured, f"update {i} fell off the plane"
        if i % 7 == 0:
            await asyncio.sleep(0.002)
    for cell in extension.cells:
        await cell._flush_now(max_batches=None, final=True)
        cell._broadcast_served(cross_instance=False)
    return docs


async def test_multi_device_state_matches_single_device_plane():
    """Byte-identical convergence fuzz: the same scripted mixed workload
    through an 8-cell multi-device plane and a single-device plane
    serves identical bytes per doc — placement and per-device kernels
    change WHERE work runs, never what state results."""
    names, updates, sources = _scripted_workload(seed=11, docs=6, edits=80)
    multi = _cells_ext(devices=8, num_docs=8, capacity=2048, native_lane=False)
    single = TpuMergeExtension(
        serve=True,
        num_docs=16,
        capacity=2048,
        flush_interval_ms=1,
        lane=DeviceLane(),
        native_lane=False,
    )
    try:
        docs_multi = await _run_workload_cells(multi, names, updates)
        from tests.tpu.test_scheduler import _run_workload

        docs_single = await _run_workload(single, names, updates)
        for name in names:
            want = sources[name].get_text("t").to_string()
            assert multi.cell_for(name).plane.text(name) == want
            assert single.plane.text(name) == want
            served_multi = multi.cell_for(name).serving.encode_state_as_update(
                name, docs_multi[name]
            )
            served_single = single.serving.encode_state_as_update(
                name, docs_single[name]
            )
            assert served_multi is not None
            assert served_multi == served_single
        # the workload actually spread over multiple chips
        populated = [
            cell for cell in multi.cells if len(cell.plane.docs) > 0
        ]
        assert len(populated) > 1, "placement stacked every doc on one chip"
    finally:
        multi.cancel_timers()
        single.cancel_timers()


# -- migration under live traffic ---------------------------------------------


async def test_migration_under_concurrent_edits_loses_nothing():
    """The zero-acked-update-loss acceptance: migrate a doc between
    cells WHILE its writer edits; every acknowledged update survives,
    the client never disconnects, and the doc ends up served by the
    target cell."""
    ext = _cells_ext(devices=4)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="mig-doc")
    b = new_provider(server, name="mig-doc")
    try:
        await wait_synced(a, b)
        src = ext.cell_index_for("mig-doc")
        a.document.get_text("t").insert(0, "before;")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "before;"
            )
        )
        dst = (src + 1) % len(ext.cells)

        async def edits():
            for i in range(20):
                a.document.get_text("t").insert(0, f"e{i};")
                await asyncio.sleep(0.002)

        edit_task = asyncio.ensure_future(edits())
        moved = False
        for _ in range(100):
            if await ext.migrate_doc("mig-doc", src, dst):
                moved = True
                break
            await asyncio.sleep(0.01)
        await edit_task
        assert moved, ext.migration_stats
        assert ext.migration_stats["docs_migrated"] == 1
        assert ext.placement.overrides["mig-doc"] == dst
        await retryable_assertion(
            lambda: _assert("mig-doc" in ext.cells[dst]._docs), timeout=10
        )
        assert "mig-doc" not in ext.cells[src]._docs
        a.document.get_text("t").insert(0, "after;")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string()
                == a.document.get_text("t").to_string()
                and "after;" in b.document.get_text("t").to_string()
            ),
            timeout=10,
        )
        text = b.document.get_text("t").to_string()
        assert "before;" in text
        for i in range(20):
            assert f"e{i};" in text, f"acked update e{i} lost in migration"
        # no client saw a disconnect
        assert a.synced and b.synced
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_rebalance_spreads_hot_cell_and_no_lane_bypass():
    """Hot-doc skew through a live server: pile dispatched work onto
    one cell's docs, tick the rebalancer, and the population spreads —
    no device owns >2x the mean — with every device dispatch accounted
    in-lane (zero bypass across all per-device lanes)."""
    ext = _cells_ext(
        devices=4,
        num_docs=24,
        capacity=8192,
        rebalance_ratio=1.5,
        rebalance_min_units=64.0,
        migrate_batch=8,
    )
    server = await new_hocuspocus(extensions=[ext])
    providers = []
    try:
        names = [f"spread-{i}" for i in range(16)]
        for name in names:
            provider = new_provider(server, name=name)
            providers.append(provider)
        await wait_synced(*providers)
        by_cell: "dict[int, list[str]]" = {}
        for name in names:
            by_cell.setdefault(ext.cell_index_for(name), []).append(name)
        hot = max(by_cell, key=lambda i: len(by_cell[i]))
        assert len(by_cell[hot]) >= 2, by_cell
        # make the hot cell's docs genuinely hot: big inserts -> big
        # dispatched-unit tallies on that chip
        for name in by_cell[hot]:
            index = names.index(name)
            providers[index].document.get_text("t").insert(0, "z" * 600)
        await retryable_assertion(
            lambda: _assert(
                sum(
                    ext.cells[hot].plane.dispatched_units[s]
                    for d in ext.cells[hot].plane.docs.values()
                    for s in d.seqs.values()
                )
                > 0
            ),
            timeout=10,
        )
        migrated = 0
        for _ in range(30):
            await ext._rebalance_tick()
            migrated = ext.migration_stats["docs_migrated"]
            stats = [s for s in ext.cell_stats() if s["healthy"]]
            docs = [s["docs"] for s in stats]
            mean = sum(docs) / len(docs)
            if migrated > 0 and max(docs) <= 2 * mean:
                break
            await asyncio.sleep(0.05)
        assert migrated > 0, ext.migration_stats
        # let the hydration drains land, then check the spread
        await asyncio.sleep(0.2)
        await retryable_assertion(
            lambda: _assert(ext.served_docs() == len(names)), timeout=10
        )
        spread = ext.utilization_spread()
        assert spread["docs_max_over_mean"] is not None
        assert spread["docs_max_over_mean"] <= 2.0, spread
        # zero bypass on EVERY per-device lane, across load-time presync
        # flushes, captures, warm grids, eviction and hydration
        for i, cell in enumerate(ext.cells):
            assert cell.lane.counters["dispatches_bypass"] == 0, (
                i,
                cell.lane.snapshot(),
            )
            assert cell.lane.counters["dispatches_in_lane"] > 0, (
                i,
                "cell never dispatched — placement skipped a device?",
            )
    finally:
        for provider in providers:
            provider.destroy()
        await server.destroy()


# -- per-cell breaker scope ----------------------------------------------------


async def test_supervisor_degrades_one_sick_cell_not_the_plane():
    """One chip wedges: ITS cell degrades (lane parked, placement
    routes around it, docs drain to CPU) while the other cells keep
    serving; a passing recovery probe restores it and re-onboards its
    docs."""
    from hocuspocus_tpu.tpu.supervisor import STATE_READY, PlaneSupervisor

    ext = _cells_ext(devices=2, num_docs=16)
    supervisor = PlaneSupervisor(
        lambda: ext, watchdog_interval=60.0, breaker_threshold=2,
        canary_deadline=0.5,
    )
    server = await new_hocuspocus(extensions=[ext])
    providers = []
    try:
        names = [f"breaker-{i}" for i in range(8)]
        for name in names:
            provider = new_provider(server, name=name)
            providers.append(provider)
        await wait_synced(*providers)
        supervisor.runtime = ext
        supervisor._instance = server.hocuspocus
        supervisor.state = STATE_READY
        sick = 0
        healthy = 1
        sick_docs = [n for n in names if ext.cell_index_for(n) == sick]
        well_docs = [n for n in names if ext.cell_index_for(n) == healthy]
        assert sick_docs and well_docs, "placement stacked one cell"

        def broken_probe():
            raise RuntimeError("chip wedged")

        original = ext.cells[sick].plane.canary_probe
        ext.cells[sick].plane.canary_probe = broken_probe
        for _ in range(3):
            await supervisor._watchdog_cells(ext)
            await asyncio.sleep(0.05)
        assert supervisor.cell_breakers[sick].state == "open"
        assert supervisor.cell_states[sick] != STATE_READY
        assert ext.cells[sick].lane.paused
        assert sick not in ext.placement.healthy
        # the sick cell's docs fell back to CPU; the healthy cell's did not
        for name in sick_docs:
            assert name not in ext.cells[sick]._docs
        for name in well_docs:
            assert name in ext.cells[healthy]._docs
        assert not ext.cells[healthy].lane.paused
        # global state: the plane still serves
        assert supervisor.state == STATE_READY
        # a CPU-path edit still works while degraded
        index = names.index(sick_docs[0])
        providers[index].document.get_text("t").insert(0, "degraded-ok")
        # recovery: probe passes -> cell restored + docs re-onboarded
        ext.cells[sick].plane.canary_probe = original
        for _ in range(3):
            await supervisor._watchdog_cells(ext)
            await asyncio.sleep(0.05)
        assert supervisor.cell_breakers[sick].state == "closed"
        assert supervisor.cell_states[sick] == STATE_READY
        assert not ext.cells[sick].lane.paused
        assert sick in ext.placement.healthy
        await retryable_assertion(
            lambda: _assert(
                all(ext.is_served(name) for name in sick_docs),
                [ (n, ext.is_served(n)) for n in sick_docs],
            ),
            timeout=10,
        )
    finally:
        for provider in providers:
            provider.destroy()
        await server.destroy()


# -- observability + CLI -------------------------------------------------------


async def test_debug_scheduler_and_per_device_metrics():
    import json

    import aiohttp

    from hocuspocus_tpu.observability import Metrics

    ext = _cells_ext(devices=4, num_docs=8, capacity=512)
    server = await new_hocuspocus(extensions=[Metrics(), ext])
    a = new_provider(server, name="cells-debug-doc")
    try:
        await wait_synced(a)
        a.document.get_text("t").insert(0, "observed")
        owner = ext.cell_for("cells-debug-doc")
        await retryable_assertion(
            lambda: _assert(owner.lane.counters["admissions"] > 0)
        )
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{server.http_url}/debug/scheduler"
            ) as response:
                assert response.status == 200
                body = json.loads(await response.text())
            async with session.get(f"{server.http_url}/metrics") as response:
                metrics_text = await response.text()
        assert len(body["devices"]) == 4
        section = body["devices"][ext.cell_index_for("cells-debug-doc")]
        assert section["lane"]["classes"]["interactive"]["admissions"] > 0
        assert section["docs"] == 1
        assert body["placement"]["hash"]
        assert "migrations" in body and "rebalance" in body
        # per-device labelled gauges + the summed plane aggregates
        assert 'hocuspocus_tpu_cell_docs{cell="' in metrics_text
        assert "hocuspocus_tpu_cell_hbm_bytes" in metrics_text
        assert "hocuspocus_tpu_cell_lane_queue_depth" in metrics_text
        assert "hocuspocus_tpu_cell_placement_epoch" in metrics_text
        assert "hocuspocus_tpu_plane_broadcasts" in metrics_text
    finally:
        a.destroy()
        await server.destroy()


def test_cli_exposes_multi_device_flags():
    from hocuspocus_tpu.cli import build_parser

    args = build_parser().parse_args(
        [
            "--tpu-serve",
            "--tpu-devices",
            "8",
            "--tpu-rebalance-interval",
            "2.5",
            "--tpu-rebalance-ratio",
            "1.75",
            "--tpu-migrate-batch",
            "4",
        ]
    )
    assert args.tpu_devices == 8
    assert args.tpu_rebalance_interval == 2.5
    assert args.tpu_rebalance_ratio == 1.75
    assert args.tpu_migrate_batch == 4


def test_supervised_factory_builds_cell_plane():
    from hocuspocus_tpu.tpu.supervisor import SupervisedTpuMergeExtension

    supervised = SupervisedTpuMergeExtension(
        devices=2, serve=True, num_docs=8, capacity=256,
        rebalance_interval_s=0,
    )
    runtime = supervised.supervisor.factory()
    try:
        assert isinstance(runtime, MultiDeviceMergeExtension)
        assert len(runtime.cells) == 2
    finally:
        runtime.cancel_timers()
    with pytest.raises(ValueError):
        SupervisedTpuMergeExtension(devices=2, shards=4)


def test_multi_device_storm_scenario_compiles_deterministically():
    from hocuspocus_tpu.loadgen import get_scenario
    from hocuspocus_tpu.loadgen.scenarios import BENCH_SUITE

    assert "multi_device_storm" in BENCH_SUITE
    scenario = get_scenario("multi_device_storm")
    a = scenario.compile(3)
    b = scenario.compile(3)
    assert a.schedule_hash == b.schedule_hash
    assert a.population["devices"] == 4
    assert scenario.params["multi_device"]["rebalance_interval_s"] > 0
    phases = [spec["name"] for spec in a.phases]
    assert phases == ["steady", "storm", "rebalanced"]
