"""Native window encoder ≡ Python Item encoder, byte for byte.

`native/codec.cpp encode_text_window` emits the struct section for the
shapes the plane serves hot (string runs, deleted runs, GC ranges,
root parents); `serving._encode_window_native` does the semantic work.
These tests pin byte-identity against the Python
`_write_structs`/`Item.write` path across origins, cutoff offsets,
multi-client groups, deleted runs and GC — plus the fallback decision
for rich content.

Encode mirror of the reference's lib0/yjs write layer
(`packages/server/src/OutgoingMessage.ts` + yjs UpdateEncoderV1).
"""

import random

import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.native import get_codec
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.serving import PlaneServing

pytestmark = pytest.mark.skipif(
    get_codec() is None or not hasattr(get_codec(), "encode_text_window"),
    reason="native codec unavailable",
)


def _seeded_plane(num_docs=4, capacity=2048):
    plane = MergePlane(num_docs=num_docs, capacity=capacity)
    serving = PlaneServing(plane)
    return plane, serving


def _python_bytes(serving, doc, sm):
    """Force the Python Item path for the same cutoff map."""
    from hocuspocus_tpu.crdt.encoding import Encoder
    from hocuspocus_tpu.crdt.update import _write_structs

    items_by_client = serving._group_items(doc, doc.serve_log, sm)
    encoder = Encoder()
    encoder.write_var_uint(len(items_by_client))
    for client in sorted(items_by_client, reverse=True):
        _write_structs(encoder, items_by_client[client], client, sm[client])
    serving._device_delete_set(doc).write(encoder)
    return encoder.to_bytes()


def _native_bytes(serving, doc, sm):
    from hocuspocus_tpu.crdt.encoding import Encoder

    body = serving._encode_window_native(doc, doc.serve_log, sm)
    assert body is not None, "expected the native fast path to qualify"
    encoder = Encoder()
    encoder.write_bytes(body)
    serving._device_delete_set(doc).write(encoder)
    return encoder.to_bytes()


def _full_sm(doc):
    return {client: 0 for client in doc.lowerer.known}


def test_multi_client_interleaved_edits_encode_identically():
    source_a, source_b = Doc(), Doc()
    source_a.client_id, source_b.client_id = 7, 1_000_000
    text_a = source_a.get_text("body")
    text_a.insert(0, "hello world, this is a longer run of text")
    apply_update(source_b, encode_state_as_update(source_a))
    source_b.get_text("body").insert(5, " INTERLEAVED")
    source_b.get_text("body").delete(0, 2)
    apply_update(source_a, encode_state_as_update(source_b))
    text_a.insert(20, " more")

    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source_a))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    sm = _full_sm(doc)
    native = _native_bytes(serving, doc, sm)
    python = _python_bytes(serving, doc, sm)
    assert native == python
    # and the bytes actually reproduce the document
    probe = Doc()
    apply_update(probe, native)
    assert probe.get_text("body").to_string() == text_a.to_string()


def test_cutoff_offsets_slice_runs_identically():
    """Stale joiners whose cutoff lands MID-RUN exercise the offset
    origin-rewrite + payload slice."""
    source = Doc()
    source.client_id = 42
    text = source.get_text("t")
    for i in range(8):
        text.insert(len(text), f"chunk-{i:02d}-" + "x" * random.Random(i).randint(1, 9))

    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    top = doc.lowerer.known[42]
    for cutoff in (0, 1, 5, top // 2, top - 1):
        sm = {42: cutoff}
        native = _native_bytes(serving, doc, sm)
        python = _python_bytes(serving, doc, sm)
        assert native == python, cutoff
        # served tail applies cleanly on top of a doc synced to `cutoff`
        probe = Doc()
        apply_update(probe, native)


def test_surrogate_pair_payloads_encode_identically():
    source = Doc()
    source.client_id = 9
    text = source.get_text("t")
    text.insert(0, "astral: \U0001f600\U0001f680 done")

    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    sm = _full_sm(doc)
    assert _native_bytes(serving, doc, sm) == _python_bytes(serving, doc, sm)


def test_deleted_runs_encode_identically_across_cutoffs():
    """ContentDeleted runs (kind 2): snapshots of gc=True docs replace
    deleted items' content with deleted runs; cutoffs landing mid-run
    exercise the length-minus-offset emission."""
    source = Doc()
    source.client_id = 21
    text = source.get_text("t")
    text.insert(0, "keep-this-then-delete-a-chunk-of-it")
    text.delete(10, 12)
    text.insert(len(text), " tail")

    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    assert any(rec.op.deleted_content for rec in doc.serve_log), (
        "expected ContentDeleted runs in the serve log"
    )
    top = doc.lowerer.known[21]
    for cutoff in (0, 12, 15, top - 2):  # 12/15 land inside the deleted run
        sm = {21: cutoff}
        assert _native_bytes(serving, doc, sm) == _python_bytes(serving, doc, sm), cutoff
    probe = Doc()
    apply_update(probe, _native_bytes(serving, doc, {21: 0}))
    assert probe.get_text("t").to_string() == source.get_text("t").to_string()


def test_gc_runs_encode_identically_across_cutoffs():
    """GC ranges (kind 1): a reload snapshot with a collected range and
    a string item anchored into it (hand-encoded wire update — GC
    structs only arise from collected subtrees, which otherwise ride
    tree docs). Cutoffs landing mid-range exercise length-minus-offset."""
    from hocuspocus_tpu.crdt.encoding import Encoder

    enc = Encoder()
    enc.write_var_uint(1)  # one client section
    enc.write_var_uint(2)  # two structs
    enc.write_var_uint(33)  # client
    enc.write_var_uint(0)  # clock
    enc.write_uint8(0)  # GC ref
    enc.write_var_uint(8)  # collected range [0, 8)
    enc.write_uint8(4 | 0x80)  # ContentString + origin
    enc.write_var_uint(33)
    enc.write_var_uint(7)  # anchored to the last collected unit
    enc.write_var_string("hello")
    enc.write_var_uint(0)  # empty delete set
    update = enc.to_bytes()

    plane, serving = _seeded_plane()
    plane.register("d")
    assert plane.enqueue_update("d", update) > 0
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    assert any(rec.op.gc for rec in doc.serve_log), (
        "expected GC structs in the serve log"
    )
    for cutoff in (0, 3, 7, 9):  # 3/7 land inside the GC range
        sm = {33: cutoff}
        assert _native_bytes(serving, doc, sm) == _python_bytes(serving, doc, sm), cutoff
    # the bytes decode cleanly (the synthetic item is root-parentless by
    # construction, so no content assertion — byte identity above is
    # the point of this test)
    probe = Doc()
    apply_update(probe, _native_bytes(serving, doc, {33: 0}))


def test_rich_content_falls_back_to_python_path():
    source = Doc()
    source.client_id = 3
    source.get_map("m").set("k", "v")  # map entry: host-side, not stringy

    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source))
    plane.flush()
    serving.refresh()
    doc = plane.docs["d"]
    assert serving._encode_window_native(doc, doc.serve_log, _full_sm(doc)) is None
    # and the public encode still serves correct bytes via the fallback
    payload = serving.encode_state_as_update("d", source, None)
    probe = Doc()
    apply_update(probe, payload)
    assert probe.get_map("m").get("k") == "v"


def test_broadcast_window_uses_native_bytes_and_converges():
    source = Doc()
    source.client_id = 11
    plane, serving = _seeded_plane()
    plane.register("d")
    plane.enqueue_update("d", encode_state_as_update(source), presync=True)

    edit = Doc()
    edit.client_id = 11
    text = edit.get_text("t")
    text.insert(0, "broadcast me")
    plane.enqueue_update("d", encode_state_as_update(edit))
    plane.flush()
    serving.refresh()
    update = serving.build_broadcast("d")
    assert update is not None
    probe = Doc()
    apply_update(probe, update)
    assert probe.get_text("t").to_string() == "broadcast me"
