"""Arena residency: eviction, batched hydration, on-device compaction.

The residency subsystem (docs/guides/tpu-residency.md) turns arena
rows from a permanent lease into a managed cache. These suites pin:

- kernel correctness: the unit-arena tombstone-GC compact and the RLE
  defragmenter against numpy references (packing order, dense ranks,
  padding sentinel, untouched rows);
- the recycle rail: a capacity/overflow-retired doc whose live state
  fits is compacted in place and serves CPU-equal bytes again;
- evict -> hydrate round trips: content AND tombstone layout identical
  to the CPU reference doc after random edit streams on both sides of
  the eviction;
- storm admission: a cold-doc catch-up burst completes with bounded
  in-flight hydrations and zero lost updates (10k variant under the
  `slow` marker);
- the satellite regressions: one fused state rebuild per multi-slot
  release, and lane-slot tombstone-cache cleanup in forget().
"""

import asyncio

import numpy as np
import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)
import hocuspocus_tpu.crdt as crdt
from hocuspocus_tpu.tpu.kernels import NONE_CLIENT, make_empty_state
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.residency import EvictedDoc, ResidencyManager
from hocuspocus_tpu.tpu.serving import PlaneServing

_INF = 0x7FFFFFFF


# -- kernel differentials ----------------------------------------------------


def _craft_unit_row(rng, n_units):
    """A plausible occupied row: dense rank permutation, two authors
    with per-author running clocks, random tombstones."""
    rank = rng.permutation(n_units).astype(np.int32)
    client = rng.integers(1, 3, n_units).astype(np.uint32)
    clock = np.zeros(n_units, np.int32)
    counters = {1: 0, 2: 0}
    for i in range(n_units):
        clock[i] = counters[int(client[i])]
        counters[int(client[i])] += 1
    deleted = rng.random(n_units) < 0.4
    return client, clock, rank, deleted


def _expected_compact(client, clock, rank, deleted, cap):
    """The packed layout integrating a freshly-lowered live snapshot
    would produce: live units in rank order at slots 0..L-1, dense
    ranks, predecessor-chained origins, no tombstones."""
    live_idx = np.flatnonzero(~deleted)
    live_sorted = live_idx[np.argsort(rank[live_idx])]
    L = len(live_sorted)
    exp = {
        "id_client": np.full(cap, NONE_CLIENT, np.uint32),
        "id_clock": np.zeros(cap, np.int32),
        "rank": np.full(cap, _INF, np.int32),
        "origin_rank": np.full(cap, -1, np.int32),
        "deleted": np.zeros(cap, bool),
    }
    exp["id_client"][:L] = client[live_sorted]
    exp["id_clock"][:L] = clock[live_sorted]
    exp["rank"][:L] = np.arange(L)
    exp["origin_rank"][:L] = np.arange(L) - 1
    return exp, L


def test_compact_kernel_matches_cpu_reference():
    from hocuspocus_tpu.tpu.kernels import DocState, compact_doc_rows

    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    D, N = 4, 32
    rows = {0: _craft_unit_row(rng, 20), 2: _craft_unit_row(rng, 31)}
    fields = {
        "id_client": np.full((D, N), NONE_CLIENT, np.uint32),
        "id_clock": np.zeros((D, N), np.int32),
        "rank": np.full((D, N), _INF, np.int32),
        "origin_rank": np.full((D, N), -1, np.int32),
        "deleted": np.zeros((D, N), bool),
    }
    length = np.zeros(D, np.int32)
    overflow = np.zeros(D, bool)
    # row 1 is an innocent bystander with content the compact must not touch
    by_client, by_clock, by_rank, by_deleted = _craft_unit_row(rng, 9)
    for d, (client, clock, rank, deleted) in {
        **rows, 1: (by_client, by_clock, by_rank, by_deleted)
    }.items():
        n = len(client)
        fields["id_client"][d, :n] = client
        fields["id_clock"][d, :n] = clock
        fields["rank"][d, :n] = rank
        fields["deleted"][d, :n] = deleted
        length[d] = n
    overflow[0] = True  # the overflow flag must clear on compaction
    before = {k: v.copy() for k, v in fields.items()}
    state = DocState(
        id_client=jnp.asarray(fields["id_client"]),
        id_clock=jnp.asarray(fields["id_clock"]),
        rank=jnp.asarray(fields["rank"]),
        origin_rank=jnp.asarray(fields["origin_rank"]),
        deleted=jnp.asarray(fields["deleted"]),
        length=jnp.asarray(length),
        overflow=jnp.asarray(overflow),
    )
    # pad with the out-of-range sentinel, exactly as the plane routes it
    slots = jnp.asarray([0, 2, D, D], jnp.int32)
    state, sizes = compact_doc_rows(state, slots)

    for i, d in enumerate((0, 2)):
        exp, L = _expected_compact(*rows[d], N)
        assert int(sizes[i]) == L
        assert int(np.asarray(state.length)[d]) == L
        assert not bool(np.asarray(state.overflow)[d])
        for name, want in exp.items():
            np.testing.assert_array_equal(
                np.asarray(getattr(state, name))[d], want, err_msg=f"{name}[{d}]"
            )
    # the unrouted row is untouched
    for name, want in before.items():
        np.testing.assert_array_equal(np.asarray(getattr(state, name))[1], want[1])
    assert int(np.asarray(state.length)[1]) == 9


def test_compact_kernel_rle_defragments():
    from hocuspocus_tpu.tpu.kernels_rle import RleState, compact_doc_rows_rle

    import jax.numpy as jnp

    D, R = 3, 16
    # entries for row 1 (rank-shuffled on purpose; the kernel sorts):
    #  - two id-AND-rank-consecutive live fragments of client 1 -> merge
    #  - a deleted continuation -> kept separate (tombstone verdict differs)
    #  - a zero-length dead lane -> dropped
    #  - client 2's run -> kept
    entries = [
        # (client, clock, len, rank, orank, deleted)
        (1, 3, 2, 3, 2, False),  # fragment tail (merges into head below)
        (1, 0, 3, 0, -1, False),  # fragment head
        (1, 5, 1, 5, 4, True),  # deleted continuation: no merge
        (3, 9, 0, 7, -1, False),  # dead lane: dropped
        (2, 0, 4, 6, 5, False),
    ]
    fields = {
        "run_client": np.full((D, R), NONE_CLIENT, np.uint32),
        "run_clock": np.zeros((D, R), np.int32),
        "run_len": np.zeros((D, R), np.int32),
        "run_rank": np.full((D, R), _INF, np.int32),
        "run_orank": np.full((D, R), -1, np.int32),
        "run_deleted": np.zeros((D, R), bool),
    }
    for j, (cl, ck, ln, rk, ok, dl) in enumerate(entries):
        fields["run_client"][1, j] = cl
        fields["run_clock"][1, j] = ck
        fields["run_len"][1, j] = ln
        fields["run_rank"][1, j] = rk
        fields["run_orank"][1, j] = ok
        fields["run_deleted"][1, j] = dl
    num_runs = np.zeros(D, np.int32)
    num_runs[1] = len(entries)
    total_units = np.zeros(D, np.int32)
    total_units[1] = 10
    state = RleState(
        run_client=jnp.asarray(fields["run_client"]),
        run_clock=jnp.asarray(fields["run_clock"]),
        run_len=jnp.asarray(fields["run_len"]),
        run_rank=jnp.asarray(fields["run_rank"]),
        run_orank=jnp.asarray(fields["run_orank"]),
        run_deleted=jnp.asarray(fields["run_deleted"]),
        num_runs=jnp.asarray(num_runs),
        total_units=jnp.asarray(total_units),
        overflow=jnp.asarray(np.asarray([False, True, False])),
    )
    state, counts = compact_doc_rows_rle(state, jnp.asarray([1, D], jnp.int32))
    assert int(counts[0]) == 3
    assert int(np.asarray(state.num_runs)[1]) == 3
    assert int(np.asarray(state.total_units)[1]) == 10  # rank space untouched
    assert not bool(np.asarray(state.overflow)[1])
    want = [
        # (client, clock, len, rank, orank, deleted) — rank order, merged
        (1, 0, 5, 0, -1, False),
        (1, 5, 1, 5, 4, True),
        (2, 0, 4, 6, 5, False),
    ]
    got = [
        tuple(
            int(np.asarray(getattr(state, f))[1, j])
            for f in (
                "run_client", "run_clock", "run_len", "run_rank", "run_orank"
            )
        )
        + (bool(np.asarray(state.run_deleted)[1, j]),)
        for j in range(3)
    ]
    assert got == want
    # packed tail is pristine empty
    assert int(np.asarray(state.run_len)[1, 3:].sum()) == 0
    assert (np.asarray(state.run_client)[1, 3:] == NONE_CLIENT).all()


# -- overflow -> compact -> recycle ------------------------------------------


def _fingerprint(doc: Doc):
    return (
        doc.get_text("t").to_delta(),
        dict(doc.get_map("m").to_json()),
        doc.get_array("a").to_json(),
    )


async def test_overflow_compact_recycle_unit_arena():
    """A churny doc retires on capacity; its live state fits, so the
    tombstone-GC kernel recycles it in place — differential vs the CPU
    reference doc, including post-recycle traffic."""
    plane = MergePlane(num_docs=4, capacity=64)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, compact_threshold=0.75)

    ref = Doc()
    t = ref.get_text("t")
    plane.register("churny")
    plane.enqueue_update("churny", encode_state_as_update(ref), presync=True)
    for _ in range(12):
        before = encode_state_vector(ref)
        t.insert(len(t), "abcdef")
        t.delete(0, 5)
        plane.enqueue_update("churny", crdt.encode_state_as_update(ref, before))
        if plane.docs["churny"].retired:
            break
    doc = plane.docs["churny"]
    assert doc.retired and doc.retire_reason == "capacity"
    assert doc.serve_log, "capacity retire must preserve logs for compaction"

    async with plane.flush_lock:
        assert await mgr.compact_doc_locked("churny")
    assert not plane.docs["churny"].retired
    assert plane.counters["docs_compacted"] == 1

    # live-tail replay brings the plane current; serves must be CPU-equal
    plane.enqueue_update("churny", encode_state_as_update(ref), presync=True)
    plane.flush()
    serving.refresh()
    assert plane.text("churny") == t.to_string()
    payload = serving.encode_state_as_update("churny", ref)
    assert payload is not None
    rebuilt = Doc()
    apply_update(rebuilt, payload)
    assert rebuilt.get_text("t").to_string() == t.to_string()

    # the doc keeps serving through fresh churn after the recycle
    for i in range(3):
        before = encode_state_vector(ref)
        t.insert(len(t), f"+{i}x")
        t.delete(0, 2)
        plane.enqueue_update("churny", crdt.encode_state_as_update(ref, before))
    plane.flush()
    serving.refresh()
    assert not plane.docs["churny"].retired, plane.docs["churny"].retire_reason
    assert plane.text("churny") == t.to_string()
    payload = serving.encode_state_as_update("churny", ref)
    assert payload is not None, "post-compaction serve fell back to CPU"
    again = Doc()
    apply_update(again, payload)
    assert again.get_text("t").to_string() == t.to_string()


async def test_overflow_compact_recycle_rle_arena():
    """RLE twin: fragmentation (not tombstones) exhausts entries; the
    id-preserving defragmenter recycles the doc."""
    plane = MergePlane(num_docs=4, capacity=48, arena="rle")
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, compact_threshold=0.75)
    ref = Doc()
    t = ref.get_text("t")
    plane.register("frag")
    plane.enqueue_update("frag", encode_state_as_update(ref), presync=True)
    for _ in range(40):
        before = encode_state_vector(ref)
        t.insert(len(t), "hello")
        t.delete(1 if len(t) > 6 else 0, 3)
        plane.enqueue_update("frag", crdt.encode_state_as_update(ref, before))
        if plane.docs["frag"].retired:
            break
    assert plane.docs["frag"].retired
    plane.flush()
    async with plane.flush_lock:
        assert await mgr.compact_doc_locked("frag")
    assert not plane.docs["frag"].retired
    plane.enqueue_update("frag", encode_state_as_update(ref), presync=True)
    plane.flush()
    serving.refresh()
    assert plane.text("frag") == t.to_string()
    payload = serving.encode_state_as_update("frag", ref)
    assert payload is not None
    rebuilt = Doc()
    apply_update(rebuilt, payload)
    assert rebuilt.get_text("t").to_string() == t.to_string()


async def test_compact_declines_when_live_state_has_no_headroom():
    """A doc whose LIVE length has no headroom declines compaction:
    the doc stays retired, the deferred log drop lands, and the
    attempt is suppressed (no busy-loop retrying a hopeless doc)."""
    plane = MergePlane(num_docs=4, capacity=64)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, compact_threshold=0.75)
    ref = Doc()
    t = ref.get_text("t")
    plane.register("dense")
    plane.enqueue_update("dense", encode_state_as_update(ref), presync=True)
    for _ in range(10):
        before = encode_state_vector(ref)
        t.insert(len(t), "0123456789")  # pure growth: everything live
        plane.enqueue_update("dense", crdt.encode_state_as_update(ref, before))
        if plane.docs["dense"].retired:
            break
    doc = plane.docs["dense"]
    assert doc.retired and doc.retire_reason == "capacity"
    async with plane.flush_lock:
        assert not await mgr.compact_doc_locked("dense")
    assert plane.docs["dense"].retired
    assert plane.counters["compactions_declined"] == 1
    assert not doc.serve_log, "declined compaction must drop retained logs"
    assert not mgr.wants_logs(doc, "capacity"), "decline is sticky"


# -- evict -> hydrate round trips --------------------------------------------


def _random_edits(rng, ref: Doc, steps: int) -> None:
    words = ["alpha ", "beta ", "gamma ", "zz", "q "]
    for step in range(steps):
        kind = int(rng.integers(0, 5))
        text = ref.get_text("t")
        if kind == 0:
            text.insert(int(rng.integers(0, len(text) + 1)),
                        words[int(rng.integers(0, len(words)))])
        elif kind == 1 and len(text) > 2:
            pos = int(rng.integers(0, len(text) - 1))
            text.delete(pos, min(int(rng.integers(1, 4)), len(text) - pos))
        elif kind == 2:
            ref.get_map("m").set(f"k{int(rng.integers(0, 3))}", int(step))
        elif kind == 3:
            key = f"k{int(rng.integers(0, 3))}"
            if ref.get_map("m").get(key) is not None:
                ref.get_map("m").delete(key)
        else:
            arr = ref.get_array("a")
            if int(rng.integers(0, 3)) == 0 and len(arr) > 0:
                arr.delete(int(rng.integers(0, len(arr))), 1)
            else:
                arr.insert(int(rng.integers(0, len(arr) + 1)), [int(step)])


@pytest.mark.parametrize("arena", ["unit", "rle"])
@pytest.mark.parametrize("seed", [3, 19])
async def test_evict_hydrate_roundtrip_fuzz(seed, arena):
    """Random edits, evict, more edits on the CPU path, hydrate: the
    re-admitted doc serves bytes that rebuild a doc with content AND
    tombstone layout identical to the CPU reference."""
    rng = np.random.default_rng(seed)
    plane = MergePlane(num_docs=8, capacity=4096, arena=arena)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, hydrate_batch=4)

    ref = Doc()
    updates = []
    ref.on("update", lambda update, *rest: updates.append(update))
    plane.register("roundtrip")
    plane.enqueue_update("roundtrip", encode_state_as_update(ref), presync=True)

    for cycle in range(3):
        _random_edits(rng, ref, 25)
        while updates:
            plane.enqueue_update("roundtrip", updates.pop(0))
        plane.flush()
        serving.refresh()
        free_before = len(plane.free)

        assert await mgr.evict("roundtrip", ref)
        assert "roundtrip" not in plane.docs
        assert len(plane.free) > free_before, "eviction must free rows"
        assert mgr.is_evicted("roundtrip")
        mid_sv = encode_state_vector(ref)

        # post-eviction tail rides the CPU path
        _random_edits(rng, ref, 10)
        updates.clear()  # the hydration live-tail replay carries these

        mgr.request_hydration("roundtrip", ref)
        for _ in range(2000):
            if not mgr._queue and not mgr._drain_running:
                break
            await asyncio.sleep(0.01)
        assert not mgr.is_evicted("roundtrip")
        assert plane.is_supported("roundtrip"), (
            seed, arena, cycle,
            {k: v for k, v in plane.counters.items() if v},
        )
        served = serving.encode_state_as_update("roundtrip", ref)
        assert served is not None, (seed, arena, cycle)
        rebuilt = Doc()
        apply_update(rebuilt, served)
        assert _fingerprint(rebuilt) == _fingerprint(ref), (seed, arena, cycle)
        # tombstone layout identical: same state vector, and a stale
        # peer catching up over the eviction boundary converges
        assert encode_state_vector(rebuilt) == encode_state_vector(ref)
        stale = serving.encode_state_as_update("roundtrip", ref, mid_sv)
        assert stale is not None
        peer = Doc()
        apply_update(peer, mgr.evicted.get("roundtrip").snapshot
                     if mgr.is_evicted("roundtrip") else served)
        apply_update(peer, stale)
        assert _fingerprint(peer) == _fingerprint(ref), (seed, arena, cycle)

    assert plane.counters["docs_evicted"] == 3
    assert plane.counters["docs_hydrated"] == 3


async def test_extension_evicts_idle_doc_and_rehydrates_on_edit():
    """The full policy loop through a live server: an idle doc's rows
    evict on the maintenance timer; fresh traffic re-admits it through
    the hydration queue, with no update lost on either side."""
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    ext = TpuMergeExtension(
        num_docs=8, capacity=1024, flush_interval_ms=1, serve=True,
        evict_idle_secs=0.3,
    )
    assert ext.residency is not None
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="sleepy")
    b = new_provider(server, name="sleepy")
    try:
        await wait_synced(a, b)
        a.document.get_text("t").insert(0, "written then idle")

        def evicted():
            assert ext.residency.is_evicted("sleepy")
            assert "sleepy" not in ext._docs
            assert "sleepy" not in ext.plane.docs

        await retryable_assertion(evicted)
        assert ext.plane.counters["docs_evicted"] >= 1

        # fresh traffic: served via CPU immediately, re-admitted via
        # the hydration queue shortly after
        a.document.get_text("t").insert(0, "awake! ")

        def converged_and_rehydrated():
            assert b.document.get_text("t").to_string() == "awake! written then idle"
            assert "sleepy" in ext._docs
            assert ext.plane.is_supported("sleepy")
            ext.plane.flush()
            assert ext.plane.text("sleepy") == "awake! written then idle"

        await retryable_assertion(converged_and_rehydrated)
        assert ext.plane.counters["docs_hydrated"] >= 1
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


# -- storm admission ---------------------------------------------------------


async def _run_admission_storm(num_docs: int, storm: int, hydrate_batch: int):
    """Shared storm body: `storm` cold snapshots burst into the
    hydration queue at once; every flush the drain issues must carry at
    most `hydrate_batch` in-flight docs, and every doc must come out
    plane-served with its exact content."""
    plane = MergePlane(num_docs=num_docs, capacity=64)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(
        plane=plane, serving=serving, hydrate_batch=hydrate_batch
    )
    texts = {}
    snapshots = {}
    for i in range(storm):
        ref = Doc()
        ref.get_text("t").insert(0, f"doc {i:05d} payload")
        texts[f"cold-{i}"] = ref.get_text("t").to_string()
        snapshots[f"cold-{i}"] = encode_state_as_update(ref)
        mgr.evicted[f"cold-{i}"] = EvictedDoc(snapshots[f"cold-{i}"], 0.0)

    inflight_at_flush = []
    orig_flush = plane.flush

    def spy_flush(*args, **kwargs):
        inflight_at_flush.append(mgr.inflight)
        return orig_flush(*args, **kwargs)

    plane.flush = spy_flush
    for name in texts:
        mgr.request_hydration(name)
    assert plane.residency_stats["hydration_queue_peak"] >= storm - hydrate_batch

    for _ in range(12000):
        if not mgr._queue and not mgr._drain_running:
            break
        await asyncio.sleep(0.005)
    plane.flush = orig_flush

    assert inflight_at_flush, "the drain never flushed"
    assert max(inflight_at_flush) <= hydrate_batch, "admission bound violated"
    assert len(inflight_at_flush) >= storm // hydrate_batch
    assert plane.counters["docs_hydrated"] == storm
    assert plane.counters["hydrations_declined"] == 0
    assert mgr.inflight == 0 and not mgr._queue
    assert plane.residency_stats["hydration_p99_ms"] > 0.0
    return plane, serving, texts, snapshots


async def test_storm_admission_bounded_inflight():
    plane, serving, texts, _snapshots = await _run_admission_storm(
        num_docs=256, storm=200, hydrate_batch=32
    )
    serving.refresh()
    for name, want in texts.items():
        assert plane.is_supported(name), name
        assert plane.text(name) == want, name


@pytest.mark.slow
def test_storm_admission_10k_cold_docs():
    """BASELINE config 5 miniature: a >=10k cold-doc catch-up storm
    completes with bounded concurrent hydrations and zero lost
    updates (acceptance rail)."""

    async def run():
        plane, serving, texts, snapshots = await _run_admission_storm(
            num_docs=10_240, storm=10_000, hydrate_batch=128
        )
        serving.refresh()
        # zero lost updates: every doc plane-served with exact content
        for name, want in texts.items():
            assert plane.is_supported(name), name
            assert plane.text(name) == want, name
        # spot-check the serving path end to end (the CPU reference doc
        # rebuilt from the stored snapshot, as the server would hold it)
        for i in range(0, 10_000, 500):
            ref = Doc()
            apply_update(ref, snapshots[f"cold-{i}"])
            payload = serving.encode_state_as_update(f"cold-{i}", ref)
            assert payload is not None
            rebuilt = Doc()
            apply_update(rebuilt, payload)
            assert rebuilt.get_text("t").to_string() == texts[f"cold-{i}"]

    asyncio.run(asyncio.wait_for(run(), timeout=1200))


async def test_storm_overflow_declines_without_loss():
    """More cold docs than rows: the overflow is declined (counted),
    never wedged, and admitted docs still serve exact content."""
    plane = MergePlane(num_docs=4, capacity=64)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, hydrate_batch=2)
    texts = {}
    for i in range(8):
        ref = Doc()
        ref.get_text("t").insert(0, f"burst {i}")
        texts[f"b-{i}"] = ref.get_text("t").to_string()
        mgr.evicted[f"b-{i}"] = EvictedDoc(encode_state_as_update(ref), 0.0)
        mgr.request_hydration(f"b-{i}")
    for _ in range(2000):
        if not mgr._queue and not mgr._drain_running:
            break
        await asyncio.sleep(0.01)
    assert plane.counters["docs_hydrated"] == 4
    assert plane.counters["hydrations_declined"] == 4
    serving.refresh()
    admitted = [n for n in texts if plane.is_supported(n)]
    assert len(admitted) == 4
    for name in admitted:
        assert plane.text(name) == texts[name]
    # declined docs keep their snapshot: a future retry can still admit
    assert sum(1 for n in texts if mgr.is_evicted(n)) == 4


# -- satellite regressions ---------------------------------------------------


def test_release_fuses_multi_slot_clears():
    """A release spanning several sequences does ONE state rebuild
    (one flush_epoch bump), not one per slot."""
    plane = MergePlane(num_docs=8, capacity=256)
    ref = Doc()
    ref.get_text("t").insert(0, "text")
    ref.get_array("a").insert(0, [1, 2])
    ref.get_xml_fragment("x")  # third root
    plane.register("wide")
    plane.enqueue_update("wide", encode_state_as_update(ref), presync=True)
    plane.flush()
    doc = plane.docs["wide"]
    assert len(set(doc.seqs.values())) >= 2, "need a multi-slot doc"
    epoch = plane.flush_epoch
    free_before = len(plane.free)
    released_slots = len(set(doc.seqs.values()))
    plane.release("wide")
    assert plane.flush_epoch == epoch + 1
    assert len(plane.free) == free_before + released_slots


def test_remap_origins_chases_stacked_compactions():
    """An origin landing in a GC'd range re-anchors to that range's
    recorded neighbor — and when a LATER compaction removed the
    neighbor too, the chase must follow the chain to a live id, never
    hand the device a dead one."""
    from hocuspocus_tpu.tpu.lowering import DenseOp
    from hocuspocus_tpu.tpu.kernels import KIND_INSERT
    from hocuspocus_tpu.tpu.merge_plane import PlaneDoc

    plane = MergePlane(num_docs=4, capacity=256)
    doc = PlaneDoc("chained")
    # compaction 1 removed client 1 clocks [10, 20); left neighbor was
    # (1, 5), right neighbor (1, 25). Compaction 2 later removed
    # [4, 7) — swallowing that left neighbor — with its own live left
    # neighbor (1, 2) and right neighbor (1, 25).
    doc.origin_remap[1] = (
        [4, 10],
        [(4, 7, (1, 2), (1, 25)), (10, 20, (1, 5), (1, 25))],
    )
    op = DenseOp(
        kind=KIND_INSERT, client=2, clock=0, run_len=1,
        left_client=1, left_clock=12, right_client=1, right_clock=15,
    )
    plane._remap_origins(doc, ("root", "t"), [op])
    assert (op.left_client, op.left_clock) == (1, 2), "one hop is not enough"
    assert (op.right_client, op.right_clock) == (1, 25)

    # both origins dissolving into boundaries -> explicit wire parent
    doc2 = PlaneDoc("edge")
    doc2.origin_remap[1] = ([0], [(0, 30, None, None)])
    op2 = DenseOp(
        kind=KIND_INSERT, client=2, clock=1, run_len=1,
        left_client=1, left_clock=3, right_client=1, right_clock=29,
    )
    plane._remap_origins(doc2, ("root", "t"), [op2])
    assert op2.left_client == NONE_CLIENT
    assert op2.right_client == NONE_CLIENT
    assert op2.parent == ("root", "t")


def test_forget_drops_lane_slot_tombstone_cache():
    """PlaneServing.forget must drop the lane slot's tombstone-cache
    entry too — lane slots may predate root discovery and are not in
    doc.seqs."""
    plane = MergePlane(num_docs=4, capacity=256)
    if not plane.enable_lane():
        pytest.skip("native lane unavailable on this build")
    serving = PlaneServing(plane)
    doc = plane.register_lane("laney")
    assert doc is not None and doc.lane_slot is not None
    serving._tombstone_cache[doc.lane_slot] = ("stale", "entry")
    serving.forget("laney", doc)
    assert doc.lane_slot not in serving._tombstone_cache


def test_residency_counters_and_occupancy_exported():
    """The capacity-pressure surface: plane counters carry the
    residency events and the occupancy partition is derivable from the
    gauges' inputs (free + live + retired == num_docs)."""
    plane = MergePlane(num_docs=8, capacity=256)
    for key in (
        "docs_evicted", "docs_hydrated", "docs_compacted",
        "hydrations_declined", "compactions_declined",
    ):
        assert key in plane.counters
    for key in (
        "evicted_docs", "hydration_queue_depth", "hydration_queue_peak",
        "hydrations_inflight", "hydration_p50_ms", "hydration_p99_ms",
    ):
        assert key in plane.residency_stats
    free = len(plane.free)
    live = int(plane.slot_live.sum())
    assert free + live + (plane.num_docs - free - live) == plane.num_docs


async def test_evict_declines_while_broadcast_window_pending():
    """An update claimed for plane-batched broadcast (try_capture said
    "no CPU fan-out") but not yet shipped must block eviction: release()
    would discard its queue entry and dirty mark and peers would never
    receive it. The decline is transient — once the window ships, the
    same eviction goes through."""
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    ext = TpuMergeExtension(
        num_docs=8, capacity=1024, flush_interval_ms=1, serve=True,
        evict_idle_secs=30.0,  # manager on; the timer never fires in-test
    )
    assert ext.residency is not None
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="windowed")
    try:
        await wait_synced(a)
        a.document.get_text("t").insert(0, "claimed update")

        def settled():
            ext.plane.flush()
            assert ext.plane.text("windowed") == "claimed update"  # arrived
            doc = ext.plane.docs.get("windowed")
            assert not ext.residency._has_unshipped(doc)  # and shipped

        await retryable_assertion(settled)
        document = ext._docs["windowed"]

        # a claimed-but-unshipped window (what a capture landing during
        # the snapshot's executor hop looks like when evict re-checks);
        # stall the broadcast tick so the window genuinely stays open
        orig_broadcast = ext._broadcast_served
        ext._broadcast_served = lambda *a, **k: None
        ext.plane.dirty.add("windowed")
        assert not await ext.residency.evict("windowed", document)
        assert "windowed" in ext._docs and "windowed" in ext.plane.docs
        assert not ext.residency.is_evicted("windowed")

        ext._broadcast_served = orig_broadcast
        ext.plane.dirty.discard("windowed")
        assert await ext.residency.evict("windowed", document)
        assert ext.residency.is_evicted("windowed")
        assert "windowed" not in ext._docs
    finally:
        a.destroy()
        await server.destroy()


async def test_preserved_retired_doc_reclaimed_by_sweep():
    """A health-sweep style retire (no recycle seam runs) preserves the
    doc's host logs; the maintenance sweep must visit it proactively —
    compacting it back onto the plane — instead of holding its
    largest-possible logs until the next edit."""
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    ext = TpuMergeExtension(
        num_docs=8, capacity=1024, flush_interval_ms=1, serve=True,
        compact_threshold=0.75, native_lane=False,
    )
    assert ext.residency is not None
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="swept")
    try:
        await wait_synced(a)
        t = a.document.get_text("t")
        t.insert(0, "abcdefgh")
        t.delete(0, 4)  # tombstones: the compact pass has work to do

        def flushed():
            ext.plane.flush()
            assert ext.plane.text("swept") == "efgh"  # edits landed
            assert ext.plane.pending_ops() == 0
            assert "swept" not in ext.plane.dirty

        await retryable_assertion(flushed)

        # the post-flush health sweep's seam: retire with NO recycle,
        # then the CPU fallback — which pops the doc from
        # extension._docs, the exact state the sweep must handle
        ext.plane.retire_doc("swept", "overflow")
        ext._fallback_to_cpu(ext._docs["swept"])
        assert "swept" not in ext._docs
        doc = ext.plane.docs["swept"]
        assert doc.retired
        assert doc.serve_log, "overflow retire must preserve logs"
        assert "swept" in ext.residency._preserved

        await ext.residency._visit_preserved()
        doc = ext.plane.docs["swept"]
        assert not doc.retired, "sweep must recycle the fitting doc"
        assert ext.plane.is_supported("swept")
        assert "swept" in ext._docs, "recycle must re-attach serving"
        assert "swept" not in ext.residency._preserved
        assert ext.plane.counters["docs_compacted"] >= 1

        def serves_again():
            ext.plane.flush()
            assert ext.plane.text("swept") == "efgh"

        await retryable_assertion(serves_again)
    finally:
        a.destroy()
        await server.destroy()


async def test_eviction_checkpoints_wal(tmp_path):
    """WAL + eviction interaction (docs/guides/durability.md): an
    eviction snapshot is folded into the write-ahead log as a
    checkpoint record that SUBSUMES the per-update history — the log
    shrinks to one record, and recovery from it rebuilds the exact
    evicted state."""
    from hocuspocus_tpu.storage import REC_SNAPSHOT, WalManager

    rng = np.random.default_rng(11)
    plane = MergePlane(num_docs=4, capacity=4096)
    serving = PlaneServing(plane)
    mgr = ResidencyManager(plane=plane, serving=serving, hydrate_batch=4)
    wal = WalManager(str(tmp_path / "wal"), fsync="tick")

    ref = Doc()
    updates = []
    ref.on("update", lambda update, *rest: updates.append(update))
    # durability capture seam, as Document wires it: every update is
    # appended; eviction checkpoints through the doc attribute
    ref.wal_checkpoint = lambda snapshot: wal.checkpoint("wal-evict", snapshot)
    plane.register("wal-evict")
    plane.enqueue_update("wal-evict", encode_state_as_update(ref), presync=True)

    _random_edits(rng, ref, 20)
    while updates:
        update = updates.pop(0)
        wal.append("wal-evict", update)
        plane.enqueue_update("wal-evict", update)
    plane.flush()
    serving.refresh()
    await wal.flush()
    records, _report = await wal.replay("wal-evict")
    assert len(records) >= 5, "edit history must be in the log pre-eviction"

    assert await mgr.evict("wal-evict", ref)
    assert mgr.is_evicted("wal-evict")
    # make the checkpoint's group commit durable before reading back
    await wal.flush()
    records, report = await wal.replay("wal-evict")
    assert len(records) == 1, "checkpoint must subsume the per-update history"
    assert records[0][0] == REC_SNAPSHOT
    assert report["torn_tail_records"] == 0

    # recovery differential: the checkpoint record alone rebuilds the
    # evicted doc byte-identically
    rebuilt = Doc()
    apply_update(rebuilt, records[0][1])
    assert _fingerprint(rebuilt) == _fingerprint(ref)
    assert encode_state_vector(rebuilt) == encode_state_vector(ref)

    # post-eviction edits keep appending AFTER the checkpoint record
    _random_edits(rng, ref, 5)
    while updates:
        wal.append("wal-evict", updates.pop(0))
    await wal.flush()
    records, _report = await wal.replay("wal-evict")
    assert records[0][0] == REC_SNAPSHOT and len(records) > 1
    replayed = Doc()
    for _rec_type, payload in records:
        apply_update(replayed, payload)
    assert _fingerprint(replayed) == _fingerprint(ref)
