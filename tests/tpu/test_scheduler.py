"""Adaptive merge scheduling (tpu/scheduler.py): the device-lane
arbiter, the arrival-aware batching governor, and their integration
with the serving extension.

The invariants pinned here:
- lane grants are strictly priority-ordered (interactive > catch-up >
  background > canary), FIFO within a class;
- the starvation guard promotes aged background waiters so a sustained
  interactive burst can never park them forever;
- pause() (the supervisor's breaker-open action) defers every queued
  non-exempt admission and parks the door; resume() restores flow;
- `should_yield`/release(preempted=True) account batch-granularity
  preemption;
- the governor changes WHEN and IN HOW MANY kernel calls queued ops
  flush — never what flushes: governor-on/off doc state is
  byte-identical under a fuzzed mixed workload;
- no device dispatch of the scheduled pipeline (flush, warm grid,
  hydration, compaction) bypasses the lane (the scheduler-accounting
  acceptance test);
- shard 2..N of identically-shaped planes skip warm-grid shapes the
  first plane already compiled (module-level jit cache).
"""

import asyncio
import random
import time

import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.tpu.merge_plane import MergePlane, TpuMergeExtension
from hocuspocus_tpu.tpu.scheduler import (
    CLASS_BACKGROUND,
    CLASS_CANARY,
    CLASS_CATCHUP,
    CLASS_INTERACTIVE,
    BatchGovernor,
    DeviceLane,
    LaneDeferred,
    reset_warm_registry,
)
from hocuspocus_tpu.server.types import Payload
from tests.utils import new_hocuspocus, new_provider, retryable_assertion


def _assert(cond, detail=None):
    assert cond, detail


# -- DeviceLane --------------------------------------------------------------


async def test_lane_grants_by_priority_then_fifo():
    lane = DeviceLane()
    holder = await lane.admit(CLASS_INTERACTIVE, site="t")
    order = []

    async def wait_for(cls, tag):
        ticket = await lane.admit(cls, site=tag)
        order.append(tag)
        ticket.release()

    tasks = [
        asyncio.ensure_future(wait_for(CLASS_BACKGROUND, "bg-1")),
        asyncio.ensure_future(wait_for(CLASS_CANARY, "canary")),
        asyncio.ensure_future(wait_for(CLASS_CATCHUP, "catchup")),
        asyncio.ensure_future(wait_for(CLASS_INTERACTIVE, "live-1")),
        asyncio.ensure_future(wait_for(CLASS_BACKGROUND, "bg-2")),
        asyncio.ensure_future(wait_for(CLASS_INTERACTIVE, "live-2")),
    ]
    await asyncio.sleep(0)  # queue them all
    assert lane.contended() and lane.has_waiter(below_class=CLASS_CATCHUP)
    holder.release()
    await asyncio.gather(*tasks)
    assert order == ["live-1", "live-2", "catchup", "bg-1", "bg-2", "canary"]
    assert lane.counters["admissions"] == 7
    assert not lane.contended()


async def test_lane_starvation_guard_promotes_aged_background():
    lane = DeviceLane(promote_after_s=0.02)
    holder = await lane.admit(CLASS_INTERACTIVE)
    order = []

    async def wait_for(cls, tag):
        ticket = await lane.admit(cls, site=tag)
        order.append(tag)
        ticket.release()

    aged = asyncio.ensure_future(wait_for(CLASS_BACKGROUND, "aged-bg"))
    await asyncio.sleep(0.05)  # the background waiter ages past the guard
    fresh = asyncio.ensure_future(wait_for(CLASS_INTERACTIVE, "fresh-live"))
    await asyncio.sleep(0)
    holder.release()
    await asyncio.gather(aged, fresh)
    # promotion lifts the aged waiter to the interactive class with its
    # ORIGINAL sequence number: it outranks the younger interactive
    assert order == ["aged-bg", "fresh-live"]
    assert lane.counters["starved_promotions"] == 1
    assert lane.starved_total.value() == 1


async def test_lane_pause_parks_and_resume_restores():
    lane = DeviceLane()
    holder = await lane.admit(CLASS_INTERACTIVE)
    queued = asyncio.ensure_future(lane.admit(CLASS_CATCHUP, site="queued"))
    await asyncio.sleep(0)
    lane.pause()
    # the queued non-exempt waiter defers instead of stacking on a
    # wedged device
    with pytest.raises(LaneDeferred):
        await queued
    # the door defers immediately too, for every non-exempt class
    for cls in (CLASS_INTERACTIVE, CLASS_CATCHUP, CLASS_BACKGROUND):
        with pytest.raises(LaneDeferred):
            await lane.admit(cls)
    assert lane.counters["deferrals"] == 4
    # pause-exempt canary admission still flows (half-open recovery)
    holder.release()
    probe = await lane.admit(CLASS_CANARY, ignore_pause=True)
    probe.release()
    lane.resume()
    ticket = await lane.admit(CLASS_INTERACTIVE)
    ticket.release()
    assert lane.counters["admissions"] == 3


async def test_lane_deadline_defers_queued_waiter():
    lane = DeviceLane()
    holder = await lane.admit(CLASS_INTERACTIVE)
    started = time.monotonic()
    with pytest.raises(LaneDeferred) as info:
        await lane.admit(CLASS_BACKGROUND, deadline_s=0.02)
    assert info.value.reason == "deadline"
    assert time.monotonic() - started < 1.0
    assert not lane.contended(), "deferred waiter must leave the queue"
    holder.release()


async def test_lane_preemption_accounting():
    lane = DeviceLane()
    bg = await lane.admit(CLASS_BACKGROUND)
    assert not bg.should_yield()
    live = asyncio.ensure_future(lane.admit(CLASS_INTERACTIVE))
    await asyncio.sleep(0)
    assert bg.should_yield(), "interactive waiter must signal preemption"
    bg.release(preempted=True)
    ticket = await live
    ticket.release()
    assert lane.counters["preemptions"] == 1
    assert lane.preemptions_total.value() == 1


async def test_lane_dispatch_accounting_flags_bypass():
    lane = DeviceLane()
    lane.note_dispatch("flush")
    assert lane.counters["dispatches_bypass"] == 1
    ticket = await lane.admit(CLASS_INTERACTIVE)
    lane.note_dispatch("flush", batches=3)
    ticket.release()
    assert lane.counters["dispatches_in_lane"] == 3
    assert lane.counters["dispatches_bypass"] == 1


# -- BatchGovernor -----------------------------------------------------------


def test_governor_drains_immediately_past_watermark():
    governor = BatchGovernor(base_interval_ms=5.0, drain_watermark=100)
    assert governor.flush_delay_s(pending_ops=100) == 0.0
    # burst-bounded, never an unbounded inline drain (head-of-line risk)
    assert governor.max_batches(pending_ops=100) == 8
    assert governor.counters["drains"] == 1


def test_governor_stretches_sparse_and_keeps_base_under_load():
    governor = BatchGovernor(
        base_interval_ms=5.0, max_stretch=4.0, drain_watermark=1000
    )
    # no arrivals yet: the first tick takes the full stretch — nothing
    # else is coming and broadcasts don't wait on this tick
    assert governor.flush_delay_s(pending_ops=1) == pytest.approx(0.02)
    assert governor.counters["stretches"] == 1
    # a sustained burst drives the EWMA past one op per base tick:
    # cadence returns to base
    now = time.monotonic()
    for i in range(50):
        governor.note_arrival(8, now=now + i * 0.001)
    assert governor.arrival_rate(now=now + 0.05) > 200.0
    assert governor.flush_delay_s(pending_ops=1) == pytest.approx(0.005)
    # silence decays the rate back toward sparse
    assert governor.arrival_rate(now=now + 30.0) < 1.0


def test_governor_congestion_caps_batches_and_cadence():
    governor = BatchGovernor(base_interval_ms=5.0, drain_watermark=100)
    assert governor.max_batches(pending_ops=500, congested=True) == 1
    assert governor.counters["congestion_caps"] == 1
    # congested ticks never shorten below base even when sparse, and
    # land in their own regime counter (not steady_ticks)
    assert governor.flush_delay_s(pending_ops=1, congested=True) == (
        pytest.approx(0.005)
    )
    assert governor.counters["congested_ticks"] == 1
    assert governor.counters["steady_ticks"] == 0


def test_governor_burst_cap_follows_measured_device_time():
    """Measured per-batch device time bounds the watermark burst: one
    admission's batches fit ~one base interval of device work."""
    governor = BatchGovernor(base_interval_ms=5.0, drain_watermark=100)
    assert governor.max_batches(pending_ops=1000) == 8  # no measurement yet
    governor.note_cycle({"batches": 1, "dispatch_ms": 0.0, "device_sync_ms": 10.0})
    assert governor.device_ms_ewma == pytest.approx(2.5)
    assert governor.max_batches(pending_ops=1000) == 2  # 5ms budget / 2.5ms
    # empty cycles do not re-fold the stale measurement
    governor.note_cycle({"batches": 0})
    assert governor.device_ms_ewma == pytest.approx(2.5)
    # a very slow backend still dispatches one batch per admission
    for _ in range(8):
        governor.note_cycle({"batches": 1, "device_sync_ms": 100.0})
    assert governor.max_batches(pending_ops=1000) == 1


def test_governor_never_changes_what_flushes():
    """Policy outputs are cadence + batch counts only: feeding wildly
    different load histories never makes max_batches drop queued work
    (None = drain all, ints >= 1)."""
    governor = BatchGovernor(base_interval_ms=5.0, drain_watermark=64)
    for pending in (0, 1, 63, 64, 100000):
        for congested in (False, True):
            batches = governor.max_batches(pending, congested)
            assert batches is None or batches >= 1


# -- cross-plane compile sharing ---------------------------------------------


def test_shared_warm_registry_skips_covered_shapes():
    reset_warm_registry()
    first = MergePlane(num_docs=8, capacity=128)
    grid = first.warmup_shapes()
    full_grid_len = len(grid) + len(first.warmup_aux_shapes())
    assert first.warmup_compiles(shared=True) is True
    assert first.compile_watch.fresh_compiles == full_grid_len
    # an identically-shaped plane skips every covered shape: no
    # dispatches, tracker seeded so live flushes classify as the
    # cache hits they are (module-level jit cache)
    second = MergePlane(num_docs=8, capacity=128)
    assert second.warmup_compiles(shared=True) is False
    assert second.compile_watch.fresh_compiles == 0
    for k, b in grid:
        site = "integrate_sparse" if b < second.num_docs else "integrate_dense"
        assert second.compile_watch.seen(site, (k, b))
    # a different geometry is NOT covered (different compiled programs)
    other = MergePlane(num_docs=8, capacity=256)
    assert other.warmup_compiles(shared=True) is True
    # direct (unshared) warmups keep their full per-plane behavior
    third = MergePlane(num_docs=8, capacity=128)
    third.warmup_compiles()
    assert third.compile_watch.fresh_compiles == full_grid_len
    reset_warm_registry()


# -- extension integration ---------------------------------------------------


class _ServedDoc(Doc):
    """Minimal server-document double for driving the extension's
    capture/serve seams without a websocket stack."""

    def __init__(self, name: str) -> None:
        super().__init__()
        self.name = name
        self.sync_source = None
        self.broadcast_source = None
        self.broadcast_frames: list[bytes] = []

    def get_connections_count(self) -> int:
        return 1

    def queue_broadcast(self, update: bytes, on_complete=None) -> None:
        self.broadcast_frames.append(update)
        if on_complete is not None:
            on_complete(time.perf_counter())

    def broadcast_update_frame(self, update: bytes) -> None:
        self.broadcast_frames.append(update)


def _scripted_workload(seed: int, docs: int, edits: int):
    """Deterministic mixed workload: per-doc fixed-client source docs
    emitting incremental updates (inserts + deletes), interleaved
    across docs by a seeded schedule. Returns (names, updates) where
    updates is a list of (name, update_bytes)."""
    rng = random.Random(seed)
    sources = {}
    names = [f"diff-{i}" for i in range(docs)]
    for i, name in enumerate(names):
        source = Doc()
        source.client_id = 1000 + i  # fixed ids => byte-stable updates
        sources[name] = source
    updates = []
    for _ in range(edits):
        name = names[rng.randrange(docs)]
        source = sources[name]
        text = source.get_text("t")
        before = encode_state_as_update(source)
        length = len(text.to_string())
        if length > 8 and rng.random() < 0.3:
            start = rng.randrange(length - 4)
            text.delete(start, rng.randrange(1, 4))
        else:
            pos = rng.randrange(length + 1)
            text.insert(pos, rng.choice("abcdef") * rng.randrange(1, 6))
        # state-vector diff of this one edit
        from hocuspocus_tpu.crdt import encode_state_vector

        probe = Doc()
        apply_update(probe, before)
        updates.append(
            (name, encode_state_as_update(source, encode_state_vector(probe)))
        )
    return names, updates, sources


async def _run_workload(extension, names, updates):
    docs = {}
    for name in names:
        doc = _ServedDoc(name)
        docs[name] = doc
        await extension.after_load_document(
            Payload(instance=None, document_name=name, document=doc)
        )
    for i, (name, update) in enumerate(updates):
        doc = docs[name]
        apply_update(doc, update)
        captured = extension.try_capture(doc, update, origin=None)
        assert captured, f"update {i} fell off the plane"
        if i % 7 == 0:
            await asyncio.sleep(0.002)  # let timers interleave
    # drain everything still queued, then close the broadcast tail
    await extension._flush_now(max_batches=None, final=True)
    extension._broadcast_served(cross_instance=False)
    return docs


async def test_governor_on_off_state_is_byte_identical():
    """The differential acceptance test: the governor changes flush
    cadence and batch counts, never content — the same fuzzed mixed
    workload produces byte-identical plane-served state with the
    governor (and lane) on vs off."""
    names, updates, sources = _scripted_workload(seed=7, docs=3, edits=60)
    ext_on = TpuMergeExtension(
        serve=True,
        num_docs=8,
        capacity=2048,
        flush_interval_ms=1,
        governor=True,
        lane=DeviceLane(),
        native_lane=False,
    )
    ext_off = TpuMergeExtension(
        serve=True,
        num_docs=8,
        capacity=2048,
        flush_interval_ms=1,
        governor=False,
        lane=False,
        native_lane=False,
    )
    docs_on = await _run_workload(ext_on, names, updates)
    docs_off = await _run_workload(ext_off, names, updates)
    for name in names:
        want = sources[name].get_text("t").to_string()
        assert ext_on.plane.text(name) == want
        assert ext_off.plane.text(name) == want
        served_on = ext_on.serving.encode_state_as_update(name, docs_on[name])
        served_off = ext_off.serving.encode_state_as_update(
            name, docs_off[name]
        )
        assert served_on is not None and served_on == served_off
    ext_on.cancel_timers()
    ext_off.cancel_timers()


async def test_no_device_dispatch_bypasses_the_lane():
    """The scheduler-accounting acceptance test: drive the full serving
    pipeline — load-time presync flushes, live captures, the warm grid,
    eviction, hydration — through a live server and assert every device
    dispatch happened under a lane admission."""
    lane = DeviceLane()
    ext = TpuMergeExtension(
        serve=True,
        num_docs=8,
        capacity=1024,
        flush_interval_ms=1,
        lane=lane,
        evict_idle_secs=0.2,
        hydrate_batch=4,
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="lane-doc")
    b = new_provider(server, name="lane-doc")
    try:
        from tests.utils import wait_synced

        await wait_synced(a, b)
        a.document.get_text("t").insert(0, "through the lane;")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "through the lane;"
            )
        )
        # idle out the doc so the residency sweep evicts it, then edit
        # again: the hydration queue re-admits it through the lane
        await retryable_assertion(
            lambda: _assert(ext.plane.counters["docs_evicted"] >= 1),
            timeout=15,
        )
        a.document.get_text("t").insert(0, "rehydrate;")
        await retryable_assertion(
            lambda: _assert(ext.plane.counters["docs_hydrated"] >= 1),
            timeout=15,
        )
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string()
                == "rehydrate;through the lane;"
            )
        )
        # warm grid + presync flushes + live flushes + hydration drain
        # all dispatched — and every one under an admission
        await retryable_assertion(
            lambda: _assert(lane.counters["dispatches_in_lane"] > 0)
        )
        assert lane.counters["dispatches_bypass"] == 0, lane.snapshot()
        assert lane.class_admissions[CLASS_INTERACTIVE] > 0
        assert lane.class_admissions[CLASS_CATCHUP] > 0, "hydration rode the lane"
        assert lane.class_admissions[CLASS_CANARY] > 0, "warm grid rode the lane"
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
    assert lane.counters["dispatches_bypass"] == 0


async def test_debug_scheduler_endpoint_and_lane_metrics():
    """`GET /debug/scheduler` serves the lane + governor state, and the
    lane's telemetry families render on /metrics."""
    import json

    import aiohttp

    from hocuspocus_tpu.observability import Metrics

    lane = DeviceLane()
    ext = TpuMergeExtension(
        serve=True, num_docs=8, capacity=512, flush_interval_ms=1, lane=lane
    )
    server = await new_hocuspocus(extensions=[Metrics(), ext])
    a = new_provider(server, name="sched-debug-doc")
    try:
        from tests.utils import wait_synced

        await wait_synced(a)
        a.document.get_text("t").insert(0, "observed")
        await retryable_assertion(
            lambda: _assert(lane.counters["admissions"] > 0)
        )
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/debug/scheduler") as response:
                assert response.status == 200
                body = json.loads(await response.text())
            async with session.get(f"{server.http_url}/metrics") as response:
                metrics_text = await response.text()
        assert body["lane"]["paused"] is False
        assert body["lane"]["classes"]["interactive"]["admissions"] > 0
        assert body["governors"][0]["drain_watermark"] == 256
        assert body["phase_offsets_ms"] == [None]
        assert "hocuspocus_tpu_lane_wait_seconds_bucket" in metrics_text
        assert "hocuspocus_tpu_lane_admissions_total" in metrics_text
        assert "hocuspocus_tpu_lane_occupancy" in metrics_text
    finally:
        a.destroy()
        await server.destroy()


async def test_sharded_router_staggers_phases_and_shares_one_lane():
    from hocuspocus_tpu.tpu.sharded_extension import ShardedTpuMergeExtension

    lane = DeviceLane()
    ext = ShardedTpuMergeExtension(
        shards=4, num_docs=8, capacity=256, flush_interval_ms=8.0, lane=lane
    )
    offsets = [shard.phase_offset_ms for shard in ext.shards]
    assert offsets == [0.0, 2.0, 4.0, 6.0]
    assert all(shard.lane is lane for shard in ext.shards)
    assert ext.lane is lane
    snapshot = ext.scheduler_snapshot()
    assert snapshot["lane"]["paused"] is False
    assert len(snapshot["governors"]) == 4
    for shard in ext.shards:
        shard.cancel_timers()


async def test_phase_alignment_never_fires_early():
    ext = TpuMergeExtension(
        num_docs=8, capacity=256, flush_interval_ms=10.0,
        phase_offset_ms=5.0, governor=False, lane=False,
    )
    interval = 0.010
    for delay in (0.0, 0.004, 0.010):
        aligned = ext._align_to_phase(delay, interval)
        assert aligned >= delay
        assert aligned <= delay + interval + 1e-9
    ext.cancel_timers()
