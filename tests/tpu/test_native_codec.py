"""Native C++ codec: build, parity with the Python decoder, speed."""

import random

import pytest

from hocuspocus_tpu.crdt import Doc, encode_state_as_update
from hocuspocus_tpu.native import build, get_codec


@pytest.fixture(scope="module")
def codec():
    assert build(), "native codec failed to build"
    codec = get_codec()
    assert codec is not None
    return codec


def test_utf16_len(codec):
    assert codec.utf16_len("hello") == 5
    assert codec.utf16_len("a😀b") == 4
    assert codec.utf16_len("") == 0
    assert codec.utf16_len("é") == 1


def test_decode_simple_update(codec):
    doc = Doc()
    doc.get_text("t").insert(0, "hello world")
    update = encode_state_as_update(doc)
    structs, deletes = codec.decode_update(update)
    assert len(structs) == 1
    client, clock, kind, oc, ok, rc, rk, payload = structs[0]
    assert client == doc.client_id
    assert clock == 0
    assert kind == 0  # string
    assert payload == "hello world"
    assert deletes == []


def test_decode_with_deletes(codec):
    doc = Doc(gc=False)
    text = doc.get_text("t")
    text.insert(0, "hello world")
    text.delete(0, 6)
    update = encode_state_as_update(doc)
    structs, deletes = codec.decode_update(update)
    assert len(deletes) == 1
    assert deletes[0][2] == 6  # deleted length


def test_decode_parity_with_python(codec):
    """Native and Python decode paths produce identical lowered ops."""
    import os

    from hocuspocus_tpu.tpu.lowering import DocLowerer

    random.seed(5)
    doc = Doc()
    text = doc.get_text("t")
    updates = []
    doc.on("update", lambda update, *rest: updates.append(update))
    for _ in range(60):
        if random.random() < 0.7 or len(text) == 0:
            text.insert(random.randint(0, len(text)), random.choice("abcé😀") * random.randint(1, 25))
        else:
            pos = random.randrange(len(text))
            text.delete(pos, min(random.randint(1, 6), len(text) - pos))

    def lower_all(lowerer):
        seq_ops, map_ops = [], []
        for update in updates:
            seqs, maps, tombs = lowerer.lower_update(update)
            for key in sorted(seqs):
                seq_ops.extend((key, op) for op in seqs[key])
            map_ops.extend(maps)
            assert tombs == []  # plain text: no map content to tombstone
        return seq_ops, map_ops

    native_lowerer = DocLowerer()
    native_seq, native_map = lower_all(native_lowerer)

    os.environ["HOCUSPOCUS_TPU_NO_NATIVE"] = "1"
    try:
        py_lowerer = DocLowerer()
        py_seq, py_map = lower_all(py_lowerer)
    finally:
        del os.environ["HOCUSPOCUS_TPU_NO_NATIVE"]

    assert not native_lowerer.unsupported and not py_lowerer.unsupported
    assert native_seq == py_seq
    assert native_map == py_map == []
    assert len(native_seq) > 0


def test_decode_unsupported_content_flagged(codec):
    doc = Doc()
    doc.get_map("m").set("k", {"nested": [1, 2]})
    update = encode_state_as_update(doc)
    structs, deletes = codec.decode_update(update)
    assert any(s[2] == 4 for s in structs)  # kind 4 = other content


def test_native_speedup(codec):
    """The native decoder should beat the Python one comfortably.

    Best-of-3 per side: wall-clock comparisons on a loaded host are
    noisy, and a single scheduler stall must not flip the verdict."""
    import time

    doc = Doc()
    text = doc.get_text("t")
    for i in range(200):
        text.insert(len(text), f"chunk {i} of text content ")
    update = encode_state_as_update(doc)

    from hocuspocus_tpu.crdt.delete_set import DeleteSet
    from hocuspocus_tpu.crdt.encoding import Decoder
    from hocuspocus_tpu.crdt.update import _read_client_struct_refs

    n = 300

    def time_native() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            codec.decode_update(update)
        return time.perf_counter() - t0

    def time_python() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            d = Decoder(update)
            _read_client_struct_refs(d)
            DeleteSet.read(d)
        return time.perf_counter() - t0

    native_time = min(time_native() for _ in range(3))
    python_time = min(time_python() for _ in range(3))
    assert native_time < python_time, (native_time, python_time)


def test_corruption_fuzz_native_and_python_agree(codec):
    """Truncated/bit-flipped updates: both decoders accept or both reject.

    The native decoder faces untrusted bytes (anything a client sends
    lands here via the merge-plane lowering), so it must never crash
    and must classify malformed inputs like the Python reference.
    """
    from hocuspocus_tpu.tpu import lowering

    rng = random.Random(99)
    doc = Doc()
    text = doc.get_text("t")
    for i in range(30):
        text.insert(rng.randint(0, len(text)), "word%d " % i)
        if len(text) > 10 and rng.random() < 0.3:
            text.delete(rng.randrange(len(text) - 5), 3)
    update = bytearray(encode_state_as_update(doc))

    def python_decode(data):
        saved = lowering.get_codec
        lowering.get_codec = lambda: None
        try:
            return lowering._decode_update(bytes(data))
        finally:
            lowering.get_codec = saved

    cases = [bytes(update[:n]) for n in range(0, len(update), 7)]
    for _ in range(150):
        mutated = bytearray(update)
        for _ in range(rng.randint(1, 4)):
            mutated[rng.randrange(len(mutated))] = rng.randrange(256)
        cases.append(bytes(mutated))

    agree_fail = agree_ok = 0
    for data in cases:
        try:
            native = codec.decode_update(data)
            native_ok = True
        except Exception:
            native_ok = False
        try:
            python_decode(data)
            python_ok = True
        except Exception:
            python_ok = False
        # the decoders need not produce identical struct lists for
        # *corrupted-but-parseable* inputs (unknown content kinds may
        # be classified differently), but neither may crash the
        # process, and a clean input must decode in both
        if native_ok and python_ok:
            agree_ok += 1
        elif not native_ok and not python_ok:
            agree_fail += 1
    assert agree_ok + agree_fail >= len(cases) * 0.9, (
        f"decoders disagreed on {len(cases) - agree_ok - agree_fail} of {len(cases)}"
    )
    # and the pristine update decodes identically
    n_structs, n_deletes = codec.decode_update(bytes(update))
    p_structs, p_deletes = python_decode(bytes(update))
    assert len(n_structs) == len(p_structs)
    assert sorted(tuple(d) for d in n_deletes) == sorted(tuple(d) for d in p_deletes)
