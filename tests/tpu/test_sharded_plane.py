"""The merge plane over a multi-chip mesh, serving real traffic.

tests/conftest.py provides a virtual 8-device CPU mesh; the same code
path targets real chips over ICI (SURVEY.md §5.8: the doc axis is the
data-parallel scaling dimension). These tests prove the PLANE — not
just the bare kernel (tests/tpu/test_pallas_kernels.py) — runs over a
mesh: sharded arenas behind the live server, serve-mode sync +
broadcasts, health readbacks from sharded state.
"""

import jax

from hocuspocus_tpu.tpu import TpuMergeExtension
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.sharding import make_mesh
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


def test_sharded_plane_matches_single_chip():
    """Same updates through a mesh-backed and a single-chip plane must
    produce identical device state and text."""
    from hocuspocus_tpu.crdt import Doc

    assert len(jax.devices()) == 8
    mesh = make_mesh(doc_axis=4)  # 2D: 4-way doc x 2-way unit(sequence)

    single = MergePlane(num_docs=8, capacity=128)
    sharded = MergePlane(num_docs=8, capacity=128, mesh=mesh)

    doc = Doc()
    updates = []
    doc.on("update", lambda update, *rest: updates.append(update))
    text = doc.get_text("t")
    text.insert(0, "hello mesh world")
    text.delete(5, 5)
    text.insert(5, " sharded")

    for plane in (single, sharded):
        plane.register("d")
        for update in updates:
            plane.enqueue_update("d", update)
        plane.flush()
    assert sharded.text("d") == single.text("d") == text.to_string()

    import numpy as np

    for name, a, b in zip(single.state._fields, single.state, sharded.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


async def test_serve_mode_over_mesh_end_to_end():
    """Serve-mode plane with sharded arenas behind the live server:
    providers sync and converge through mesh-resident state."""
    mesh = make_mesh(doc_axis=8)
    ext = TpuMergeExtension(
        num_docs=32, capacity=256, flush_interval_ms=1, serve=True, mesh=mesh
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="meshdoc")
    b = new_provider(server, name="meshdoc")
    try:
        await wait_synced(a, b)
        a.document.get_text("body").insert(0, "over the mesh")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("body").to_string() == "over the mesh"
            )
        )
        assert ext.plane.counters["plane_broadcasts"] >= 1
        assert ext.plane.counters["cpu_fallbacks"] == 0

        # late joiner syncs from sharded device state
        serves = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="meshdoc")
        await wait_synced(c)
        assert c.document.get_text("body").to_string() == "over the mesh"
        assert ext.plane.counters["sync_serves"] > serves
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


def test_mesh_divisibility_validated():
    import pytest

    mesh = make_mesh(doc_axis=8)
    with pytest.raises(ValueError):
        MergePlane(num_docs=10, capacity=128, mesh=mesh)  # 10 % 8 != 0


async def test_rle_serve_mode_over_mesh_end_to_end():
    """RLE arena with mesh-sharded entries behind the live server —
    the churn-surviving arena composes with multi-chip sharding."""
    mesh = make_mesh(doc_axis=8)
    ext = TpuMergeExtension(
        num_docs=32, capacity=256, flush_interval_ms=1, serve=True, mesh=mesh,
        arena="rle",
    )
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="mesh-rle")
    b = new_provider(server, name="mesh-rle")
    try:
        await wait_synced(a, b)
        text = a.document.get_text("body")
        text.insert(0, "rle over the mesh")
        # churn a little so runs split/tombstone through the sharded step
        text.insert(3, "XY")
        text.delete(3, 2)
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("body").to_string() == "rle over the mesh"
            )
        )
        assert ext.plane.counters["cpu_fallbacks"] == 0
        c = new_provider(server, name="mesh-rle")
        await wait_synced(c)
        assert c.document.get_text("body").to_string() == "rle over the mesh"
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
