"""Pallas integrate kernel vs the XLA-scan reference path.

Runs in Pallas interpret mode on the virtual CPU backend (conftest);
the identical kernel code compiles via Mosaic on real TPU (bench.py).
"""

import numpy as np

from hocuspocus_tpu.tpu.kernels import (
    NONE_CLIENT,
    OpBatch,
    integrate_op_slots,
    make_empty_state,
)
from hocuspocus_tpu.tpu.pallas_kernels import _pick_block, integrate_op_slots_pallas


# one client below 2^31 and one above: same-origin concurrent inserts
# from these two exercise the YATA client-id tiebreak as an UNSIGNED
# compare (a signed compare would order them the other way round)
_CLIENTS = (7, 0x9000_0001)


def _random_stream(rng, num_docs, num_slots, next_clock):
    """Causally-valid two-client op stream with random origins.

    next_clock has shape (num_clients, num_docs).
    """
    import jax.numpy as jnp

    kind = rng.integers(0, 3, size=(num_slots, num_docs)).astype(np.int32)
    client = np.full((num_slots, num_docs), _CLIENTS[0], np.uint32)
    clock = np.zeros((num_slots, num_docs), np.int32)
    run_len = rng.integers(1, 9, size=(num_slots, num_docs)).astype(np.int32)
    lc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    lk = np.zeros((num_slots, num_docs), np.int32)
    rc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    rk = np.zeros((num_slots, num_docs), np.int32)
    for k in range(num_slots):
        for d in range(num_docs):
            ci = rng.integers(0, len(_CLIENTS))
            if kind[k, d] == 1:
                client[k, d] = _CLIENTS[ci]
                clock[k, d] = next_clock[ci, d]
                known = [(i, c) for i, c in enumerate(next_clock[:, d]) if c > 0]
                if known:
                    oi, oc = known[rng.integers(0, len(known))]
                    lc[k, d] = _CLIENTS[oi]
                    lk[k, d] = rng.integers(0, oc)
                    if rng.random() < 0.3:
                        ri, rcl = known[rng.integers(0, len(known))]
                        rc[k, d] = _CLIENTS[ri]
                        rk[k, d] = rng.integers(0, rcl)
                next_clock[ci, d] += run_len[k, d]
            elif kind[k, d] == 2:
                if next_clock[ci, d] == 0:
                    kind[k, d] = 0
                else:
                    client[k, d] = _CLIENTS[ci]
                    clock[k, d] = rng.integers(0, next_clock[ci, d])
                    run_len[k, d] = min(
                        run_len[k, d], next_clock[ci, d] - clock[k, d]
                    )
    return OpBatch(*map(jnp.asarray, (kind, client, clock, run_len, lc, lk, rc, rk)))


def test_pallas_matches_xla_scan_fuzz():
    rng = np.random.default_rng(7)
    num_docs, capacity, num_slots = 16, 256, 6
    next_clock = np.zeros((len(_CLIENTS), num_docs), np.int64)
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    for _ in range(3):
        ops = _random_stream(rng, num_docs, num_slots, next_clock)
        state_a, ca = integrate_op_slots(state_a, ops)
        state_b, cb = integrate_op_slots_pallas(state_b, ops, interpret=True)
        assert int(ca) == int(cb)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pallas_overflow_and_deps():
    """Capacity overflow and missing-origin ops behave like the XLA path."""
    import jax.numpy as jnp

    num_docs, capacity = 8, 32
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    mk = lambda arr, dt: jnp.asarray(np.asarray(arr, dt))
    # slot 0: fits; slot 1: overflows; slot 2: unknown left origin
    kind = mk([[1] * num_docs, [1] * num_docs, [1] * num_docs], np.int32)
    client = mk([[7] * num_docs] * 3, np.uint32)
    clock = mk([[0] * num_docs, [30] * num_docs, [99] * num_docs], np.int32)
    run_len = mk([[30] * num_docs, [30] * num_docs, [1] * num_docs], np.int32)
    lc = mk([[NONE_CLIENT] * num_docs, [7] * num_docs, [12345] * num_docs], np.uint32)
    lk = mk([[0] * num_docs, [0] * num_docs, [0] * num_docs], np.int32)
    rc = mk([[NONE_CLIENT] * num_docs] * 3, np.uint32)
    rk = mk([[0] * num_docs] * 3, np.int32)
    ops = OpBatch(kind, client, clock, run_len, lc, lk, rc, rk)
    state_a, _ = integrate_op_slots(state_a, ops)
    state_b, _ = integrate_op_slots_pallas(state_b, ops, interpret=True)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    assert bool(np.asarray(state_b.overflow).all())
    assert (np.asarray(state_b.length) == 30).all()  # dep-missing op skipped


def test_pick_block_respects_vmem():
    from hocuspocus_tpu.tpu.pallas_kernels import (
        _LIVE_BUFFERS,
        _VMEM_BUDGET,
        _VMEM_LIMIT,
    )

    assert _pick_block(8192, 2048) == 64
    assert _pick_block(8192, 32768) == 16  # huge arenas shrink the block
    assert _pick_block(7, 2048) == 0  # indivisible doc counts fall back
    # the chosen block's modeled footprint must fit the compiler cap we
    # actually request, or Mosaic rejects the kernel at compile time
    for docs, cap in ((8192, 5632), (8192, 2048), (100_000, 5632), (2048, 32768)):
        db = _pick_block(docs, cap)
        if db:
            assert _LIVE_BUFFERS * db * cap * 4 <= _VMEM_BUDGET <= _VMEM_LIMIT


def test_pick_block_model_covers_r02_oom_shape():
    """Regression for the round-2 Mosaic VMEM OOM at the bench shape.

    The driver bench ran docs=8192, capacity=5632, K=64; Mosaic measured
    a 19.68MB scoped allocation at db=32 — i.e. ~27.3 live (db, N) int32
    buffers — while the old model assumed 12 and the old budget was 14MB
    under a 16MB cap. Pin the model to that measurement: at the OOM
    shape the modeled footprint of db=32 must be >= the observed 19.68MB
    (so an optimistic model can't sneak back in), and the picked block's
    footprint must stay under the requested compiler cap.
    """
    from hocuspocus_tpu.tpu.pallas_kernels import _LIVE_BUFFERS, _VMEM_LIMIT

    observed_oom_bytes = 19_680_000  # "Scoped allocation with size 19.68M"
    assert _LIVE_BUFFERS * 32 * 5632 * 4 >= observed_oom_bytes
    db = _pick_block(8192, 5632)
    assert db > 0, "bench shape must stay on the Pallas path"
    assert _LIVE_BUFFERS * db * 5632 * 4 <= _VMEM_LIMIT


def test_pallas_compile_failure_falls_back_to_xla(monkeypatch):
    """A Mosaic failure must degrade to the XLA scan, then stop retrying."""
    import hocuspocus_tpu.tpu.pallas_kernels as pk

    calls = {"pallas": 0}

    def boom(state, ops, interpret):
        calls["pallas"] += 1
        raise RuntimeError("Mosaic says no (simulated VMEM OOM)")

    monkeypatch.setattr(pk, "_integrate_pallas", boom)
    monkeypatch.setattr(pk, "_pallas_broken_shapes", set())
    num_docs, capacity = 64, 256
    state = make_empty_state(num_docs, capacity)
    ops = OpBatch(
        kind=np.ones((2, num_docs), np.int32),
        client=np.full((2, num_docs), 7, np.uint32),
        clock=np.asarray([[0] * num_docs, [4] * num_docs], np.int32),
        run_len=np.full((2, num_docs), 4, np.int32),
        left_client=np.asarray(
            [[NONE_CLIENT] * num_docs, [7] * num_docs], np.uint32
        ),
        left_clock=np.zeros((2, num_docs), np.int32),
        right_client=np.full((2, num_docs), NONE_CLIENT, np.uint32),
        right_clock=np.zeros((2, num_docs), np.int32),
    )
    state, count = pk.integrate_op_slots_pallas(state, ops)
    assert int(count) == 2 * num_docs  # the XLA path served the flush
    assert (np.asarray(state.length) == 8).all()
    assert calls["pallas"] == 1
    # second flush at the same shape skips the broken compile entirely
    state, _ = pk.integrate_op_slots_pallas(state, ops)
    assert calls["pallas"] == 1
    assert (num_docs, capacity, 2) in pk._pallas_broken_shapes


def test_pallas_compiles_at_production_shape_on_tpu():
    """Mosaic-compiles (not interpret) the bench shape on a real TPU.

    Gated: needs the real chip, and the suite conftest pins this process
    to the virtual CPU mesh — so the compile runs in a clean subprocess.
    Run with HOCUSPOCUS_TPU_COMPILE_TEST=1 on TPU hardware; bench.py
    exercises the same shape every round either way.
    """
    import os
    import subprocess
    import sys

    import pytest

    if os.environ.get("HOCUSPOCUS_TPU_COMPILE_TEST") != "1":
        pytest.skip("set HOCUSPOCUS_TPU_COMPILE_TEST=1 on TPU hardware")
    snippet = (
        "import jax, numpy as np, jax.numpy as jnp\n"
        "assert jax.default_backend() == 'tpu', jax.default_backend()\n"
        "from hocuspocus_tpu.tpu.kernels import make_empty_state, OpBatch, NONE_CLIENT\n"
        "import hocuspocus_tpu.tpu.pallas_kernels as pk\n"
        "D, N, K = 8192, 5632, 64\n"
        "state = make_empty_state(D, N)\n"
        "ops = OpBatch(kind=jnp.ones((K, D), jnp.int32),\n"
        "    client=jnp.full((K, D), 7, jnp.uint32),\n"
        "    clock=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None] * 16, (K, D)),\n"
        "    run_len=jnp.full((K, D), 16, jnp.int32),\n"
        "    left_client=jnp.broadcast_to(jnp.where(jnp.arange(K)[:, None] == 0,\n"
        "        jnp.uint32(NONE_CLIENT), jnp.uint32(7)), (K, D)),\n"
        "    left_clock=jnp.broadcast_to(jnp.maximum(jnp.arange(K, dtype=jnp.int32)[:, None] * 16 - 1, 0), (K, D)),\n"
        "    right_client=jnp.full((K, D), NONE_CLIENT, jnp.uint32),\n"
        "    right_clock=jnp.zeros((K, D), jnp.int32))\n"
        "state, count = pk.integrate_op_slots_pallas(state, ops)\n"
        "assert not pk._pallas_broken_shapes, pk._pallas_broken_shapes\n"
        "assert int(np.asarray(state.length).sum()) == D * K * 16\n"
        "print('TPU-COMPILE-OK')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run(
        [sys.executable, "-c", snippet],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    )
    assert "TPU-COMPILE-OK" in proc.stdout, proc.stderr[-2000:]


def test_sharded_pallas_step_matches_xla():
    """shard_map(pallas) over a doc-only mesh == XLA sharded step."""
    import jax
    import numpy as np

    from hocuspocus_tpu.tpu.sharding import (
        make_mesh,
        make_sharded_state,
        make_sharded_step,
        ops_sharding,
    )

    assert len(jax.devices()) == 8
    mesh = make_mesh(doc_axis=8)  # doc-only: unit axis size 1
    num_docs, capacity, num_slots = 64, 128, 4

    rng = np.random.default_rng(3)
    next_clock = np.zeros((len(_CLIENTS), num_docs), np.int64)
    ops = _random_stream(rng, num_docs, num_slots, next_clock)
    op_shards = ops_sharding(mesh)
    ops = type(ops)(*(jax.device_put(f, s) for f, s in zip(ops, op_shards)))

    state_x = make_sharded_state(mesh, num_docs, capacity)
    step_x = make_sharded_step(mesh, use_pallas=False)
    state_x, count_x = step_x(state_x, ops)

    state_p = make_sharded_state(mesh, num_docs, capacity)
    step_p = make_sharded_step(mesh, use_pallas=True, interpret=True)
    state_p, count_p = step_p(state_p, ops)

    assert int(count_x) == int(count_p)
    for name, a, b in zip(state_x._fields, state_x, state_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
