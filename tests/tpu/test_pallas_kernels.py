"""Pallas integrate kernel vs the XLA-scan reference path.

Runs in Pallas interpret mode on the virtual CPU backend (conftest);
the identical kernel code compiles via Mosaic on real TPU (bench.py).
"""

import numpy as np

from hocuspocus_tpu.tpu.kernels import (
    NONE_CLIENT,
    OpBatch,
    integrate_op_slots,
    make_empty_state,
)
from hocuspocus_tpu.tpu.pallas_kernels import _pick_block, integrate_op_slots_pallas


# one client below 2^31 and one above: same-origin concurrent inserts
# from these two exercise the YATA client-id tiebreak as an UNSIGNED
# compare (a signed compare would order them the other way round)
_CLIENTS = (7, 0x9000_0001)


def _random_stream(rng, num_docs, num_slots, next_clock):
    """Causally-valid two-client op stream with random origins.

    next_clock has shape (num_clients, num_docs).
    """
    import jax.numpy as jnp

    kind = rng.integers(0, 3, size=(num_slots, num_docs)).astype(np.int32)
    client = np.full((num_slots, num_docs), _CLIENTS[0], np.uint32)
    clock = np.zeros((num_slots, num_docs), np.int32)
    run_len = rng.integers(1, 9, size=(num_slots, num_docs)).astype(np.int32)
    lc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    lk = np.zeros((num_slots, num_docs), np.int32)
    rc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    rk = np.zeros((num_slots, num_docs), np.int32)
    for k in range(num_slots):
        for d in range(num_docs):
            ci = rng.integers(0, len(_CLIENTS))
            if kind[k, d] == 1:
                client[k, d] = _CLIENTS[ci]
                clock[k, d] = next_clock[ci, d]
                known = [(i, c) for i, c in enumerate(next_clock[:, d]) if c > 0]
                if known:
                    oi, oc = known[rng.integers(0, len(known))]
                    lc[k, d] = _CLIENTS[oi]
                    lk[k, d] = rng.integers(0, oc)
                    if rng.random() < 0.3:
                        ri, rcl = known[rng.integers(0, len(known))]
                        rc[k, d] = _CLIENTS[ri]
                        rk[k, d] = rng.integers(0, rcl)
                next_clock[ci, d] += run_len[k, d]
            elif kind[k, d] == 2:
                if next_clock[ci, d] == 0:
                    kind[k, d] = 0
                else:
                    client[k, d] = _CLIENTS[ci]
                    clock[k, d] = rng.integers(0, next_clock[ci, d])
                    run_len[k, d] = min(
                        run_len[k, d], next_clock[ci, d] - clock[k, d]
                    )
    return OpBatch(*map(jnp.asarray, (kind, client, clock, run_len, lc, lk, rc, rk)))


def test_pallas_matches_xla_scan_fuzz():
    rng = np.random.default_rng(7)
    num_docs, capacity, num_slots = 16, 256, 6
    next_clock = np.zeros((len(_CLIENTS), num_docs), np.int64)
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    for _ in range(3):
        ops = _random_stream(rng, num_docs, num_slots, next_clock)
        state_a, ca = integrate_op_slots(state_a, ops)
        state_b, cb = integrate_op_slots_pallas(state_b, ops, interpret=True)
        assert int(ca) == int(cb)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pallas_overflow_and_deps():
    """Capacity overflow and missing-origin ops behave like the XLA path."""
    import jax.numpy as jnp

    num_docs, capacity = 8, 32
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    mk = lambda arr, dt: jnp.asarray(np.asarray(arr, dt))
    # slot 0: fits; slot 1: overflows; slot 2: unknown left origin
    kind = mk([[1] * num_docs, [1] * num_docs, [1] * num_docs], np.int32)
    client = mk([[7] * num_docs] * 3, np.uint32)
    clock = mk([[0] * num_docs, [30] * num_docs, [99] * num_docs], np.int32)
    run_len = mk([[30] * num_docs, [30] * num_docs, [1] * num_docs], np.int32)
    lc = mk([[NONE_CLIENT] * num_docs, [7] * num_docs, [12345] * num_docs], np.uint32)
    lk = mk([[0] * num_docs, [0] * num_docs, [0] * num_docs], np.int32)
    rc = mk([[NONE_CLIENT] * num_docs] * 3, np.uint32)
    rk = mk([[0] * num_docs] * 3, np.int32)
    ops = OpBatch(kind, client, clock, run_len, lc, lk, rc, rk)
    state_a, _ = integrate_op_slots(state_a, ops)
    state_b, _ = integrate_op_slots_pallas(state_b, ops, interpret=True)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    assert bool(np.asarray(state_b.overflow).all())
    assert (np.asarray(state_b.length) == 30).all()  # dep-missing op skipped


def test_pick_block_respects_vmem():
    assert _pick_block(8192, 2048) == 64
    assert _pick_block(8192, 32768) in (0, 8)  # huge arenas fall back/shrink
    assert _pick_block(7, 2048) == 0  # indivisible doc counts fall back


def test_sharded_pallas_step_matches_xla():
    """shard_map(pallas) over a doc-only mesh == XLA sharded step."""
    import jax
    import numpy as np

    from hocuspocus_tpu.tpu.sharding import (
        make_mesh,
        make_sharded_state,
        make_sharded_step,
        ops_sharding,
    )

    assert len(jax.devices()) == 8
    mesh = make_mesh(doc_axis=8)  # doc-only: unit axis size 1
    num_docs, capacity, num_slots = 64, 128, 4

    rng = np.random.default_rng(3)
    next_clock = np.zeros((len(_CLIENTS), num_docs), np.int64)
    ops = _random_stream(rng, num_docs, num_slots, next_clock)
    op_shards = ops_sharding(mesh)
    ops = type(ops)(*(jax.device_put(f, s) for f, s in zip(ops, op_shards)))

    state_x = make_sharded_state(mesh, num_docs, capacity)
    step_x = make_sharded_step(mesh, use_pallas=False)
    state_x, count_x = step_x(state_x, ops)

    state_p = make_sharded_state(mesh, num_docs, capacity)
    step_p = make_sharded_step(mesh, use_pallas=True, interpret=True)
    state_p, count_p = step_p(state_p, ops)

    assert int(count_x) == int(count_p)
    for name, a, b in zip(state_x._fields, state_x, state_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
