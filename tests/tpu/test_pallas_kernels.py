"""Pallas integrate kernel vs the XLA-scan reference path.

Runs in Pallas interpret mode on the virtual CPU backend (conftest);
the identical kernel code compiles via Mosaic on real TPU (bench.py).
"""

import numpy as np

from hocuspocus_tpu.tpu.kernels import (
    NONE_CLIENT,
    OpBatch,
    integrate_op_slots,
    make_empty_state,
)
from hocuspocus_tpu.tpu.pallas_kernels import _pick_block, integrate_op_slots_pallas


def _random_stream(rng, num_docs, num_slots, next_clock):
    """Causally-valid single-client op stream with random origins."""
    import jax.numpy as jnp

    kind = rng.integers(0, 3, size=(num_slots, num_docs)).astype(np.int32)
    client = np.full((num_slots, num_docs), 7, np.uint32)
    clock = np.zeros((num_slots, num_docs), np.int32)
    run_len = rng.integers(1, 9, size=(num_slots, num_docs)).astype(np.int32)
    lc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    lk = np.zeros((num_slots, num_docs), np.int32)
    rc = np.full((num_slots, num_docs), NONE_CLIENT, np.uint32)
    rk = np.zeros((num_slots, num_docs), np.int32)
    for k in range(num_slots):
        for d in range(num_docs):
            if kind[k, d] == 1:
                clock[k, d] = next_clock[d]
                if next_clock[d] > 0:
                    lc[k, d] = 7
                    lk[k, d] = rng.integers(0, next_clock[d])
                    if rng.random() < 0.3:
                        rc[k, d] = 7
                        rk[k, d] = rng.integers(lk[k, d], next_clock[d])
                next_clock[d] += run_len[k, d]
            elif kind[k, d] == 2:
                if next_clock[d] == 0:
                    kind[k, d] = 0
                else:
                    clock[k, d] = rng.integers(0, next_clock[d])
                    run_len[k, d] = min(run_len[k, d], next_clock[d] - clock[k, d])
    return OpBatch(*map(jnp.asarray, (kind, client, clock, run_len, lc, lk, rc, rk)))


def test_pallas_matches_xla_scan_fuzz():
    rng = np.random.default_rng(7)
    num_docs, capacity, num_slots = 16, 256, 6
    next_clock = np.zeros(num_docs, np.int64)
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    for _ in range(3):
        ops = _random_stream(rng, num_docs, num_slots, next_clock)
        state_a, ca = integrate_op_slots(state_a, ops)
        state_b, cb = integrate_op_slots_pallas(state_b, ops, interpret=True)
        assert int(ca) == int(cb)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pallas_overflow_and_deps():
    """Capacity overflow and missing-origin ops behave like the XLA path."""
    import jax.numpy as jnp

    num_docs, capacity = 8, 32
    state_a = make_empty_state(num_docs, capacity)
    state_b = make_empty_state(num_docs, capacity)
    mk = lambda arr, dt: jnp.asarray(np.asarray(arr, dt))
    # slot 0: fits; slot 1: overflows; slot 2: unknown left origin
    kind = mk([[1] * num_docs, [1] * num_docs, [1] * num_docs], np.int32)
    client = mk([[7] * num_docs] * 3, np.uint32)
    clock = mk([[0] * num_docs, [30] * num_docs, [99] * num_docs], np.int32)
    run_len = mk([[30] * num_docs, [30] * num_docs, [1] * num_docs], np.int32)
    lc = mk([[NONE_CLIENT] * num_docs, [7] * num_docs, [12345] * num_docs], np.uint32)
    lk = mk([[0] * num_docs, [0] * num_docs, [0] * num_docs], np.int32)
    rc = mk([[NONE_CLIENT] * num_docs] * 3, np.uint32)
    rk = mk([[0] * num_docs] * 3, np.int32)
    ops = OpBatch(kind, client, clock, run_len, lc, lk, rc, rk)
    state_a, _ = integrate_op_slots(state_a, ops)
    state_b, _ = integrate_op_slots_pallas(state_b, ops, interpret=True)
    for name, a, b in zip(state_a._fields, state_a, state_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    assert bool(np.asarray(state_b.overflow).all())
    assert (np.asarray(state_b.length) == 30).all()  # dep-missing op skipped


def test_pick_block_respects_vmem():
    assert _pick_block(8192, 2048) == 64
    assert _pick_block(8192, 32768) in (0, 8)  # huge arenas fall back/shrink
    assert _pick_block(7, 2048) == 0  # indivisible doc counts fall back
