"""Run-length arena ≡ unit arena, on identical op streams.

The RLE kernel (tpu/kernels_rle.py) must produce the same DOCUMENT —
unit ids in rank order plus the same tombstone set — as the unit
kernel for every stream the unit kernel accepts, while consuming
O(runs) entries instead of O(units) slots. Streams come from three
sources: the bench generator's random-position insert/delete shape,
adversarial concurrent-sibling batches (YATA ties), and real yjs
updates lowered from CPU docs.
"""

import numpy as np
import pytest

from hocuspocus_tpu.tpu.kernels import (
    KIND_DELETE,
    KIND_INSERT,
    NONE_CLIENT,
    OpBatch,
    integrate_op_slots,
    make_empty_state,
)
from hocuspocus_tpu.tpu.kernels_rle import (
    delete_ranges,
    expand_to_units,
    integrate_op_slots_rle,
    make_empty_rle_state,
)


def _unit_doc(state, doc):
    """(client, clock, deleted) arrays in rank order from the unit arena."""
    length = int(np.asarray(state.length)[doc])
    client = np.asarray(state.id_client)[doc][:length]
    clock = np.asarray(state.id_clock)[doc][:length]
    rank = np.asarray(state.rank)[doc][:length]
    deleted = np.asarray(state.deleted)[doc][:length]
    order = np.argsort(rank)
    return client[order], clock[order], deleted[order]


def _ops_from_list(ops_list, num_docs=1):
    """(K, D) OpBatch from per-doc lists of op tuples."""
    k = max(len(col) for col in ops_list)
    fields = {
        "kind": np.zeros((k, num_docs), np.int32),
        "client": np.zeros((k, num_docs), np.uint32),
        "clock": np.zeros((k, num_docs), np.int32),
        "run_len": np.zeros((k, num_docs), np.int32),
        "left_client": np.full((k, num_docs), NONE_CLIENT, np.uint32),
        "left_clock": np.zeros((k, num_docs), np.int32),
        "right_client": np.full((k, num_docs), NONE_CLIENT, np.uint32),
        "right_clock": np.zeros((k, num_docs), np.int32),
    }
    for d, col in enumerate(ops_list):
        for i, op in enumerate(col):
            for name, value in op.items():
                fields[name][i, d] = value
    return OpBatch(**fields)


def _run_both(ops, num_docs, capacity=512, entries=256):
    unit = make_empty_state(num_docs, capacity)
    rle = make_empty_rle_state(num_docs, entries)
    unit, cu = integrate_op_slots(unit, ops)
    rle, cr = integrate_op_slots_rle(rle, ops)
    assert int(cu) == int(cr)
    assert not bool(np.asarray(unit.overflow).any())
    assert not bool(np.asarray(rle.overflow).any())
    return unit, rle


def _assert_docs_equal(unit, rle, num_docs):
    for d in range(num_docs):
        uc, uk, ud = _unit_doc(unit, d)
        rc, rk, rd = expand_to_units(rle, d)
        assert np.array_equal(uc, rc), d
        assert np.array_equal(uk, rk), d
        assert np.array_equal(ud, rd), d


def test_typing_run_costs_one_entry():
    """A 100-unit typed burst: 100 unit slots vs ONE rle entry."""
    ops = _ops_from_list(
        [[dict(kind=KIND_INSERT, client=7, clock=0, run_len=100)]]
    )
    unit, rle = _run_both(ops, 1)
    _assert_docs_equal(unit, rle, 1)
    assert int(np.asarray(unit.length)[0]) == 100
    assert int(np.asarray(rle.num_runs)[0]) == 1


def test_mid_run_insert_splits():
    """Insert anchored mid-run splits it: 3 entries, same document."""
    ops = _ops_from_list(
        [
            [
                dict(kind=KIND_INSERT, client=7, clock=0, run_len=10),
                # client 3 < 7 loses the YATA tie against the unit at
                # left_rank+1, so it blocks there and the run SPLITS
                dict(
                    kind=KIND_INSERT, client=3, clock=0, run_len=4,
                    left_client=7, left_clock=4,
                ),
            ]
        ]
    )
    unit, rle = _run_both(ops, 1)
    _assert_docs_equal(unit, rle, 1)
    assert int(np.asarray(rle.num_runs)[0]) == 3


def test_concurrent_siblings_order_by_client_id():
    """YATA tie: two inserts with the same left origin — ascending
    client id order, and an insert INTO the winner's run."""
    ops = _ops_from_list(
        [
            [
                dict(kind=KIND_INSERT, client=500, clock=0, run_len=6),
                dict(
                    kind=KIND_INSERT, client=100, clock=0, run_len=3,
                    left_client=500, left_clock=2,
                ),
                dict(
                    kind=KIND_INSERT, client=900, clock=0, run_len=2,
                    left_client=500, left_clock=2,
                ),
                dict(
                    kind=KIND_INSERT, client=700, clock=50, run_len=2,
                    left_client=100, left_clock=1,
                ),
            ]
        ]
    )
    unit, rle = _run_both(ops, 1)
    _assert_docs_equal(unit, rle, 1)


def test_high_bit_client_ids():
    """uint32 client ids above 2^31 (real yjs ids are random uint32)."""
    big, huge = 0x9000_0001, 0xF000_0000
    ops = _ops_from_list(
        [
            [
                dict(kind=KIND_INSERT, client=big, clock=0, run_len=5),
                dict(
                    kind=KIND_INSERT, client=huge, clock=0, run_len=3,
                    left_client=big, left_clock=1,
                ),
                dict(kind=KIND_DELETE, client=big, clock=1, run_len=2),
            ]
        ]
    )
    unit, rle = _run_both(ops, 1)
    _assert_docs_equal(unit, rle, 1)


def test_delete_splits_and_ranges():
    """Partial deletes split runs; delete_ranges reports exact merged
    id-ranges without a per-unit scan."""
    ops = _ops_from_list(
        [
            [
                dict(kind=KIND_INSERT, client=7, clock=0, run_len=20),
                dict(kind=KIND_DELETE, client=7, clock=5, run_len=4),
                dict(kind=KIND_DELETE, client=7, clock=9, run_len=2),  # adjacent
                dict(kind=KIND_DELETE, client=7, clock=15, run_len=3),
            ]
        ]
    )
    unit, rle = _run_both(ops, 1)
    _assert_docs_equal(unit, rle, 1)
    assert delete_ranges(rle, 0) == [(7, 5, 6), (7, 15, 3)]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_streams_match_unit_kernel(seed):
    """The bench generator's shape: multi-doc random-position inserts
    and id-range deletes, sequential clocks per doc-author."""
    rng = np.random.default_rng(seed)
    num_docs, slots = 8, 48
    cols = []
    for _ in range(num_docs):
        next_clock = 0
        col = []
        for _ in range(slots):
            if next_clock > 8 and rng.random() < 0.25:
                start = int(rng.integers(0, next_clock - 4))
                col.append(
                    dict(
                        kind=KIND_DELETE, client=7, clock=start,
                        run_len=int(rng.integers(1, 4)),
                    )
                )
            else:
                run = int(rng.integers(1, 6))
                op = dict(kind=KIND_INSERT, client=7, clock=next_clock, run_len=run)
                if next_clock > 0:
                    origin = int(rng.integers(0, next_clock))
                    op.update(left_client=7, left_clock=origin)
                col.append(op)
                next_clock += run
        cols.append(col)
    ops = _ops_from_list(cols, num_docs)
    unit, rle = _run_both(ops, num_docs, capacity=512, entries=256)
    _assert_docs_equal(unit, rle, num_docs)


@pytest.mark.parametrize("seed", [11, 12])
def test_real_lowered_docs_match_unit_kernel(seed):
    """Real yjs update streams (two CPU replicas cross-merging) lowered
    by the production DocLowerer, fed to both kernels."""
    import random

    from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
    from hocuspocus_tpu.tpu.lowering import DocLowerer

    rng = random.Random(seed)
    a, b = Doc(), Doc()
    docs = [a, b]
    updates: list[bytes] = []
    for doc in docs:
        doc.on("update", lambda u, *r: updates.append(u))
    for step in range(40):
        doc = docs[rng.randrange(2)]
        text = doc.get_text("t")
        if len(text) > 4 and rng.random() < 0.3:
            pos = rng.randrange(len(text) - 2)
            text.delete(pos, rng.randint(1, 2))
        else:
            text.insert(rng.randint(0, len(text)), rng.choice("abcdef") * rng.randint(1, 5))
        if rng.random() < 0.4:
            apply_update(a, encode_state_as_update(b))
            apply_update(b, encode_state_as_update(a))
    apply_update(a, encode_state_as_update(b))

    lowerer = DocLowerer()
    seq_ops, map_ops, tombs = lowerer.lower_update(encode_state_as_update(a))
    assert not lowerer.unsupported and not map_ops and not tombs
    (ops_list,) = seq_ops.values()
    col = [
        dict(
            kind=op.kind,
            client=op.client,
            clock=op.clock,
            run_len=op.run_len,
            left_client=op.left_client,
            left_clock=op.left_clock,
            right_client=op.right_client,
            right_clock=op.right_clock,
        )
        for op in ops_list
    ]
    ops = _ops_from_list([col])
    unit, rle = _run_both(ops, 1, capacity=1024, entries=512)
    _assert_docs_equal(unit, rle, 1)


def test_delete_splits_do_not_flag_overflow_at_tight_capacity():
    """A delete whose own boundary splits consume the last free entries
    must succeed WITHOUT sticky overflow (the capacity verdict is taken
    before the splits mutate num_runs)."""
    ops = _ops_from_list(
        [
            [
                dict(kind=KIND_INSERT, client=7, clock=0, run_len=20),
                dict(kind=KIND_DELETE, client=7, clock=5, run_len=4),
            ]
        ]
    )
    rle = make_empty_rle_state(1, 4)
    rle, _ = integrate_op_slots_rle(rle, ops)
    assert not bool(np.asarray(rle.overflow)[0])
    assert int(np.asarray(rle.num_runs)[0]) == 3
    assert delete_ranges(rle, 0) == [(7, 5, 4)]


@pytest.mark.slow  # ~35s of incremental fuzz: outside the tier-1 gate
@pytest.mark.parametrize("seed", [21, 22])
def test_incremental_batches_match_unit_kernel(seed):
    """Serving feeds ops incrementally across flush batches, not as one
    snapshot: lower each replica update as it arrives (one production
    DocLowerer, causal buffering included) and integrate batch by
    batch, comparing the two arenas after EVERY batch."""
    import random

    from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
    from hocuspocus_tpu.crdt.update import encode_state_vector
    from hocuspocus_tpu.tpu.lowering import DocLowerer

    rng = random.Random(seed)
    a, b = Doc(), Doc()
    lowerer = DocLowerer()
    unit = make_empty_state(1, 2048)
    rle = make_empty_rle_state(1, 1024)
    pending: list[dict] = []

    def ship(doc, other):
        nonlocal unit, rle, pending
        update = encode_state_as_update(doc, encode_state_vector(other))
        seq_ops, m, t = lowerer.lower_update(update)
        assert not lowerer.unsupported and not m and not t
        for ops_list in seq_ops.values():
            for op in ops_list:
                pending.append(
                    dict(
                        kind=op.kind, client=op.client, clock=op.clock,
                        run_len=op.run_len, left_client=op.left_client,
                        left_clock=op.left_clock, right_client=op.right_client,
                        right_clock=op.right_clock,
                    )
                )

    for step in range(30):
        doc = a if rng.random() < 0.5 else b
        text = doc.get_text("t")
        if len(text) > 5 and rng.random() < 0.3:
            text.delete(rng.randrange(len(text) - 2), rng.randint(1, 2))
        else:
            text.insert(
                rng.randint(0, len(text)), rng.choice("xyzw") * rng.randint(1, 6)
            )
        # cross-merge sometimes so each replica builds on the other
        if rng.random() < 0.5:
            apply_update(a, encode_state_as_update(b))
            apply_update(b, encode_state_as_update(a))
        # ship this replica's new ops to the "server" arenas
        ship(doc, Doc())  # full diff vs empty = everything; lowerer dedups
        if pending and rng.random() < 0.6:
            ops = _ops_from_list([pending])
            pending = []
            unit, _ = integrate_op_slots(unit, ops)
            rle, _ = integrate_op_slots_rle(rle, ops)
            _assert_docs_equal(unit, rle, 1)
    if pending:
        ops = _ops_from_list([pending])
        unit, _ = integrate_op_slots(unit, ops)
        rle, _ = integrate_op_slots_rle(rle, ops)
    _assert_docs_equal(unit, rle, 1)
    assert not bool(np.asarray(unit.overflow)[0])
    assert not bool(np.asarray(rle.overflow)[0])


def test_rle_kernel_shards_over_doc_mesh():
    """The RLE integrate runs unchanged under NamedSharding over the
    doc axis (the virtual 8-device CPU mesh used by every sharding
    test) and matches the unsharded result — mesh-readiness for the
    round-4 Pallas/plane wiring."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 8:
        import pytest

        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    mesh = Mesh(np.array(devices[:8]), ("doc",))
    num_docs = 16
    cols = []
    for d in range(num_docs):
        cols.append(
            [
                dict(kind=KIND_INSERT, client=7, clock=0, run_len=8 + d),
                dict(
                    kind=KIND_INSERT, client=3, clock=0, run_len=4,
                    left_client=7, left_clock=2,
                ),
                dict(kind=KIND_DELETE, client=7, clock=1, run_len=3),
            ]
        )
    ops = _ops_from_list(cols, num_docs)
    plain = make_empty_rle_state(num_docs, 64)
    plain, _ = integrate_op_slots_rle(plain, ops)

    row = NamedSharding(mesh, P("doc"))
    vec = NamedSharding(mesh, P(None, "doc"))
    # every state field leads with the doc axis, 1-D and 2-D alike
    sharded = jax.tree.map(
        lambda a: jax.device_put(np.asarray(a), row),
        make_empty_rle_state(num_docs, 64),
    )
    sharded_ops = jax.tree.map(lambda a: jax.device_put(np.asarray(a), vec), ops)
    sharded, _ = integrate_op_slots_rle(sharded, sharded_ops)
    for d in range(num_docs):
        pc, pk, pd = expand_to_units(plain, d)
        sc, sk, sd = expand_to_units(sharded, d)
        assert np.array_equal(pc, sc) and np.array_equal(pk, sk)
        assert np.array_equal(pd, sd)
