"""Resource-exhaustion degradation rails in serve mode.

The plane is a bounded accelerator, not the source of truth: when its
rows run out (plane_full) or a document outgrows its arena row
(capacity), the doc must degrade to the CPU path — counted, with a
full-state fallback broadcast so receivers that only saw plane frames
stay whole — while other docs stay plane-served. These are the safety
rails the 100k-doc regime leans on (BASELINE.md north star; SURVEY.md
§5.7 "documents is the scaling dimension").
"""

import asyncio

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_plane_full_degrades_newcomers_only():
    """Rows exhausted: later docs fall back to CPU; earlier docs stay
    plane-served and correct."""
    ext = TpuMergeExtension(num_docs=2, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    providers = []
    try:
        # two docs claim the two rows
        for d in range(2):
            a = new_provider(server, name=f"full-{d}")
            b = new_provider(server, name=f"full-{d}")
            providers += [a, b]
            await wait_synced(a, b)
            a.document.get_text("t").insert(0, f"doc {d}")
        # the third doc cannot get a row
        c1 = new_provider(server, name="full-2")
        c2 = new_provider(server, name="full-2")
        providers += [c1, c2]
        await wait_synced(c1, c2)
        c1.document.get_text("t").insert(0, "cpu-served")
        await retryable_assertion(
            lambda: _assert(c2.document.get_text("t").to_string() == "cpu-served")
        )
        assert ext.plane.counters["docs_retired_plane_full"] >= 1
        assert "full-2" not in ext._docs  # degraded to the CPU path
        # earlier docs still ride the plane and still converge
        assert "full-0" in ext._docs and "full-1" in ext._docs
        providers[0].document.get_text("t").insert(0, "more ")
        await retryable_assertion(
            lambda: _assert(
                providers[1].document.get_text("t").to_string() == "more doc 0"
            )
        )
    finally:
        for p in providers:
            p.destroy()
        await server.destroy()


async def test_concurrent_editors_converge_across_recycles():
    """Recycling races live traffic: two editors churn paragraphs on a
    tiny plane so recycles fire mid-stream, and every replica (both
    editors, the server doc, a late joiner) must still converge."""
    import random

    ext = TpuMergeExtension(num_docs=48, capacity=512, flush_interval_ms=1, serve=True,
                            native_lane=False)  # tests Python-plane recycling under load
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="race")
    b = new_provider(server, name="race")
    try:
        await wait_synced(a, b)
        from hocuspocus_tpu.crdt import YXmlElement, YXmlText

        rng = random.Random(7)
        for wave in range(16):
            for who, p in (("a", a), ("b", b)):
                frag = p.document.get_xml_fragment("x")
                el = YXmlElement("paragraph")
                frag.push([el])
                t = YXmlText()
                el.push([t])
                t.insert(0, f"{who}{wave:02d} " * rng.randint(4, 10))
                # delete OLDEST paragraphs down to a bounded live size
                # (concurrent random-middle deletes can GC ranges later
                # ops depend on, which is the separate 'unsupported'
                # rail): churning history while the live doc stays
                # small is the recycle scenario under test
                while len(frag) > 2:
                    frag.delete(0, 1)
            await asyncio.sleep(0.03)

        def converged():
            fa = a.document.get_xml_fragment("x")
            fb = b.document.get_xml_fragment("x")
            fs = server.documents["race"].get_xml_fragment("x")
            assert len(fa) == len(fb) == len(fs)
            assert fa.to_string() == fb.to_string() == fs.to_string()

        await retryable_assertion(converged, timeout=20)
        # the recycle runs as an async task behind the flush lock —
        # convergence (via the CPU fallback broadcasts) can land first
        await retryable_assertion(
            lambda: _assert(ext.plane.counters["docs_recycled"] >= 1)
        )
        # late joiner sees the same converged doc
        c = new_provider(server, name="race")
        try:
            await wait_synced(c)
            assert (
                c.document.get_xml_fragment("x").to_string()
                == a.document.get_xml_fragment("x").to_string()
            )
        finally:
            c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_offline_edits_merge_through_plane_on_reconnect():
    """The lossless-recovery story on the serve plane: a client editing
    while disconnected reconnects (server restart on the same port,
    fresh serve-mode plane), SyncStep1/2 exchange merges the offline
    edits, and the plane serves the merged doc to everyone."""
    from hocuspocus_tpu.server import Configuration, Server
    from tests.utils import wait_for

    ext1 = TpuMergeExtension(num_docs=8, capacity=1024, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext1])
    port = server.port
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "before restart")
        await asyncio.sleep(0.2)
        await server.destroy()
        ext2 = TpuMergeExtension(
            num_docs=8, capacity=1024, flush_interval_ms=1, serve=True
        )
        server2 = Server(Configuration(quiet=True, extensions=[ext2]))
        await server2.listen(port=port)
        provider.document.get_text("t").insert(0, "offline! ")
        await wait_for(lambda: provider.synced, timeout=20)
        await retryable_assertion(
            lambda: _assert(
                server2.documents["hocuspocus-test"].get_text("t").to_string()
                == "offline! before restart"
            ),
            timeout=15,
        )
        # the merged doc is plane-served to a fresh joiner
        assert "hocuspocus-test" in ext2._docs
        joiner = new_provider(server2)
        try:
            await wait_synced(joiner)
            assert (
                joiner.document.get_text("t").to_string() == "offline! before restart"
            )
            assert ext2.plane.counters["sync_serves"] >= 1
            assert ext2.plane.counters["cpu_fallbacks"] == 0
        finally:
            joiner.destroy()
        await server2.destroy()
    finally:
        provider.destroy()


async def test_capacity_recycle_reclaims_rows_for_subtree_churn():
    """A rich-text doc churning paragraphs (insert + delete whole
    elements) exhausts its append-only rows, but the collected
    subtrees vanish from the live snapshot — the doc recycles onto
    fresh rows and STAYS plane-served instead of degrading forever."""
    ext = TpuMergeExtension(num_docs=16, capacity=512, flush_interval_ms=1, serve=True,
                            native_lane=False)  # tests Python-plane recycling: a lane rebuild would compact for free
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="churny")
    b = new_provider(server, name="churny")
    try:
        await wait_synced(a, b)
        from hocuspocus_tpu.crdt import YXmlElement, YXmlText

        frag = a.document.get_xml_fragment("x")
        # each wave inserts a ~100-unit paragraph and deletes the
        # oldest: cumulative insertions blow past 512 while the live
        # doc stays ~2 paragraphs
        for wave in range(12):
            el = YXmlElement("paragraph")
            frag.push([el])
            t = YXmlText()
            el.push([t])
            t.insert(0, f"wave {wave:02d} " * 12)
            if len(frag) > 2:
                frag.delete(0, 1)
            await asyncio.sleep(0.05)
        await retryable_assertion(
            lambda: _assert(ext.plane.counters["docs_recycled"] >= 1)
        )
        # the doc is BACK on the plane after recycling
        await retryable_assertion(lambda: _assert("churny" in ext._docs))
        # convergence continues through the plane
        frag2 = b.document.get_xml_fragment("x")
        await retryable_assertion(
            lambda: _assert(len(frag2) == len(frag) and len(frag) >= 2)
        )
        # a late joiner syncs the live doc from the plane
        serves_before = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="churny")
        try:
            await wait_synced(c)
            assert len(c.document.get_xml_fragment("x")) == len(frag)
            assert ext.plane.counters["sync_serves"] > serves_before
        finally:
            c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_capacity_overflow_degrades_without_data_loss():
    """A doc outgrowing its arena row retires (capacity) mid-stream;
    the full-state CPU fallback keeps every receiver whole and edits
    keep flowing on the CPU path."""
    ext = TpuMergeExtension(num_docs=4, capacity=96, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="grower")
    b = new_provider(server, name="grower")
    try:
        await wait_synced(a, b)
        text = a.document.get_text("t")
        expected = ""
        # grow well past the 96-unit row in small increments so the
        # overflow happens mid-traffic, between plane broadcasts
        for i in range(10):
            chunk = f"chunk-{i:02d}-aaaaaaaaaaaa;"
            text.insert(len(expected), chunk)
            expected += chunk
            await asyncio.sleep(0.02)

        def converged():
            assert b.document.get_text("t").to_string() == expected

        await retryable_assertion(converged)
        assert ext.plane.counters["docs_retired_capacity"] >= 1
        assert ext.plane.counters["cpu_fallbacks"] >= 1
        assert "grower" not in ext._docs
        # steady state continues on the CPU path, both directions
        b.document.get_text("t").insert(0, ">> ")
        await retryable_assertion(
            lambda: _assert(a.document.get_text("t").to_string() == ">> " + expected)
        )
        # late joiner gets the whole doc via the CPU sync path
        c = new_provider(server, name="grower")
        try:
            await wait_synced(c)
            assert c.document.get_text("t").to_string() == ">> " + expected
        finally:
            c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
