"""Served-load harness (hocuspocus_tpu.loadgen) at CI scale.

The same harness bench.py uses for the at-scale served p99 — here with
small populations so CI proves the topology end-to-end: sockets-free
providers, sharded serve planes, background load, cross-instance Redis
fan-out (verdict item: "measure the SERVED 100k regime without
sockets").
"""

import pytest

from hocuspocus_tpu.loadgen import run_served_load

# ~70s of served-load topology runs: excluded from the tier-1 gate
# (-m 'not slow'); the full suite still runs wherever slow tests do
pytestmark = pytest.mark.slow


async def test_loadgen_single_instance():
    result = await run_served_load(
        num_docs=96,
        sampled=8,
        edits=12,
        shards=2,
        shard_rows=64,
        capacity=512,
        docs_per_socket=48,
        sync_timeout=60,
        budget_s=120,
    )
    assert result["metric"] == "served_merge_to_broadcast_p99_ms"
    assert result["value"] > 0
    assert result["extra"]["docs"] == 96
    assert result["extra"]["samples"] == 12
    # reproducibility: the harness RNG seed rides in the artifact
    assert result["extra"]["seed"] == 0
    health = result["extra"]["plane_health"][0]
    assert health["plane_broadcasts"] > 0
    assert health["cpu_fallbacks"] == 0
    # every doc landed on a serve plane
    assert result["extra"]["served_docs"][0] == 96


async def test_loadgen_cross_instance_redis():
    result = await run_served_load(
        num_docs=24,
        instances=2,
        sampled=4,
        edits=8,
        shards=2,
        shard_rows=32,
        capacity=512,
        docs_per_socket=24,
        sync_timeout=60,
        budget_s=120,
    )
    assert result["extra"]["cross_instance"] is True
    assert result["extra"]["samples"] == 8
    # the timed path crossed instances: instance 1 (readers) served too
    assert result["extra"]["served_docs"][1] >= 4
    for health in result["extra"]["plane_health"]:
        assert health["cpu_fallbacks"] == 0


async def test_loadgen_scales_population_beyond_fd_budget():
    """A population of sockets this size would exhaust default fd
    limits with real websockets (2 fds per socket end); in-process it
    is just objects. Keeps CI honest about the harness's reason to
    exist without burning minutes (1,024 docs)."""
    result = await run_served_load(
        num_docs=1024,
        sampled=8,
        edits=10,
        shards=4,
        shard_rows=384,
        capacity=256,
        docs_per_socket=256,
        sync_timeout=300,
        budget_s=300,
    )
    assert result["extra"]["served_docs"][0] == 1024
    assert result["extra"]["plane_health"][0]["cpu_fallbacks"] == 0
