"""Chaos for the degradation rails (round-4 verdict item 7).

The existing rails tests trigger CLEAN degradations (plane full,
capacity overflow). These inject the messy versions: the device step
dying mid-flush with broadcasts in flight, Redis vanishing during a
serve window, and a recycle storm colliding with a catch-up storm.
Invariants under every fault: no data loss (every provider converges to
the CPU-authoritative state), no stuck docs (each is either
plane-served or counted as degraded — counters account for every doc),
and the server keeps serving.

Reference analog: per-socket error isolation (`Server.ts:71-80`) is the
reference's whole fault story; the plane adds device/network fault
domains that need their own rails (SURVEY.md §5.3).
"""

import asyncio

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_device_fault_mid_flush_degrades_all_without_loss():
    """The device step raises (XlaRuntimeError stand-in) while served
    docs have fresh edits queued and broadcasts in flight. The dead
    flush consumed queued ops — every served doc must degrade via the
    full-state CPU broadcast, receivers stay whole, and edits keep
    flowing on the CPU path afterward."""
    ext = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    pairs = []
    try:
        for d in range(3):
            a = new_provider(server, name=f"chaos-{d}")
            b = new_provider(server, name=f"chaos-{d}")
            pairs.append((a, b))
            await wait_synced(a, b)
        for i, (a, _b) in enumerate(pairs):
            a.document.get_text("t").insert(0, f"pre{i};")
        await retryable_assertion(
            lambda: _assert(
                all(
                    b.document.get_text("t").to_string() == f"pre{i};"
                    for i, (_a, b) in enumerate(pairs)
                )
            )
        )
        served_before = len(ext._docs)
        assert served_before == 3, "setup: all docs should be plane-served"
        fallbacks_before = ext.plane.counters["cpu_fallbacks"]

        # kill the device: every step from here raises mid-flush
        # (both entry points — dense sweeps and sparse busy-doc batches)
        def dead_step_factory():
            def dead_step(state, ops, slots=None):
                raise RuntimeError("XlaRuntimeError: DEVICE_FAULT (injected)")

            return dead_step

        ext.plane._step_fn = dead_step_factory
        ext.plane._sparse_step_fn = dead_step_factory

        # edits DURING the fault window — their queued ops ride the
        # flush that dies
        for i, (a, _b) in enumerate(pairs):
            a.document.get_text("t").insert(0, f"mid{i};")

        # every served doc degrades; the accounting adds up
        await retryable_assertion(lambda: _assert(len(ext._docs) == 0))
        assert (
            ext.plane.counters["cpu_fallbacks"] - fallbacks_before == served_before
        ), "every served doc must be counted exactly once as a fallback"
        assert ext.plane.counters["docs_retired_fallback"] >= served_before

        # no data loss: the fault-window edits reach the other side
        await retryable_assertion(
            lambda: _assert(
                all(
                    b.document.get_text("t").to_string() == f"mid{i};pre{i};"
                    for i, (_a, b) in enumerate(pairs)
                )
            )
        )

        # steady state continues on the CPU path, both directions
        for i, (_a, b) in enumerate(pairs):
            b.document.get_text("t").insert(0, f"post{i};")
        await retryable_assertion(
            lambda: _assert(
                all(
                    a.document.get_text("t").to_string() == f"post{i};mid{i};pre{i};"
                    for i, (a, _b) in enumerate(pairs)
                )
            )
        )

        # late joiners cold-sync the whole state via the CPU path
        c = new_provider(server, name="chaos-0")
        try:
            await wait_synced(c)
            assert c.document.get_text("t").to_string() == "post0;mid0;pre0;"
        finally:
            c.destroy()
    finally:
        for a, b in pairs:
            a.destroy()
            b.destroy()
        await server.destroy()


async def test_redis_outage_during_serve_window_keeps_plane_and_heals():
    """Redis dies while a plane-served doc is mid-traffic: publish
    failures must NOT degrade the plane (the network fault domain is
    not the device fault domain). Edits made during the outage flow
    cross-instance once Redis returns, via resubscribe + the sync
    exchange."""
    redis = await MiniRedis().start()
    port = redis.port
    ext_a = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    ext_b = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    redis_a = Redis(port=port, identifier="out-a", disconnect_delay=100)
    redis_b = Redis(port=port, identifier="out-b", disconnect_delay=100)
    server_a = await new_hocuspocus(extensions=[redis_a, ext_a])
    server_b = await new_hocuspocus(extensions=[redis_b, ext_b])
    provider_a = new_provider(server_a, name="outage-doc")
    provider_b = new_provider(server_b, name="outage-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "up;")
        await retryable_assertion(
            lambda: _assert(provider_b.document.get_text("t").to_string() == "up;")
        )
        assert "outage-doc" in ext_a._docs and "outage-doc" in ext_b._docs

        # the outage, mid-capture-window: publishes start failing
        await redis.stop()
        for i in range(5):
            provider_a.document.get_text("t").insert(3, f"dark{i};")
            await asyncio.sleep(0.01)
        expected = "up;" + "".join(f"dark{i};" for i in reversed(range(5)))

        # LOCAL serving survived the outage: doc still plane-served at A
        # and same-instance receivers stay live
        local = new_provider(server_a, name="outage-doc")
        try:
            await wait_synced(local)
            await retryable_assertion(
                lambda: _assert(
                    local.document.get_text("t").to_string()
                    == provider_a.document.get_text("t").to_string()
                )
            )
        finally:
            local.destroy()
        assert "outage-doc" in ext_a._docs, "publish failure degraded the plane"

        # redis returns; subscribers reconnect; the next change's
        # exchange heals the outage-window edits
        redis.port = port
        await redis.start()
        await retryable_assertion(
            lambda: _assert(
                len(redis.subscribers.get(b"hocuspocus:outage-doc", set())) >= 2
            )
        )
        provider_a.document.get_text("t").insert(0, "back;")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "back;" + expected
            )
        )
        # both planes are still serving this doc (no degradation)
        assert "outage-doc" in ext_a._docs and "outage-doc" in ext_b._docs
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_recycle_storm_concurrent_with_catchup_storm():
    """Row-recycling churn (append-only rows exhausted by insert+delete
    tombstones, docs recycling onto fresh rows) while a wave of cold
    joiners demands catch-up serves of the same docs. Every joiner must
    receive the full correct state — a recycle mid-serve must not hand
    out a half-rebuilt row — and every doc ends the storm either
    plane-served or counted."""
    # RLE arena: the production 100k-regime substrate, and the one where
    # a re-lowered snapshot is COMPACT (ContentDeleted runs cost one
    # entry each) so tombstone churn actually recycles instead of
    # re-exhausting the fresh row
    ext = TpuMergeExtension(
        num_docs=16,
        capacity=24,
        flush_interval_ms=1,
        serve=True,
        native_lane=False,
        arena="rle",
    )
    server = await new_hocuspocus(extensions=[ext])
    writers = []
    joiners = []
    try:
        n_docs = 4
        for d in range(n_docs):
            w = new_provider(server, name=f"storm-{d}")
            writers.append(w)
            await wait_synced(w)

        def exhausted() -> int:
            c = ext.plane.counters
            return c["docs_retired_overflow"] + c["docs_retired_capacity"]

        async def churn(d: int) -> None:
            # burst churn (insert + immediate delete leaves a tombstoned
            # run behind each cycle) until SOME doc exhausts its
            # 24-entry row; live snapshots stay tiny, which is exactly
            # the doc class recycling rescues
            text = writers[d].document.get_text("t")
            i = 0
            while exhausted() == 0 and i < 100:
                burst = f"d{d}burst{i};"
                base = len(text.to_string())
                text.insert(base, burst)
                text.delete(base, len(burst))
                i += 1
                await asyncio.sleep(0.02)

        async def join_wave(d: int, count: int) -> None:
            for _ in range(count):
                c = new_provider(server, name=f"storm-{d}")
                joiners.append((d, c))
                await asyncio.sleep(0.05)

        # the storm: burst-churn every doc while cold joiners arrive
        await asyncio.gather(
            *[churn(d) for d in range(n_docs)],
            *[join_wave(d, 4) for d in range(n_docs)],
        )
        assert exhausted() >= 1, ext.plane.counters

        # sparse nudges while the recycle queues behind warmup compiles
        # and piled flush cycles (tight churn would outgrow the fresh
        # row before the attempt takes the lock)
        for _ in range(60):
            if ext.plane.counters["docs_recycled"]:
                break
            for d in range(n_docs):
                writers[d].document.get_text("t").insert(0, "z")
            await asyncio.sleep(1.0)
        assert ext.plane.counters["docs_recycled"] >= 1, ext.plane.counters

        # every joiner converges to its writer's full state
        def all_converged():
            for d, c in joiners:
                want = writers[d].document.get_text("t").to_string()
                got = c.document.get_text("t").to_string()
                assert got == want, f"joiner of storm-{d} diverged"

        await retryable_assertion(all_converged)

        # accounting: each doc is live on the plane or counted as
        # retired/degraded — nothing vanished
        counters = ext.plane.counters
        retired = sum(
            counters[k]
            for k in counters
            if k.startswith("docs_retired_")
        )
        for d in range(n_docs):
            name = f"storm-{d}"
            if name not in ext._docs:
                assert retired > 0, f"{name} gone from the plane but never counted"

        # storm over: a fresh edit on every doc still propagates
        for d in range(n_docs):
            writers[d].document.get_text("t").insert(0, "after-storm;")
        await retryable_assertion(all_converged)
    finally:
        for _d, c in joiners:
            c.destroy()
        for w in writers:
            w.destroy()
        await server.destroy()


async def test_wedged_tpu_runtime_server_still_accepts_and_syncs():
    """THE round-5 verdict defect: a server configured with the TPU
    merge plane whose runtime is wedged (device discovery blocks
    forever — the state this machine's tunnel was in for two rounds)
    must still accept WebSocket connections and complete sync WITHIN
    the configured init deadline, serving on the CPU path. Previously
    plane construction blocked boot and the server served nothing."""
    import threading
    import time

    from hocuspocus_tpu.tpu import SupervisedTpuMergeExtension

    gate = threading.Event()

    def wedged_runtime_factory():
        gate.wait()  # simulated wedged TPU runtime: init never returns

    init_timeout = 2.0
    ext = SupervisedTpuMergeExtension(
        runtime_factory=wedged_runtime_factory,
        init_timeout=init_timeout,
        watchdog_interval=0.1,
    )
    started = time.monotonic()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="verdict-doc")
    b = new_provider(server, name="verdict-doc")
    try:
        # connection + full sync handshake, bounded by the init deadline
        await wait_synced(a, b, timeout=init_timeout)
        assert time.monotonic() - started < init_timeout, (
            "sync must complete within the init deadline, not behind it"
        )
        a.document.get_text("t").insert(0, "availability first")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "availability first"
            )
        )
        # the plane never came up; the supervisor says so
        await retryable_assertion(
            lambda: _assert(ext.supervisor.state == "degraded")
        )
        assert ext.health_status()["degraded"]
    finally:
        gate.set()
        a.destroy()
        b.destroy()
        await server.destroy()
