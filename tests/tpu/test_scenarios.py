"""Scenario traffic simulator + SLO-verdict harness (hocuspocus_tpu/loadgen).

Tier-1 coverage: schedule compilation is deterministic and replayable
byte-identically, a tiny smoke scenario runs end-to-end through real
servers with the verdict coming from the SLO engine's multi-window burn
rates, phase transitions land in the flight recorder's `__loadgen__`
ring and on the live `/debug/loadgen` timeline, and an impossible SLO
latches a `fail` verdict. The composed storm scenario is slow-marked.
"""

import asyncio
import json
import time

import aiohttp
import pytest

from hocuspocus_tpu.loadgen import (
    ScenarioRunner,
    Schedule,
    get_loadgen_timeline,
    get_scenario,
)
from hocuspocus_tpu.loadgen.scenario import PhaseSpec, Scenario
from hocuspocus_tpu.loadgen.scenarios import SCENARIOS, _edit_gen, storm
from hocuspocus_tpu.observability.flight_recorder import get_flight_recorder

from tests.utils import new_hocuspocus


# -- compilation / replay determinism -----------------------------------------


def test_schedule_compile_is_deterministic():
    """Same (scenario, seed) -> same schedule hash; different seed ->
    different hash — for every scenario in the library."""
    for name in SCENARIOS:
        first = get_scenario(name).compile(seed=7)
        second = get_scenario(name).compile(seed=7)
        other = get_scenario(name).compile(seed=8)
        assert first.schedule_hash == second.schedule_hash, name
        assert first.canonical_bytes() == second.canonical_bytes(), name
        assert first.schedule_hash != other.schedule_hash, name
        assert len(first.ops) > 0, name
        # ops are phase-tagged with the declared phase names, in time order
        declared = {phase["name"] for phase in first.phases}
        assert {op.phase for op in first.ops} <= declared, name
        times = [op.at_ms for op in first.ops]
        assert times == sorted(times), name


def test_schedule_ops_stay_phase_monotonic_at_boundaries():
    """Ops landing exactly on a phase boundary must not interleave with
    the next phase (the runner's phase walk requires monotonic order,
    and alphabetical phase names must not influence it)."""
    from hocuspocus_tpu.loadgen.scenario import OpEvent

    def boundary_gen(rng, scenario, phase):
        # deliberately emit at/past the boundary; compile must clamp
        return [
            OpEvent(phase.duration_ms, phase.name, "edit", doc=0, size=8),
            OpEvent(0, phase.name, "edit", doc=0, size=8),
        ]

    scenario = Scenario(
        name="boundary",
        num_docs=2,
        # 'zz_first' sorts AFTER 'aa_second' alphabetically: a
        # name-based tie-break would reorder the boundary ops
        phases=[
            PhaseSpec("zz_first", 100, boundary_gen),
            PhaseSpec("aa_second", 100, boundary_gen),
        ],
    )
    schedule = scenario.compile(seed=0)
    declared = ["zz_first", "aa_second"]
    seen = [op.phase for op in schedule.ops]
    # phase-monotonic: once aa_second starts, zz_first never reappears
    assert seen == sorted(seen, key=declared.index)
    # every op stays strictly inside its phase window
    for op in schedule.ops:
        if op.phase == "zz_first":
            assert 0 <= op.at_ms < 100
        else:
            assert 100 <= op.at_ms < 200


def test_schedule_records_and_replays_byte_identically():
    """to_json -> from_json round-trips to the exact same bytes (the
    recorded op-stream replays byte-identically), and any op change
    changes the hash."""
    schedule = get_scenario("flash_crowd").compile(seed=3)
    recorded = schedule.to_json()
    replayed = Schedule.from_json(recorded)
    assert replayed.canonical_bytes() == schedule.canonical_bytes()
    assert replayed.schedule_hash == schedule.schedule_hash
    assert [op.row() for op in replayed.ops] == [op.row() for op in schedule.ops]
    # tampering with the stream is visible in the hash
    tampered = json.loads(recorded)
    tampered["ops"][0][0] += 1
    assert (
        Schedule.from_json(json.dumps(tampered)).schedule_hash
        != schedule.schedule_hash
    )


# -- the smoke scenario through real servers ----------------------------------


async def test_smoke_scenario_slo_verdict_and_phase_ordering():
    """The tier-1 acceptance run: a tiny scenario through the real
    server path produces a deterministic-hash artifact whose verdict is
    the SLO engine's burn-rate breach status, with per-phase latency
    breakdowns, `__loadgen__` flight-recorder events and a live
    timeline."""
    recorder = get_flight_recorder()
    events_before = len(recorder.events("__loadgen__"))
    scenario = get_scenario("smoke")
    schedule = scenario.compile(seed=7)
    runner = ScenarioRunner(schedule, time_scale=4.0)
    result = await runner.run()

    # deterministic replay: the artifact's hash is reproducible from
    # (scenario, seed) alone
    assert result["schedule_hash"] == get_scenario("smoke").compile(7).schedule_hash
    assert result["seed"] == 7

    # the verdict IS the engine's latched multi-window breach status
    assert result["metric"] == "scenario_slo_verdict"
    assert result["verdict"] in ("pass", "fail")
    breached = result["slo"]["breached_targets"]
    assert result["verdict"] == ("fail" if breached else "pass")
    assert set(result["slo"]["windows"]) == {"burst", "run"}
    # two targets per phase (latency + op success), all known to the engine
    target_names = set(result["slo"]["targets"])
    for phase in ("warm", "burst", "cool"):
        assert f"{phase}:latency" in target_names
        assert f"{phase}:op_success" in target_names

    # per-phase breakdown, in declared order, with measured latencies
    assert [phase["name"] for phase in result["phases"]] == [
        "warm", "burst", "cool",
    ]
    for phase in result["phases"]:
        assert phase["measured_ops"] > 0
        assert phase["latency_p99_ms"] is not None
        assert set(phase["burn_rates"]) == {
            f"{phase['name']}:latency", f"{phase['name']}:op_success",
        }
    assert result["extra"]["ops_total"] == len(schedule.ops)
    assert result["extra"]["plane_health"][0]["cpu_fallbacks"] == 0

    # flight recorder: run/phase edges under the __loadgen__ ring
    events = recorder.events("__loadgen__")[events_before:]
    kinds = [event["event"] for event in events]
    assert kinds[0] == "run_start"
    assert kinds[-1] == "run_end"
    phase_starts = [
        event["phase"] for event in events if event["event"] == "phase_start"
    ]
    assert phase_starts == ["warm", "burst", "cool"]
    run_start = events[0]
    assert run_start["schedule_hash"] == result["schedule_hash"]

    # live timeline: the finished run is the status page's last_run
    status = get_loadgen_timeline().status()
    assert status["active"] is False
    assert status["last_run"]["verdict"] == result["verdict"]
    assert status["last_run"]["schedule_hash"] == result["schedule_hash"]
    assert [p["state"] for p in status["last_run"]["phases"]] == ["done"] * 3


async def test_impossible_slo_latches_fail_verdict():
    """A sub-millisecond latency objective is unmeetable through a real
    server: every measured op is a bad event, both burn-rate windows
    blow past the alert threshold, and the verdict latches `fail`."""
    scenario = Scenario(
        name="impossible",
        num_docs=4,
        sampled=4,
        shards=1,
        capacity=256,
        shard_rows=16,
        docs_per_socket=4,
        phases=[
            PhaseSpec(
                "overload",
                1500,
                _edit_gen(20.0),
                slo_e2e_ms=0.5,  # snaps to the 0.5ms bucket bound
                slo_objective=0.95,
            )
        ],
    )
    recorder = get_flight_recorder()
    events_before = len(recorder.events("__loadgen__"))
    result = await ScenarioRunner(scenario.compile(seed=1)).run()
    assert result["verdict"] == "fail"
    assert result["value"] == 0.0
    assert "overload:latency" in result["slo"]["breached_targets"]
    assert result["slo"]["targets"]["overload:latency"]["breached"] is True
    # the breach burned on both windows (multi-window rule, not a blip)
    burns = result["slo"]["max_burn_rates"]["overload:latency"]
    assert burns["burst"] >= result["slo"]["alert_burn_rate"]
    assert burns["run"] >= result["slo"]["alert_burn_rate"]
    events = recorder.events("__loadgen__")[events_before:]
    assert any(event["event"] == "slo_breach" for event in events)


async def test_debug_loadgen_endpoint_serves_timeline():
    """`GET /debug/loadgen` on any Metrics-bearing server serves the
    process-global scenario timeline."""
    from hocuspocus_tpu.observability import Metrics

    server = await new_hocuspocus(extensions=[Metrics()])
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/debug/loadgen") as response:
                assert response.status == 200
                payload = json.loads(await response.text())
        # timeline fields plus the consistent attributable /debug header
        assert {"active", "run", "last_run", "events"} <= set(payload)
        assert {"generated_utc", "role", "node_id"} <= set(payload)
        assert payload["active"] is False
    finally:
        await server.destroy()


async def test_mini_redis_publish_latency_injection():
    """The replication-lag scenario's fault: published frames arrive
    delayed by publish_latency_ms, in order."""
    from hocuspocus_tpu.net.mini_redis import MiniRedis
    from hocuspocus_tpu.net.resp import read_reply

    redis = await MiniRedis().start()
    try:
        sub_reader, sub_writer = await asyncio.open_connection(
            "127.0.0.1", redis.port
        )
        sub_writer.write(b"*2\r\n$9\r\nSUBSCRIBE\r\n$2\r\nch\r\n")
        await sub_writer.drain()
        assert (await read_reply(sub_reader))[0] == b"subscribe"

        pub_reader, pub_writer = await asyncio.open_connection(
            "127.0.0.1", redis.port
        )

        async def publish(payload: bytes) -> None:
            pub_writer.write(
                b"*3\r\n$7\r\nPUBLISH\r\n$2\r\nch\r\n$%d\r\n%s\r\n"
                % (len(payload), payload)
            )
            await pub_writer.drain()
            await read_reply(pub_reader)

        redis.publish_latency_ms = 80
        t0 = time.perf_counter()
        await publish(b"first")
        await publish(b"second")
        first = await read_reply(sub_reader)
        delay = time.perf_counter() - t0
        second = await read_reply(sub_reader)
        assert first[2] == b"first"
        assert second[2] == b"second"  # order preserved through the delay
        assert delay >= 0.06
        # lowering the injection mid-flight must NOT reorder: a frame
        # published at latency 0 floors to the pending deadline
        redis.publish_latency_ms = 80
        await publish(b"slow")
        redis.publish_latency_ms = 0
        await publish(b"fast")
        assert (await read_reply(sub_reader))[2] == b"slow"
        assert (await read_reply(sub_reader))[2] == b"fast"
        # once the floor drains, delivery is immediate again
        await asyncio.sleep(0.02)
        t0 = time.perf_counter()
        await publish(b"third")
        assert (await read_reply(sub_reader))[2] == b"third"
        assert time.perf_counter() - t0 < 0.06
        # delivered counter reflects actual enqueues (no double count):
        # exactly the five frames published above
        assert redis.counters["delivered"] == 5
        assert redis.counters["dropped_slow"] == 0
        sub_writer.close()
        pub_writer.close()
    finally:
        await redis.stop()


# -- the composed storm (slow) ------------------------------------------------


@pytest.mark.slow
async def test_storm_scenario_composed_mix():
    """Flash crowd + reconnect herd composed at a CI-scale population:
    joins, reconnects and edits all execute, every phase reports
    measured latencies, and the artifact carries the full SLO rollup."""
    scenario = storm(num_docs=32, joins=12, reconnects=8, phase_ms=1800)
    schedule = scenario.compile(seed=11)
    kinds = {op.kind for op in schedule.ops}
    assert {"edit", "join", "leave", "reconnect"} <= kinds
    result = await ScenarioRunner(schedule, time_scale=2.0).run()
    assert result["verdict"] in ("pass", "fail")
    assert [phase["name"] for phase in result["phases"]] == [
        "build_up", "landfall", "aftermath",
    ]
    for phase in result["phases"]:
        assert phase["measured_ops"] > 0
    # the landfall phase actually measured join/reconnect traffic
    landfall = result["phases"][1]
    assert landfall["measured_ops"] >= 12
    assert result["extra"]["ops_measured"] > 0
    for health in result["extra"]["plane_health"]:
        assert health["cpu_fallbacks"] == 0


# -- overload_storm / partition_heal (ISSUE 12) -------------------------------


async def test_overload_storm_scenario_sheds_and_recovers_hysteresis_clean():
    """The overload-control acceptance run: injected RED pressure lands
    with a join wave — the ladder rejects the joins (shed/reject
    counters nonzero) while interactive edit p99 holds (the verdict
    stays pass), and recovery walks back to GREEN one rung per hold
    window with zero flapping."""
    from hocuspocus_tpu.server.overload import get_overload_controller

    recorder = get_flight_recorder()
    overload_events_before = len(recorder.events("__overload__"))
    schedule = get_scenario("overload_storm", hold_s=0.05).compile(seed=7)
    runner = ScenarioRunner(schedule, time_scale=3.0)
    result = await runner.run()

    assert result["verdict"] == "pass", result["slo"]["breached_targets"]
    # load actually exceeded capacity: the joins were sacrificed
    storm = next(p for p in result["phases"] if p["name"] == "storm")
    assert storm["failed_ops"] > 0, "RED must have rejected the join wave"
    assert storm["latency_p99_ms"] is not None
    overload = result["extra"]["overload"]
    assert overload["shed"].get("connects_rejected", 0) > 0

    # hysteresis-clean recovery: strictly monotonic descent back to
    # GREEN — one escalation to red, then one rung down per hold
    # window, never a re-escalation or flap
    path = [(t["from_rung"], t["to_rung"]) for t in overload["transitions"]]
    assert path == [
        ("green", "red"),
        ("red", "brownout2"),
        ("brownout2", "brownout1"),
        ("brownout1", "green"),
    ], path
    # the same story in the flight recorder's __overload__ ring
    ring = [
        (event["from_rung"], event["to_rung"])
        for event in recorder.events("__overload__")[overload_events_before:]
        if event["event"] == "rung_change"
    ]
    assert ring == path
    # teardown left the process-global controller cold for the next run
    controller = get_overload_controller()
    assert not controller.enabled
    assert controller.rung == 0


async def test_partition_heal_scenario_converges_byte_identically():
    """The chaos acceptance run: a one-way mini_redis partition drops
    instance A's publishes (every drop accounted), edits keep flowing,
    and after the heal the anti-entropy exchange reconverges both
    instances byte-identically — the runner latches the verdict on
    convergence, so pass IS the zero-silent-loss proof."""
    schedule = get_scenario("partition_heal").compile(seed=7)
    runner = ScenarioRunner(schedule, time_scale=3.0)
    result = await runner.run()

    assert result["verdict"] == "pass", result["slo"]["breached_targets"]
    convergence = result["extra"]["convergence"]
    assert convergence["converged"] is True
    assert convergence["diverged"] == []
    assert convergence["docs_checked"] == schedule.population["sampled"]
    # the partition was real AND accounted: publishes were blackholed
    assert result["extra"]["mini_redis"]["dropped_partition"] > 0
    # the healed phase measured real edits (their latency includes the
    # anti-entropy heal) and none failed
    healed = next(p for p in result["phases"] if p["name"] == "healed")
    assert healed["measured_ops"] > 0
    assert healed["failed_ops"] == 0
    # the partitioned phase deliberately measured nothing (its
    # observation channel was dead by design)
    partitioned = next(
        p for p in result["phases"] if p["name"] == "partitioned"
    )
    assert partitioned["measured_ops"] == 0
