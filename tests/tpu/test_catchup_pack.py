"""On-device catch-up encode (ISSUE 17): the packed tombstone readback
must be invisible on the wire.

Acceptance: SyncStep2 payloads served with the device pack enabled are
BYTE-IDENTICAL to the host full-row gather across random cutoff SVs,
flush epochs, pack-width overflow fallbacks, and post-compaction row
remaps — on both arenas. And the run-merge fast path (tentpole part 1)
is byte-invisible too: a plane with run-merge on serves the same bytes
as one with it off over mixed sequential/concurrent traffic.
"""

import random

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.residency import ResidencyManager
from hocuspocus_tpu.tpu.serving import PlaneServing

WORDS = ["alpha ", "bete ", "gamma ", "dd", "e", "zeta-zeta "]


def _grow_history(plane, name, rng, rounds=6):
    """Two replicas edit (sometimes concurrently), deltas flow to the
    plane, flushes interleave. Returns (converged ref doc, cutoff SVs
    snapshotted at random epochs)."""
    a, b = Doc(), Doc()
    svs = [None]
    for r in range(rounds):
        deltas = []
        for doc in (a, b) if rng.random() < 0.4 else (a,):
            before = encode_state_vector(doc)
            t = doc.get_text("t")
            roll = rng.random()
            if roll < 0.55 or len(t) == 0:
                pos = rng.choice([len(t), rng.randrange(len(t) + 1)])
                t.insert(pos, rng.choice(WORDS))
            elif roll < 0.85:
                start = rng.randrange(len(t))
                t.delete(start, min(rng.randint(1, 4), len(t) - start))
            else:
                start = rng.randrange(len(t))
                t.format(start, min(2, len(t) - start), {"bold": True})
            deltas.append(encode_state_as_update(doc, before))
        # converge the replicas, then ship the same deltas to the plane
        ua, ub = encode_state_as_update(a), encode_state_as_update(b)
        apply_update(a, ub)
        apply_update(b, ua)
        for delta in deltas:
            plane.enqueue_update(name, delta)
        if rng.random() < 0.5:
            plane.flush()
        svs.append(encode_state_vector(a))
    plane.flush()
    return a, svs


def _rebuilt_text(payload):
    doc = Doc()
    apply_update(doc, payload)
    return doc.get_text("t").to_string()


def _assert_device_matches_host(arena, seed):
    plane = MergePlane(num_docs=8, capacity=512, arena=arena)
    dev = PlaneServing(plane)
    host = PlaneServing(plane)
    host.device_pack_enabled = False
    plane.register("doc")
    rng = random.Random(seed)
    ref, svs = _grow_history(plane, "doc", rng)
    for sv in svs:
        p_dev = dev.encode_state_as_update("doc", ref, sv)
        p_host = host.encode_state_as_update("doc", ref, sv)
        assert p_dev is not None and p_host is not None, "plane must serve"
        assert p_dev == p_host, f"device/host bytes diverge (arena={arena})"
    assert _rebuilt_text(dev.encode_state_as_update("doc", ref, None)) == (
        ref.get_text("t").to_string()
    )
    assert plane.counters["sync_encode_device"] > 0
    assert plane.counters["sync_encode_host"] > 0  # the pack-off serving


def test_device_encode_matches_host_bytes_unit_arena():
    for seed in range(3):
        _assert_device_matches_host("unit", seed)


def test_device_encode_matches_host_bytes_rle_arena():
    for seed in range(3):
        _assert_device_matches_host("rle", 100 + seed)


def test_pack_width_overflow_falls_back_to_host_rows():
    """A row with more tombstones than the pack width reports its true
    count; the serve transparently re-reads it via the full-row gather
    and the bytes stay identical."""
    plane = MergePlane(num_docs=4, capacity=512)
    dev = PlaneServing(plane)
    host = PlaneServing(plane)
    host.device_pack_enabled = False
    assert dev._pack_width() == 128
    ref = Doc()
    t = ref.get_text("t")
    plane.register("tomby")
    before = encode_state_vector(ref)
    t.insert(0, "x" * 300)
    plane.enqueue_update("tomby", encode_state_as_update(ref, before))
    before = encode_state_vector(ref)
    t.delete(0, 200)  # 200 dead units > pack width 128
    plane.enqueue_update("tomby", encode_state_as_update(ref, before))
    plane.flush()
    device_before = plane.counters["sync_encode_device"]
    host_before = plane.counters["sync_encode_host"]
    p_dev = dev.encode_state_as_update("tomby", ref, None)
    assert p_dev is not None
    # pack dispatched, overflowed, and the host path finished the row
    assert plane.counters["sync_encode_device"] == device_before
    assert plane.counters["sync_encode_host"] > host_before
    p_host = host.encode_state_as_update("tomby", ref, None)
    assert p_dev == p_host
    assert _rebuilt_text(p_dev) == ref.get_text("t").to_string()


async def test_device_encode_after_compaction_remap():
    """Compaction rewrites rows in place (fresh slot generations, a
    remapped arena layout): the packed read must track the remap and
    keep serving host-identical bytes."""
    plane = MergePlane(num_docs=4, capacity=64)
    dev = PlaneServing(plane)
    host = PlaneServing(plane)
    host.device_pack_enabled = False
    mgr = ResidencyManager(plane=plane, serving=dev, compact_threshold=0.75)
    ref = Doc()
    t = ref.get_text("t")
    plane.register("churny")
    plane.enqueue_update("churny", encode_state_as_update(ref), presync=True)
    for _ in range(12):
        before = encode_state_vector(ref)
        t.insert(len(t), "abcdef")
        t.delete(0, 5)
        plane.enqueue_update("churny", encode_state_as_update(ref, before))
        if plane.docs["churny"].retired:
            break
    assert plane.docs["churny"].retired
    async with plane.flush_lock:
        assert await mgr.compact_doc_locked("churny")
    # live-tail replay brings the plane current
    plane.enqueue_update("churny", encode_state_as_update(ref), presync=True)
    plane.flush()
    for sv in (None, encode_state_vector(ref)):
        p_dev = dev.encode_state_as_update("churny", ref, sv)
        p_host = host.encode_state_as_update("churny", ref, sv)
        assert p_dev is not None and p_dev == p_host
    assert _rebuilt_text(dev.encode_state_as_update("churny", ref, None)) == (
        t.to_string()
    )


def _assert_run_merge_invisible(arena, seed):
    """Same traffic into a run-merge-on and a run-merge-off plane:
    identical text and identical served SyncStep2 bytes."""
    on = MergePlane(num_docs=8, capacity=512, arena=arena)
    off = MergePlane(num_docs=8, capacity=512, arena=arena)
    off.run_merge_enabled = False
    s_on, s_off = PlaneServing(on), PlaneServing(off)
    for plane in (on, off):
        plane.register("doc")
    rng = random.Random(seed)
    a, b = Doc(), Doc()
    svs = [None]
    for r in range(8):
        deltas = []
        concurrent = rng.random() < 0.35
        for doc in (a, b) if concurrent else (a,):
            before = encode_state_vector(doc)
            t = doc.get_text("t")
            if rng.random() < 0.7 or len(t) == 0:
                # mostly appends: the fast-path classifier's home turf
                t.insert(len(t), rng.choice(WORDS))
            else:
                pos = rng.randrange(len(t) + 1)
                t.insert(pos, rng.choice(WORDS))
            deltas.append(encode_state_as_update(doc, before))
        ua, ub = encode_state_as_update(a), encode_state_as_update(b)
        apply_update(a, ub)
        apply_update(b, ua)
        for delta in deltas:
            on.enqueue_update("doc", delta)
            off.enqueue_update("doc", delta)
        if rng.random() < 0.5:
            on.flush()
            off.flush()
        svs.append(encode_state_vector(a))
    on.flush()
    off.flush()
    assert off.counters["flush_fast_ops"] == 0
    assert on.text("doc") == off.text("doc") == a.get_text("t").to_string()
    for sv in svs:
        p_on = s_on.encode_state_as_update("doc", a, sv)
        p_off = s_off.encode_state_as_update("doc", a, sv)
        assert p_on is not None and p_on == p_off, (
            f"run-merge changed served bytes (arena={arena})"
        )
    return on.counters["flush_fast_ops"]


def test_run_merge_on_off_byte_identical_unit_arena():
    # byte-identity must hold for EVERY seed; whether a given seed's
    # traffic happens to form fast-eligible columns is seed luck, so
    # fast-path engagement is asserted in aggregate
    fast = sum(_assert_run_merge_invisible("unit", 7 + s) for s in range(3))
    assert fast > 0, "fast path never engaged across seeds"


def test_run_merge_on_off_byte_identical_rle_arena():
    fast = sum(_assert_run_merge_invisible("rle", 70 + s) for s in range(3))
    assert fast > 0, "fast path never engaged across seeds"
