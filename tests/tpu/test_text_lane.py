"""Native text lane ≡ Python host path (differential suite).

The lane (native/text_lane.cpp) re-implements the DocLowerer subset
for plain-text docs plus the serve-log/window machinery in C++. These
tests pin byte-identity of broadcast windows, dispatch-stream equality
into the device batch, sync-serve equality, out-of-order (pending)
buffering, and the demote path for rich content — the same random
streams driven through a lane plane and a Python plane side by side.
"""

import numpy as np
import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    diff_update,
    encode_state_as_update,
    encode_state_vector,
)
from hocuspocus_tpu.native import get_codec
from hocuspocus_tpu.tpu.merge_plane import MergePlane
from hocuspocus_tpu.tpu.serving import PlaneServing

pytestmark = pytest.mark.skipif(
    # gate on the NEWEST lane symbol, mirroring enable_lane: a stale
    # prebuilt codec must skip this suite, not fail its assertions
    get_codec() is None or not hasattr(get_codec(), "lane_window_sm"),
    reason="native text lane unavailable",
)


def _planes(num_docs=8, capacity=4096):
    lane_plane = MergePlane(num_docs=num_docs, capacity=capacity)
    assert lane_plane.enable_lane()
    py_plane = MergePlane(num_docs=num_docs, capacity=capacity)
    return lane_plane, PlaneServing(lane_plane), py_plane, PlaneServing(py_plane)


@pytest.mark.parametrize("seed", [2, 9, 31])
def test_lane_windows_and_serves_match_python_fuzz(seed):
    rng = np.random.default_rng(seed)
    lane_plane, lane_serving, py_plane, py_serving = _planes()
    assert lane_plane.register_lane("d") is not None
    py_plane.register("d")

    src = Doc()
    src.client_id = 7
    text = src.get_text("body")
    updates = []
    src.on("update", lambda u, *r: updates.append(u))

    for round_no in range(12):
        for _ in range(int(rng.integers(1, 5))):
            r = rng.random()
            n = len(text)
            if r < 0.6 or n < 4:
                pos = int(rng.integers(0, n + 1))
                text.insert(pos, f"r{round_no}x{'y' * int(rng.integers(1, 9))}")
            elif r < 0.85:
                pos = int(rng.integers(0, n - 2))
                text.delete(pos, int(rng.integers(1, min(3, n - pos) + 1)))
            else:
                pos = int(rng.integers(0, n + 1))
                text.insert(pos, "emoji\U0001f600")
        while updates:
            u = updates.pop(0)
            assert lane_plane.enqueue_update("d", u) > 0
            assert py_plane.enqueue_update("d", u) > 0
        # broadcast windows must be byte-identical
        lw = lane_serving.build_broadcast_pair("d")
        pw = py_serving.build_broadcast_pair("d")
        assert (lw is None) == (pw is None)
        if lw is not None:
            assert lw[0] == pw[0], round_no
            assert lw[1] == pw[1], round_no
        # integrate as we go (the real pipeline interleaves flushes)
        lane_plane.flush()
        py_plane.flush()

    # flush through the real kernels and serve cold + stale
    lane_plane.flush()
    py_plane.flush()
    lane_serving.refresh()
    py_serving.refresh()
    assert lane_plane.text("d") == text.to_string() == py_plane.text("d")

    cold_l = lane_serving.encode_state_as_update("d", src, None)
    cold_p = py_serving.encode_state_as_update("d", src, None)
    assert cold_l is not None and cold_l == cold_p
    probe = Doc()
    apply_update(probe, cold_l)
    assert probe.get_text("body").to_string() == text.to_string()

    mid_sv = encode_state_vector(src)
    text.insert(0, "tail ")
    while updates:
        u = updates.pop(0)
        lane_plane.enqueue_update("d", u)
        py_plane.enqueue_update("d", u)
    lane_plane.flush()
    py_plane.flush()
    lane_serving.refresh()
    py_serving.refresh()
    stale_l = lane_serving.encode_state_as_update("d", src, mid_sv)
    stale_p = py_serving.encode_state_as_update("d", src, mid_sv)
    assert stale_l is not None and stale_l == stale_p


def test_lane_drain_feeds_identical_device_batches():
    """lane_drain's columnar scatter must hand the kernel the same op
    stream, slot column for slot column, as the Python queue loop."""
    lane_plane, _, py_plane, _ = _planes()
    lane_plane.register_lane("d")
    py_plane.register("d")
    src = Doc()
    src.client_id = 7
    text = src.get_text("t")
    text.insert(0, "hello world")
    text.insert(5, " BIG")
    text.delete(0, 3)
    text.insert(0, "emoji\U0001f600")
    u = encode_state_as_update(src)
    lane_plane.enqueue_update("d", u)
    py_plane.enqueue_update("d", u)
    lane_ops, lane_built = lane_plane._build_batch(64)
    py_ops, py_built = py_plane._build_batch(64)
    assert lane_built == py_built > 0
    ls = lane_plane.docs["d"].lane_slot
    ps = py_plane.docs["d"].seqs[("root", "t")]
    for name in ("kind", "client", "clock", "run_len", "left_client",
                 "left_clock", "right_client", "right_clock"):
        la = np.asarray(getattr(lane_ops, name))
        pa = np.asarray(getattr(py_ops, name))
        np.testing.assert_array_equal(la[:, ls], pa[:, ps], err_msg=name)
    assert lane_plane.dispatched_units[ls] == py_plane.dispatched_units[ps]


def test_lane_buffers_out_of_order_updates():
    """A delta that arrives before its causal predecessor waits in the
    lane's pending set and applies once the gap closes — mirroring the
    Python lowerer (reconnecting offline editors)."""
    lane_plane, lane_serving, py_plane, py_serving = _planes()
    lane_plane.register_lane("d")
    py_plane.register("d")

    src = Doc()
    src.client_id = 3
    text = src.get_text("t")
    text.insert(0, "base ")
    u1 = encode_state_as_update(src)
    sv1 = encode_state_vector(src)
    text.insert(5, "middle ")
    u2 = diff_update(encode_state_as_update(src), sv1)
    sv2 = encode_state_vector(src)
    text.insert(0, "front ")
    u3 = diff_update(encode_state_as_update(src), sv2)

    for plane in (lane_plane, py_plane):
        assert plane.enqueue_update("d", u1) > 0
        assert plane.enqueue_update("d", u3) == 0  # gap: buffered
        assert plane.enqueue_update("d", u2) > 0  # closes the gap; drains u3
        assert plane.is_supported("d")
    lw = lane_serving.build_broadcast_pair("d")
    pw = py_serving.build_broadcast_pair("d")
    assert lw is not None and lw[0] == pw[0]
    lane_plane.flush()
    lane_serving.refresh()
    assert lane_plane.text("d") == text.to_string()


def test_lane_demotes_on_rich_content_and_bans():
    lane_plane, lane_serving, _, _ = _planes()
    lane_plane.register_lane("d")
    src = Doc()
    src.get_text("t").insert(0, "plain")
    assert lane_plane.enqueue_update("d", encode_state_as_update(src)) > 0
    src.get_map("m").set("k", 1)
    assert lane_plane.enqueue_update("d", encode_state_as_update(src)) == 0
    doc = lane_plane.docs["d"]
    assert doc.retired and doc.retire_reason == "lane_demote"
    assert "d" in lane_plane._lane_banned
    assert lane_plane.counters["docs_retired_lane_demote"] == 1
    # re-onboard goes to the Python path
    lane_plane.release("d")
    assert lane_plane.register_lane("d") is None


def test_lane_remote_flags_split_cross_instance_windows():
    lane_plane, lane_serving, py_plane, py_serving = _planes()
    lane_plane.register_lane("d")
    py_plane.register("d")
    src = Doc()
    src.client_id = 5
    src.get_text("t").insert(0, "local one ")
    u_local = encode_state_as_update(src)
    sv = encode_state_vector(src)
    peer = Doc()
    peer.client_id = 6
    apply_update(peer, u_local)
    peer.get_text("t").insert(0, "REMOTE ")
    u_remote = diff_update(encode_state_as_update(peer), sv)

    for plane in (lane_plane, py_plane):
        plane.enqueue_update("d", u_local)
        plane.enqueue_update("d", u_remote, remote=True)
    lw_full, lw_cross = lane_serving.build_broadcast_pair("d")
    pw_full, pw_cross = py_serving.build_broadcast_pair("d")
    assert lw_full == pw_full
    assert lw_cross == pw_cross
    assert lw_cross != lw_full  # remote record excluded


def test_lane_native_sm_serves_match_python_cross_product():
    """The native stale/cold serve (lane_window_sm: cutoff trimming,
    offset origin-rewrite, surrogate widening in C) must be
    byte-identical to the Python _encode_from_sm path across the full
    per-client cutoff cross-product, surrogate pairs included."""
    lane_plane, lane_serving, py_plane, py_serving = _planes(capacity=4096)
    assert lane_plane.register_lane("d") is not None
    py_plane.register("d")
    a, b = Doc(), Doc()
    a.client_id, b.client_id = 7, 0x9000001
    ta = a.get_text("t")
    ta.insert(0, "base \U0001f600 text")
    u1 = encode_state_as_update(a)
    apply_update(b, u1)
    b.get_text("t").insert(3, "B\U0001f680B")
    u2 = encode_state_as_update(b)
    apply_update(a, u2)
    ta.insert(0, "more ")
    ta.delete(2, 4)
    u3 = encode_state_as_update(a)
    for plane in (lane_plane, py_plane):
        for u in (u1, u2, u3):
            plane.enqueue_update("d", u)
        plane.flush()
    lane_serving.refresh()
    py_serving.refresh()
    lane_doc, py_doc = lane_plane.docs["d"], py_plane.docs["d"]
    known = lane_serving._local_sv(lane_doc)
    assert known == dict(py_doc.lowerer.known)
    for cut_a in range(known.get(7, 0) + 1):
        for cut_b in range(0, known.get(0x9000001, 0) + 1, 2):
            sm = {7: cut_a, 0x9000001: cut_b}
            assert lane_serving._encode_from_sm(
                lane_doc, dict(sm)
            ) == py_serving._encode_from_sm(py_doc, dict(sm)), sm


@pytest.mark.parametrize("seed", [4, 19, 42])
def test_lane_concurrent_editors_differential(seed):
    """Two TEXT editors mutate independent replicas; updates cross-apply
    in randomized interleave — the lane's riskiest logic (pending
    buffering, overlap trims, route resolution under concurrency) must
    stay byte-identical to the Python plane on broadcast windows and
    cold/stale serves, round after round."""
    rng = np.random.default_rng(seed)
    a, b = Doc(), Doc()
    a.client_id, b.client_id = 7, 0x9000001  # unsigned tiebreak coverage
    out_a, out_b = [], []
    a.on("update", lambda update, *rest: out_a.append(update))
    b.on("update", lambda update, *rest: out_b.append(update))

    lane_plane, lane_serving, py_plane, py_serving = _planes(capacity=8192)
    assert lane_plane.register_lane("conc") is not None
    py_plane.register("conc")

    def edit(doc, tag):
        text = doc.get_text("t")
        n = len(text)
        r = rng.random()
        if r < 0.55 or n < 4:
            text.insert(int(rng.integers(0, n + 1)), f"{tag}x{'y' * int(rng.integers(1, 7))}")
        elif r < 0.8:
            pos = int(rng.integers(0, n - 2))
            text.delete(pos, int(rng.integers(1, min(4, n - pos) + 1)))
        else:
            text.insert(int(rng.integers(0, n + 1)), "\U0001f600")

    for round_no in range(12):
        for doc, tag in ((a, "a"), (b, "b")):
            for step in range(int(rng.integers(1, 5))):
                edit(doc, f"{tag}{round_no}")
        pending = out_a + out_b
        rng.shuffle(pending)
        for update in pending:
            # SAME interleave into both planes
            lane_plane.enqueue_update("conc", update)
            py_plane.enqueue_update("conc", update)
        for update in out_a:
            apply_update(b, update)
        for update in out_b:
            apply_update(a, update)
        out_a.clear()
        out_b.clear()
        assert a.get_text("t").to_string() == b.get_text("t").to_string()

        lw = lane_serving.build_broadcast_pair("conc")
        pw = py_serving.build_broadcast_pair("conc")
        assert (lw is None) == (pw is None), round_no
        if lw is not None:
            assert lw[0] == pw[0] and lw[1] == pw[1], (seed, round_no)
        lane_plane.flush()
        py_plane.flush()
        lane_serving.refresh()
        py_serving.refresh()
        assert lane_plane.is_supported("conc") and py_plane.is_supported("conc")
        cold_l = lane_serving.encode_state_as_update("conc", a, None)
        cold_p = py_serving.encode_state_as_update("conc", a, None)
        assert cold_l is not None and cold_l == cold_p, (seed, round_no)
        if round_no % 3 == 2:
            sv = encode_state_vector(b)
            edit(a, f"tail{round_no}")
            while out_a:
                u = out_a.pop(0)
                lane_plane.enqueue_update("conc", u)
                py_plane.enqueue_update("conc", u)
                apply_update(b, u)
            lane_plane.flush()
            py_plane.flush()
            lane_serving.refresh()
            py_serving.refresh()
            stale_l = lane_serving.encode_state_as_update("conc", a, sv)
            stale_p = py_serving.encode_state_as_update("conc", a, sv)
            assert stale_l is not None and stale_l == stale_p, (seed, round_no)
    # final content equality against the CPU replicas
    assert lane_plane.text("conc") == a.get_text("t").to_string()


def test_lane_gc_structs_match_python():
    """A wire GC struct (collected range) on a text doc: the lane must
    record it host-side (never queued to the device), advance known
    past the range, integrate subsequent structs that chain onto it,
    and serve windows byte-identical to the Python path."""
    from hocuspocus_tpu.crdt.encoding import Encoder

    lane_plane, lane_serving, py_plane, py_serving = _planes()
    assert lane_plane.register_lane("d") is not None
    py_plane.register("d")

    # [1 section][2 structs][client 42][clock 0]
    #   GC len 4, then ContentString "hi" with origin (42, 3)
    e = Encoder()
    e.write_var_uint(1)
    e.write_var_uint(2)
    e.write_var_uint(42)
    e.write_var_uint(0)
    e.write_uint8(0)  # GC ref
    e.write_var_uint(4)
    e.write_uint8(0x04 | 0x80)  # ContentString + origin
    e.write_var_uint(42)
    e.write_var_uint(3)
    e.write_var_string("hi")
    e.write_var_uint(0)  # empty delete set
    update = e.to_bytes()

    assert lane_plane.enqueue_update("d", update) > 0
    assert py_plane.enqueue_update("d", update) > 0
    assert lane_plane.is_supported("d") and py_plane.is_supported("d")
    # BOTH structs end up host-only GC records: the insert's origin
    # resolves into the collected range, so it too collapses to GC
    # (yjs Item.getMissing semantics) — nothing queues to the device
    assert lane_plane.pending_ops() == py_plane.pending_ops() == 0
    lw = lane_serving.build_broadcast_pair("d")
    pw = py_serving.build_broadcast_pair("d")
    assert lw is not None and lw[0] == pw[0]
    lane_plane.flush()
    py_plane.flush()
    lane_serving.refresh()
    py_serving.refresh()
    assert lane_serving._local_sv(lane_plane.docs["d"]) == {42: 6}
    # cold + stale serves agree (stale cutoff inside the GC range)
    for sm in ({42: 0}, {42: 2}, {42: 4}, {42: 5}):
        assert lane_serving._encode_from_sm(
            lane_plane.docs["d"], dict(sm)
        ) == py_serving._encode_from_sm(py_plane.docs["d"], dict(sm)), sm
