"""Rich content on the TPU merge plane (serve=True).

Round-2 verdict items 4/5: formats, embeds, tree documents (ProseMirror
XML) and map/array docs must STAY on the plane — lowered as sequence
rows + host-side map records — instead of retiring to the CPU path.
Reference parity: the reference serves every Y type through one hot
loop (`/root/reference/packages/server/src/MessageReceiver.ts:195-213`
readUpdate handles maps/arrays/rich text identically).

Every test here drives real ws providers against a serve-mode plane and
asserts (a) convergence, (b) zero unsupported retires, (c) the traffic
actually rode the plane (plane_broadcasts / sync_serves counters).
"""

from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


def _plane_ext():
    return TpuMergeExtension(num_docs=64, capacity=1024, flush_interval_ms=1, serve=True)


async def test_rich_text_formats_served_from_plane():
    """Bold/link formats are zero-width arena units (Yjs countable=False);
    the doc stays plane-served and deltas converge byte-faithfully."""
    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="rich")
    b = new_provider(server, name="rich")
    try:
        await wait_synced(a, b)
        text_a = a.document.get_text("t")
        text_a.insert(0, "hello world")
        text_a.format(0, 5, {"bold": True})
        text_a.insert(11, "!", {"link": "https://x.test"})

        def converged():
            assert b.document.get_text("t").to_delta() == text_a.to_delta()
            assert b.document.get_text("t").to_string() == "hello world!"

        await retryable_assertion(converged)
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert "rich" in ext._docs
        assert ext.plane.counters["plane_broadcasts"] >= 1
        # formats are zero-width for text extraction, as in Yjs
        assert ext.plane.text("rich") == "hello world!"

        # late joiner gets formats through the plane sync path
        serves = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="rich")
        await wait_synced(c)
        assert c.document.get_text("t").to_delta() == text_a.to_delta()
        assert ext.plane.counters["sync_serves"] > serves
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_embeds_served_from_plane():
    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="embeds")
    b = new_provider(server, name="embeds")
    try:
        await wait_synced(a, b)
        text_a = a.document.get_text("t")
        text_a.insert(0, "image: ")
        text_a.insert_embed(7, {"src": "pic.png"}, {"width": 100})

        def converged():
            assert b.document.get_text("t").to_delta() == text_a.to_delta()

        await retryable_assertion(converged)
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert "embeds" in ext._docs
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_prosemirror_tree_served_from_plane():
    """A transformer-built ProseMirror doc (XmlElement tree + attributes
    + marks) lives on the plane as one arena row per sequence."""
    from hocuspocus_tpu.crdt import apply_update, encode_state_as_update
    from hocuspocus_tpu.transformer import ProsemirrorTransformer

    pm_json = {
        "type": "doc",
        "content": [
            {
                "type": "heading",
                "attrs": {"level": 2},
                "content": [{"type": "text", "text": "Title"}],
            },
            {
                "type": "paragraph",
                "content": [
                    {"type": "text", "text": "plain "},
                    {"type": "text", "text": "bold", "marks": [{"type": "bold"}]},
                ],
            },
        ],
    }

    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="pm")
    b = new_provider(server, name="pm")
    try:
        await wait_synced(a, b)
        seed = ProsemirrorTransformer.to_ydoc(pm_json, "prosemirror")
        apply_update(a.document, encode_state_as_update(seed))

        def converged():
            result = ProsemirrorTransformer.from_ydoc(b.document, "prosemirror")
            assert result == pm_json

        await retryable_assertion(converged)
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert "pm" in ext._docs

        # the lane-demote rebuild lands asynchronously (it queues behind
        # the listen-time warm compiles for the flush lock), so poll for
        # the plane-side registration instead of asserting a fixed point
        # in the race. The tree consumed one arena row per sequence
        # (fragment + heading + paragraph child lists at minimum).
        def on_plane():
            assert len(ext.plane.docs["pm"].seqs) >= 3

        await retryable_assertion(on_plane)

        # live tree edit: type into the heading text node
        frag = a.document.get_xml_fragment("prosemirror")
        frag.get(0).get(0).insert(0, "The ")

        def edited():
            result = ProsemirrorTransformer.from_ydoc(b.document, "prosemirror")
            assert result["content"][0]["content"][0]["text"] == "The Title"

        await retryable_assertion(edited)
        assert ext.plane.counters["docs_retired_unsupported"] == 0

        # late joiner builds the whole tree from the plane sync path
        serves = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="pm")
        await wait_synced(c)
        result = ProsemirrorTransformer.from_ydoc(c.document, "prosemirror")
        assert result["content"][0]["content"][0]["text"] == "The Title"
        assert ext.plane.counters["sync_serves"] > serves
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_array_and_mixed_doc_served_from_plane():
    """BASELINE config-4 shape: mixed Y.Map/Y.Array docs stay on the
    plane — array runs are value sequences, map keys host-side LWW."""
    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="mixed")
    b = new_provider(server, name="mixed")
    try:
        await wait_synced(a, b)
        arr = a.document.get_array("list")
        arr.insert(0, [1, 2, 3])
        arr.push(["four", {"five": 5}])
        a.document.get_map("meta").set("rev", 7)
        b_arr = b.document.get_array("list")

        def converged():
            assert b_arr.to_json() == [1, 2, 3, "four", {"five": 5}]
            assert b.document.get_map("meta").get("rev") == 7

        await retryable_assertion(converged)

        # the lane-demote rebuild lands asynchronously (it queues behind
        # the listen-time warm compiles for the flush lock) — wait for
        # the plane-side registration before editing again, so the
        # second round provably flows through the plane
        def on_plane():
            doc = ext.plane.docs.get("mixed")
            assert doc is not None and not doc.retired

        await retryable_assertion(on_plane)

        # concurrent-ish edits from both sides keep flowing
        arr.delete(1, 2)  # -> [1, "four", {"five": 5}]
        b.document.get_map("meta").set("rev", 8)

        def second():
            assert b_arr.to_json() == [1, "four", {"five": 5}]
            assert a.document.get_map("meta").get("rev") == 8

        await retryable_assertion(second)
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert ext.plane.counters["cpu_fallbacks"] == 0
        assert "mixed" in ext._docs

        def plane_broadcasting():
            assert ext.plane.counters["plane_broadcasts"] >= 1

        await retryable_assertion(plane_broadcasting)

        serves = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="mixed")
        await wait_synced(c)
        assert c.document.get_array("list").to_json() == [1, "four", {"five": 5}]
        assert c.document.get_map("meta").get("rev") == 8
        assert ext.plane.counters["sync_serves"] > serves
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_nested_types_in_map_served_from_plane():
    """A Y.Text living under a Y.Map key (ContentType as a map value)."""
    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="nested")
    b = new_provider(server, name="nested")
    try:
        await wait_synced(a, b)
        from hocuspocus_tpu.crdt import YText

        a.document.get_map("fields").set("title", YText("draft"))

        def converged():
            field = b.document.get_map("fields").get("title")
            assert field is not None and field.to_string() == "draft"

        await retryable_assertion(converged)
        # edit the nested text through the map
        a.document.get_map("fields").get("title").insert(5, " v2")

        def edited():
            assert b.document.get_map("fields").get("title").to_string() == "draft v2"

        await retryable_assertion(edited)
        assert ext.plane.counters["docs_retired_unsupported"] == 0
        assert "nested" in ext._docs
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_reloaded_doc_with_gc_subtree_stays_on_plane():
    """A ProseMirror doc whose snapshot contains GC structs (a deleted
    paragraph's collected subtree) must load onto the plane and serve —
    previously any GC'd range retired the doc to the CPU path forever,
    so every long-lived rich doc degraded after its first reload."""
    from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
    from hocuspocus_tpu.transformer import ProsemirrorTransformer

    pm = {
        "type": "doc",
        "content": [
            {"type": "paragraph", "content": [{"type": "text", "text": "keep me"}]},
            {"type": "paragraph", "content": [{"type": "text", "text": "delete me"}]},
        ],
    }
    # build the pre-loaded state: delete paragraph 2 so gc collects it
    source = Doc()
    apply_update(source, encode_state_as_update(ProsemirrorTransformer.to_ydoc(pm, "pm")))
    source.get_xml_fragment("pm").delete(1, 1)
    snapshot = encode_state_as_update(source)
    from hocuspocus_tpu.tpu.lowering import STRUCT_GC, _decode_update

    structs, _ = _decode_update(snapshot)
    assert any(s.kind == STRUCT_GC for s in structs), "precondition: snapshot has GC"

    # the doc loads from persistence (snapshot WITH gc) on first connect
    from hocuspocus_tpu.extensions import Database

    async def fetch(data):
        return snapshot if data.document_name == "gcdoc" else None

    ext = _plane_ext()
    server = await new_hocuspocus(extensions=[Database(fetch=fetch), ext])
    a = new_provider(server, name="gcdoc")
    b = new_provider(server, name="gcdoc")
    try:
        await wait_synced(a, b)
        expected = {
            "type": "doc",
            "content": [
                {"type": "paragraph", "content": [{"type": "text", "text": "keep me"}]}
            ],
        }
        assert ProsemirrorTransformer.from_ydoc(a.document, "pm") == expected
        assert ext.plane.counters["docs_retired_unsupported"] == 0, {
            k: v for k, v in ext.plane.counters.items() if v
        }
        assert "gcdoc" in ext._docs  # plane-served despite the GC range

        # live edits keep flowing through the plane
        a.document.get_xml_fragment("pm").get(0).get(0).insert(0, "still ")

        def edited():
            result = ProsemirrorTransformer.from_ydoc(b.document, "pm")
            assert result["content"][0]["content"][0]["text"] == "still keep me"

        await retryable_assertion(edited)
        assert ext.plane.counters["docs_retired_unsupported"] == 0

        # late joiner rebuilds from the plane, GC range included
        serves = ext.plane.counters["sync_serves"]
        c = new_provider(server, name="gcdoc")
        await wait_synced(c)
        result = ProsemirrorTransformer.from_ydoc(c.document, "pm")
        assert result["content"][0]["content"][0]["text"] == "still keep me"
        assert ext.plane.counters["sync_serves"] > serves
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
