"""Elastic-fleet decision core (fleet/controller.py) + admission policy
(fleet/roster.py): the scale-up/scale-down decision table, hysteresis
no-flap under an oscillating digest, brownout park/unpark, the
projection guard, the clock-gated cross-host AdmissionGate and the
cell-side PeerRoster epoch. All controller tests drive `observe` /
`tick_once` with injected digest-shaped stats — no wall-clock waits."""

import pytest

from hocuspocus_tpu.fleet import (
    AdmissionGate,
    FleetController,
    FleetControllerExtension,
    PeerRoster,
    cell_host,
    qualify_cell_id,
)
from hocuspocus_tpu.server.overload import get_overload_controller


@pytest.fixture(autouse=True)
def _reset_overload():
    controller = get_overload_controller()
    controller.reset()
    yield
    controller.reset()


def _cell(i, healthy=True, work=0.0, lane=0.0, occ=0.0):
    """One digest-shaped per-cell stats entry (the tpu/cells.py
    cell_stats fields the controller reads, plus the sampled rate)."""
    return {
        "cell": i,
        "healthy": healthy,
        "work_rate": work,
        "lane_queue_depth": lane,
        "occupancy": occ,
    }


def _fleet(total, active, work=0.0):
    return [
        _cell(i, healthy=i < active, work=work if i < active else 0.0)
        for i in range(total)
    ]


def _controller(**kwargs):
    kwargs.setdefault("num_cells", 4)
    kwargs.setdefault("work_target", 100.0)
    kwargs.setdefault("lane_target", 10.0)
    kwargs.setdefault("occupancy_target", 0.8)
    return FleetController(**kwargs)


# -- signal --------------------------------------------------------------------


def test_cell_load_takes_the_hottest_signal_not_the_mean():
    ctl = _controller()
    # a saturated lane on an otherwise idle cell still counts as hot
    load = ctl.cell_load(_cell(0, work=50.0, lane=8.0, occ=0.2))
    assert load == pytest.approx(0.8)  # lane 8/10, not work 0.5 or occ 0.25
    assert ctl.cell_load(_cell(0)) == 0.0


# -- decision table --------------------------------------------------------------


def test_scale_up_needs_hold_ticks_then_targets_the_first_spare():
    ctl = _controller(hold_ticks=2, cooldown_ticks=2)
    hot = _fleet(4, active=2, work=90.0)  # signal 0.9 >= 0.75
    assert ctl.observe(hot)["reason"] == "up_streak_building"
    decision = ctl.observe(hot)
    assert decision["action"] == "scale_up"
    assert decision["cell"] == 2  # min-index spare
    assert ctl.counters["scale_ups"] == 1
    # the action bought a cooldown: the same hot signal now holds
    for _ in range(2):
        assert ctl.observe(hot)["reason"] == "cooldown"
    # cooldown spent: the streak must REBUILD from zero
    assert ctl.observe(hot)["reason"] == "up_streak_building"


def test_scale_up_holds_without_spare_capacity():
    ctl = _controller(hold_ticks=1, cooldown_ticks=0)
    hot = _fleet(4, active=4, work=90.0)
    assert ctl.observe(hot)["reason"] == "no_spare_capacity"
    assert ctl.counters["scale_ups"] == 0


def test_scale_down_targets_the_coldest_cell():
    ctl = _controller(hold_ticks=2, cooldown_ticks=0)
    cold = [
        _cell(0, work=30.0),
        _cell(1, work=10.0),
        _cell(2, work=20.0),
        _cell(3, healthy=False),
    ]  # signal 0.2 <= 0.35
    assert ctl.observe(cold)["reason"] == "down_streak_building"
    decision = ctl.observe(cold)
    assert decision["action"] == "scale_down"
    assert decision["cell"] == 1  # the coldest, not the lowest index
    assert ctl.counters["scale_downs"] == 1


def test_scale_down_projection_guard_keeps_survivors_in_band():
    # signal 0.3 is below the 0.35 threshold, but ONE fewer cell would
    # carry 0.3 * 2/1 = 0.6 > projected_max 0.55 — removing the cell
    # would land the fleet straight back in scale-up territory
    ctl = _controller(hold_ticks=1, cooldown_ticks=0)
    cells = [_cell(0, work=30.0), _cell(1, work=30.0), _cell(2, healthy=False)]
    assert ctl.observe(cells)["reason"] == "survivors_too_hot"
    assert ctl.counters["scale_downs"] == 0


def test_scale_down_respects_min_cells():
    ctl = _controller(hold_ticks=1, cooldown_ticks=0, min_cells=1)
    lone = _fleet(4, active=1, work=5.0)
    assert ctl.observe(lone)["reason"] == "at_min_cells"


def test_oscillating_signal_never_flaps():
    """The anti-flap acceptance: a digest oscillating across the
    thresholds every tick resets the streaks and never scales
    anything, exactly like the PR-12 brownout ladder's hold."""
    ctl = _controller(hold_ticks=3, cooldown_ticks=0)
    hot = _fleet(4, active=2, work=90.0)  # 0.9: above up
    cold = _fleet(4, active=2, work=10.0)  # 0.1: below down
    mid = _fleet(4, active=2, work=55.0)  # 0.55: in band
    for _ in range(10):
        assert ctl.observe(hot)["action"] == "hold"
        assert ctl.observe(cold)["action"] == "hold"
    for _ in range(10):
        assert ctl.observe(hot)["action"] == "hold"
        assert ctl.observe(mid)["action"] == "hold"
    assert ctl.counters["scale_ups"] == 0
    assert ctl.counters["scale_downs"] == 0
    assert not ctl.decisions  # the history keeps transitions only


def test_brownout_parks_scaling_and_unpark_rearms_cooldown():
    ctl = _controller(hold_ticks=1, cooldown_ticks=2)
    hot = _fleet(4, active=2, work=90.0)
    parked = ctl.observe(hot, scaling_allowed=False, park_reason="brownout:red")
    assert parked["action"] == "park"
    assert ctl.parked and ctl.park_reason == "brownout:red"
    assert ctl.counters["parks"] == 1
    assert len(ctl.decisions) == 1  # the transition tick only
    for _ in range(5):
        ctl.observe(hot, scaling_allowed=False, park_reason="brownout:red")
    assert ctl.counters["parks"] == 1
    assert len(ctl.decisions) == 1  # steady parked ticks aren't history
    # brownout over: unpark is recorded, then a FULL cooldown runs
    # before the first post-brownout action
    assert ctl.observe(hot)["reason"] == "cooldown"
    assert not ctl.parked
    assert ctl.counters["unparks"] == 1
    assert [d["action"] for d in ctl.decisions] == ["park", "unpark"]
    assert ctl.observe(hot)["reason"] == "cooldown"
    # cooldown spent; hold_ticks=1 means the next hot tick may act
    assert ctl.observe(hot)["action"] == "scale_up"
    assert ctl.counters["scale_ups"] == 1


# -- the extension's tick loop (injected digests, no plane) ---------------------


async def test_extension_tick_actuates_through_the_overrides():
    ups, downs = [], []

    async def scale_up(index):
        ups.append(index)

    async def scale_down(index):
        downs.append(index)

    ext = FleetControllerExtension(
        interval_s=0.01, scale_up=scale_up, scale_down=scale_down
    )
    ext.controller = _controller(hold_ticks=1, cooldown_ticks=0)
    decision = await ext.tick_once(cells=_fleet(4, active=2, work=90.0))
    assert decision["action"] == "scale_up"
    assert ups == [2]
    cold = _fleet(4, active=3, work=10.0)
    decision = await ext.tick_once(cells=cold)
    assert decision["action"] == "scale_down"
    assert downs == [0]
    assert ext.actuation == {
        "activations": 1,
        "parks": 1,
        "docs_migrated": 0,
        "failures": 0,
    }
    assert [entry["action"] for entry in ext.timeline] == [
        "scale_up",
        "scale_down",
    ]
    status = ext.status()
    assert status["enabled"] and status["counters"]["scale_ups"] == 1


async def test_extension_parks_while_the_ladder_is_at_brownout():
    ext = FleetControllerExtension(interval_s=0.01)
    ext.controller = _controller(hold_ticks=1, cooldown_ticks=0)
    overload = get_overload_controller()
    overload.enable()
    overload.inject_pressure(1)  # BROWNOUT-1
    hot = _fleet(4, active=2, work=90.0)
    decision = await ext.tick_once(cells=hot)
    assert decision["action"] == "park"
    assert decision["reason"] == "brownout:brownout1"
    # parking is accounted as shed deferrable work, like maintenance
    assert overload.shed_total.value(reason="autoscale_parked") >= 1
    overload.reset()  # ladder back to cold GREEN
    decision = await ext.tick_once(cells=hot)
    assert decision["action"] != "park"
    assert ext.controller.counters["unparks"] == 1


# -- cross-host admission policy -------------------------------------------------


class _FakeEstimator:
    def __init__(self, samples=0, rtt_s=None):
        self.samples = samples
        self.rtt_s = rtt_s


def test_cell_id_qualification_roundtrip():
    assert qualify_cell_id("host-b", "cell-0") == "host-b/cell-0"
    assert qualify_cell_id(None, "cell-0") == "cell-0"
    assert qualify_cell_id("host-b", "host-a/cell-0") == "host-a/cell-0"
    assert cell_host("host-b/cell-0") == "host-b"
    assert cell_host("cell-0") is None


def test_admission_gate_local_cells_admit_immediately():
    gate = AdmissionGate(local_host="host-a")
    assert gate.evaluate("cell-0") == (True, "local")  # bare legacy id
    assert gate.evaluate("host-a/cell-1") == (True, "local")
    gate.note_local(True)
    gate.note_local(False)  # heartbeat: no-op
    assert gate.counters["admitted_local"] == 1


def test_admission_gate_holds_foreign_cells_until_clock_resolves():
    gate = AdmissionGate(local_host="host-a", min_samples=2, max_rtt_s=0.5)
    cell = "host-b/cell-0"
    admit, reason = gate.evaluate(cell)
    assert (admit, reason) == (False, "clock_unresolved:0/2")
    admit, reason = gate.evaluate(cell, _FakeEstimator(samples=1, rtt_s=0.01))
    assert (admit, reason) == (False, "clock_unresolved:1/2")
    # resolution QUALITY gates admission, never offset magnitude: a
    # wide RTT means the estimate (and staleness math) is garbage
    admit, reason = gate.evaluate(cell, _FakeEstimator(samples=3, rtt_s=0.9))
    assert (admit, reason) == (False, "rtt_unbounded:0.900s")
    admit, reason = gate.evaluate(cell, _FakeEstimator(samples=3, rtt_s=None))
    assert (admit, reason) == (False, "rtt_unbounded:none")
    admit, reason = gate.evaluate(cell, _FakeEstimator(samples=2, rtt_s=0.01))
    assert (admit, reason) == (True, "clock_resolved")


def test_admission_gate_pending_lifecycle_and_expiry():
    gate = AdmissionGate(local_host="host-a")
    cell = "host-b/cell-0"
    assert gate.hold(cell, "clock_unresolved:0/2") is True
    assert gate.hold(cell, "clock_unresolved:1/2") is False  # heartbeat
    assert gate.counters["held_pending"] == 1
    assert gate.status()["pending"] == {cell: "clock_unresolved:1/2"}
    assert gate.admit(cell) is True  # foreign join completing
    assert gate.admit(cell) is False  # heartbeat after admission
    assert gate.counters["admitted_foreign"] == 1
    # expiry keys off the LAST announce, not pending age: a re-held
    # (still-announcing) cell survives, a silent one expires
    gate.hold(cell, "clock_unresolved:0/2")
    gate.pending[cell]["last_seen"] -= 10.0
    assert gate.expire(timeout_s=5.0) == [cell]
    assert not gate.pending
    assert gate.counters["pending_expired"] == 1
    gate.hold(cell, "clock_unresolved:0/2")
    assert gate.expire(timeout_s=5.0) == []


def test_peer_roster_epoch_counts_transitions_not_heartbeats():
    roster = PeerRoster()
    assert roster.note("cell-0", "healthy") is True
    assert roster.note("cell-0", "healthy") is False  # heartbeat no-op
    assert roster.note("host-b/cell-1", "healthy") is True
    assert roster.note("cell-0", "draining") is True
    assert roster.note("cell-0", "down") is True
    assert roster.note("cell-0", "down") is False  # unknown: no-op
    assert roster.epoch == 4
    assert roster.table() == {
        "epoch": 4,
        "peers": {"host-b/cell-1": "healthy"},
    }
