"""Warm-spare cell lifecycle on the multi-device plane (tpu/cells.py
park_cell/activate_cell — the autoscaler's actuation layer): a parked
cell migrates every doc over the evict-snapshot→hydrate rail with zero
acked-update loss before leaving placement, stays warm (no teardown),
and rejoins in one placement-epoch bump."""

import asyncio

import pytest

from hocuspocus_tpu.fleet import FleetControllerExtension
from hocuspocus_tpu.tpu.cells import MultiDeviceMergeExtension

from tests.utils import new_hocuspocus, new_provider, wait_for, wait_synced


@pytest.fixture(autouse=True)
def _fresh_lanes():
    from hocuspocus_tpu.tpu.scheduler import reset_device_lane

    reset_device_lane()
    yield
    reset_device_lane()


def _cells_ext(devices=4, **kwargs) -> MultiDeviceMergeExtension:
    kwargs.setdefault("num_docs", 16)
    kwargs.setdefault("capacity", 2048)
    kwargs.setdefault("flush_interval_ms", 1)
    kwargs.setdefault("rebalance_interval_s", 0)
    return MultiDeviceMergeExtension(devices=devices, **kwargs)


async def test_park_cell_drains_under_live_edits_and_activate_rejoins():
    """The zero-acked-loss scale-down regression vs the surviving
    reference client: park the doc's cell WHILE a writer edits — every
    acknowledged update survives the migration, no client disconnects,
    the parked cell leaves placement fully drained, and activation is
    one epoch bump with nothing to rebuild."""
    ext = _cells_ext(devices=4)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="park-doc")
    b = new_provider(server, name="park-doc")
    try:
        await wait_synced(a, b)
        a.document.get_text("t").insert(0, "acked-before-park;")
        await wait_for(
            lambda: "acked-before-park"
            in b.document.get_text("t").to_string()
        )
        src = ext.cell_index_for("park-doc")

        async def live_edits():
            for i in range(15):
                a.document.get_text("t").insert(0, f"e{i};")
                await asyncio.sleep(0.002)

        edit_task = asyncio.ensure_future(live_edits())
        # migrations can transiently decline (hydration ticket in
        # flight); the controller retries next tick — mirror that
        result = await ext.park_cell(src)
        for _ in range(50):
            if result["drained"]:
                break
            await asyncio.sleep(0.02)
            result = await ext.park_cell(src)
        await edit_task
        assert result["drained"], result
        assert src not in ext.placement.healthy
        assert "park-doc" not in ext.cells[src]._docs
        assert ext.migration_stats["cells_parked"] >= 1
        # the doc serves on from a survivor; everything acked survives
        a.document.get_text("t").insert(0, "post-park;")
        await wait_for(
            lambda: a.document.get_text("t").to_string()
            == b.document.get_text("t").to_string()
            and "post-park" in b.document.get_text("t").to_string(),
            timeout=10,
        )
        text = b.document.get_text("t").to_string()
        assert "acked-before-park" in text
        for i in range(15):
            assert f"e{i};" in text, f"acked update e{i} lost in park"
        assert a.synced and b.synced  # no client-visible disconnect
        # warm re-activation: one epoch bump, no rebuild
        epoch = ext.placement.epoch
        await ext.activate_cell(src)
        assert src in ext.placement.healthy
        assert ext.placement.epoch == epoch + 1
        assert ext.migration_stats["cells_activated"] == 1
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_controller_extension_boots_warm_spares_parked():
    """`--fleet-warm-spares N`: the last N cells boot BUILT (arena
    allocated, registry warm) but out of placement — the fleet starts
    at its trough footprint, and the controller sees them as the spare
    pool. The extension finds the co-installed plane by duck type and
    publishes its status through the FleetView autoscale seam."""
    from hocuspocus_tpu.observability.fleet import get_fleet_view

    plane_ext = _cells_ext(devices=4)
    fleet_ext = FleetControllerExtension(
        interval_s=60.0, warm_spares=2, min_cells=1
    )
    server = await new_hocuspocus(extensions=[plane_ext, fleet_ext])
    try:
        assert fleet_ext.plane is plane_ext
        assert fleet_ext.active_cells() == [0, 1]
        assert plane_ext.placement.healthy == {0, 1}
        status = fleet_ext.status()
        assert status["roster"] == {"active": [0, 1], "total": 4}
        assert status["bounds"] == {"min_cells": 1, "max_cells": 4}
        # the /debug/fleet autoscale section reads THIS status
        view_status = get_fleet_view().status()
        assert view_status["autoscale"]["roster"]["active"] == [0, 1]
        # digest-shaped samples carry the monotonic dispatch totals
        cells = fleet_ext.sample_cells()
        assert [c["cell"] for c in cells] == [0, 1, 2, 3]
        assert all("work_rate" in c and "dispatched_total" in c for c in cells)
        assert sum(c["healthy"] for c in cells) == 2
    finally:
        await server.destroy()
        get_fleet_view().reset()
