"""Cross-host cell admission end-to-end (fleet/roster.py + edge tier):
two in-process "hosts" share one MiniRedis relay bus. A cell announcing
with a foreign host qualifier is HELD pending until its clock offset
resolves over PING/PONG probes, joins through the normal epoch-bump
machinery, serves placement-routed docs, and hands its docs off with
zero acked-update loss when it scales back down (the PR-13 drain is the
cross-host scale-down actuation)."""

import asyncio

import pytest

from hocuspocus_tpu.crdt import encode_state_as_update
from hocuspocus_tpu.edge import (
    CellIngressExtension,
    EdgeGatewayExtension,
    EdgeServer,
)
from hocuspocus_tpu.fleet import AdmissionGate
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.observability.fleet import get_fleet_view
from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.provider.inprocess import InProcessProviderSocket
from hocuspocus_tpu.server import Configuration, Server
from hocuspocus_tpu.server.overload import get_overload_controller

from tests.utils import wait_for, wait_synced


@pytest.fixture(autouse=True)
def _reset_globals():
    get_overload_controller().reset()
    get_fleet_view().reset()
    yield
    get_overload_controller().reset()
    get_fleet_view().reset()


class TwoHostTopology:
    """One relay bus, cells tagged per 'host', edges on host-a."""

    def __init__(self) -> None:
        self.redis = None
        self.cells = []  # (Server, CellIngressExtension)
        self.edges = []  # (EdgeServer, EdgeGatewayExtension)
        self.sockets = []
        self.providers = []

    async def start_bus(self):
        self.redis = await MiniRedis().start()
        return self

    async def add_cell(self, cell_id, host_id):
        ext = CellIngressExtension(
            cell_id=cell_id,
            host_id=host_id,
            host="127.0.0.1",
            port=self.redis.port,
            announce_interval_s=0.2,
        )
        server = Server(Configuration(quiet=True, extensions=[ext]))
        await server.listen(port=0)
        self.cells.append((server, ext))
        return server, ext

    async def add_edge(self, edge_id, **kwargs):
        gx = EdgeGatewayExtension(
            edge_id=edge_id,
            host="127.0.0.1",
            port=self.redis.port,
            host_id="host-a",
            **kwargs,
        )
        server = EdgeServer(Configuration(quiet=True, extensions=[gx]))
        await server.listen(port=0)
        self.edges.append((server, gx))
        return server, gx

    def provider(self, edge_index, name):
        socket = InProcessProviderSocket(self.edges[edge_index][0])
        self.sockets.append(socket)
        provider = HocuspocusProvider(name=name, websocket_provider=socket)
        provider.attach()
        self.providers.append(provider)
        return provider

    def cell_owning(self, name):
        for server, ext in self.cells:
            if name in server.hocuspocus.documents:
                return server, ext
        return None, None

    async def close(self):
        for provider in self.providers:
            provider.destroy()
        for socket in self.sockets:
            socket.destroy()
        await asyncio.sleep(0)
        for server, _ in self.edges + self.cells:
            await server.destroy()
        if self.redis is not None:
            await self.redis.stop()


async def test_foreign_cell_pends_then_joins_epoch_safe_and_serves():
    """The admission acceptance: a second-host cell's first CELL_UP is
    deterministically HELD (no routable membership), the PING/PONG
    probe chain resolves its clock, and the join rides a router epoch
    bump — after which placement-routed docs are served by the foreign
    cell and converge across edges byte-identically."""
    topo = await TwoHostTopology().start_bus()
    try:
        await topo.add_cell("cell-0", "host-a")
        _, gx = await topo.add_edge("edge-0")
        gateway = gx.gateway
        await wait_for(
            lambda: gateway.router.healthy_cells() == ["host-a/cell-0"]
        )
        epoch_before = gateway.router.epoch
        foreign_server, foreign_ext = await topo.add_cell("cell-0", "host-b")
        assert foreign_ext.cell_id == "host-b/cell-0"
        # held first: the gate needs min_samples probe replies, and the
        # first CELL_UP is evaluated before any probe ever went out
        await wait_for(lambda: gateway.counters["admissions_pending"] >= 1)
        # ... then admitted once the offset estimator resolves
        await wait_for(
            lambda: "host-b/cell-0" in gateway.router.healthy_cells(),
            timeout=15,
        )
        assert gateway.counters["admissions_foreign"] == 1
        assert not gateway.admission.pending
        assert gateway.router.epoch > epoch_before  # the epoch-bump join
        estimator = get_fleet_view().offsets["host-b/cell-0"]
        assert estimator.samples >= gateway.admission.min_samples

        # the foreign cell is a first-class rendezvous target: find a
        # doc the router places THERE and drive it from two edges
        await topo.add_edge("edge-1")
        await wait_for(
            lambda: "host-b/cell-0"
            in topo.edges[1][1].gateway.router.healthy_cells(),
            timeout=15,
        )
        name = next(
            f"xh-{i}"
            for i in range(128)
            if gateway.router.route(f"xh-{i}") == "host-b/cell-0"
        )
        writer = topo.provider(0, name)
        reader = topo.provider(1, name)
        await wait_synced(writer, reader)
        assert name in foreign_server.hocuspocus.documents
        writer.document.get_text("body").insert(0, "from-host-a ")
        await wait_for(
            lambda: "from-host-a" in str(reader.document.get_text("body"))
        )
        await wait_for(
            lambda: encode_state_as_update(writer.document)
            == encode_state_as_update(reader.document)
        )
        # both cells watched the same control stream: equal roster epochs
        await wait_for(
            lambda: topo.cells[0][1].roster.table()
            == topo.cells[1][1].roster.table()
        )
    finally:
        await topo.close()


async def test_unresolved_clock_skew_keeps_the_cell_pending():
    """A peer whose probes never resolve (RTT above the bound — the
    unresolved-skew stand-in) stays announced-but-unroutable for as
    long as it keeps announcing; the local fleet serves on."""
    topo = await TwoHostTopology().start_bus()
    try:
        await topo.add_cell("cell-0", "host-a")
        _, gx = await topo.add_edge(
            "edge-0",
            admission=AdmissionGate(local_host="host-a", max_rtt_s=-1.0),
        )
        gateway = gx.gateway
        await wait_for(
            lambda: gateway.router.healthy_cells() == ["host-a/cell-0"]
        )
        await topo.add_cell("cell-0", "host-b")
        await wait_for(lambda: "host-b/cell-0" in gateway.admission.pending)
        # probes flow (liveness is fine) yet admission never completes
        await wait_for(
            lambda: getattr(
                get_fleet_view().offsets.get("host-b/cell-0"), "samples", 0
            )
            >= 2,
            timeout=15,
        )
        assert gateway.router.healthy_cells() == ["host-a/cell-0"]
        reason = gateway.admission.pending["host-b/cell-0"]["reason"]
        assert reason.startswith("rtt_unbounded")
        assert gateway.counters["admissions_foreign"] == 0
        # the held cell costs nothing: local docs still admit + serve
        provider = topo.provider(0, "local-doc")
        await wait_synced(provider)
        assert "local-doc" in topo.cells[0][0].hocuspocus.documents
    finally:
        await topo.close()


async def test_cross_host_scale_down_drain_loses_nothing_acked():
    """The scale-down acceptance against the surviving reference
    client: drain the FOREIGN cell mid-edit (the autoscaler's
    cross-host actuation is exactly the PR-13 drain handoff) — no
    client-visible disconnect, everything acknowledged survives, and
    the post-drain state converges byte-identically on the survivor."""
    topo = await TwoHostTopology().start_bus()
    try:
        await topo.add_cell("cell-0", "host-a")
        _, gx = await topo.add_edge("edge-0")
        gateway = gx.gateway
        await wait_for(
            lambda: gateway.router.healthy_cells() == ["host-a/cell-0"]
        )
        foreign_server, foreign_ext = await topo.add_cell("cell-0", "host-b")
        await topo.add_edge("edge-1")
        for _, edge_gx in topo.edges:
            await wait_for(
                lambda g=edge_gx.gateway: len(g.router.healthy_cells()) == 2,
                timeout=15,
            )
        name = next(
            f"sd-{i}"
            for i in range(128)
            if gateway.router.route(f"sd-{i}") == "host-b/cell-0"
        )
        writer = topo.provider(0, name)
        reader = topo.provider(1, name)
        await wait_synced(writer, reader)
        assert name in foreign_server.hocuspocus.documents
        writer.document.get_text("body").insert(0, "acked-before-scale-down ")
        await wait_for(
            lambda: "acked-before-scale-down"
            in str(reader.document.get_text("body"))
        )
        closes = []
        for provider in (writer, reader):
            provider.on("close", lambda *a, **k: closes.append("close"))
            provider.on(
                "authentication_failed", lambda *a, **k: closes.append("denied")
            )

        async def live_edits():
            for i in range(15):
                writer.document.get_text("body").insert(0, f"live{i};")
                await asyncio.sleep(0.01)

        edit_task = asyncio.ensure_future(live_edits())
        await foreign_server.drain(timeout_secs=5)
        await edit_task
        # both directions flow through the survivor after the handoff
        writer.document.get_text("body").insert(0, "post-scale-down-w ")
        await wait_for(
            lambda: "post-scale-down-w"
            in str(reader.document.get_text("body")),
            timeout=15,
        )
        reader.document.get_text("body").insert(0, "post-scale-down-r ")
        await wait_for(
            lambda: "post-scale-down-r"
            in str(writer.document.get_text("body")),
            timeout=15,
        )
        await wait_for(
            lambda: encode_state_as_update(writer.document)
            == encode_state_as_update(reader.document)
        )
        text = str(reader.document.get_text("body"))
        assert "acked-before-scale-down" in text
        for i in range(15):
            assert f"live{i};" in text, f"acked edit live{i} lost in drain"
        assert not closes, f"client-visible disconnect in scale-down: {closes}"
        survivor, _ = topo.cell_owning(name)
        assert survivor is not None and survivor is not foreign_server
        assert gateway.router.state_of("host-b/cell-0") == "draining"
    finally:
        await topo.close()
