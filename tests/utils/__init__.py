"""E2E test harness: in-process server + real websocket providers.

Mirrors the reference test strategy (`tests/utils/newHocuspocus.ts`):
every test boots a real server on an OS-assigned port and real provider
clients over real WebSockets, in one process.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional

from hocuspocus_tpu.provider import HocuspocusProvider, HocuspocusProviderWebsocket
from hocuspocus_tpu.server import Configuration, Server


async def new_hocuspocus(**options: Any) -> Server:
    options.setdefault("quiet", True)
    configuration = Configuration(**options)
    server = Server(configuration)
    await server.listen(port=0)
    return server


def new_provider_websocket(server: Server, **options: Any) -> HocuspocusProviderWebsocket:
    return HocuspocusProviderWebsocket(url=server.web_socket_url, **options)


def new_provider(server: Server, name: str = "hocuspocus-test", **options: Any) -> HocuspocusProvider:
    return HocuspocusProvider(name=name, url=server.web_socket_url, **options)


async def retryable_assertion(fn, timeout: float = 10.0, interval: float = 0.05) -> Any:
    """Poll until `fn` stops raising (eventual-consistency assertions —
    reference `tests/utils/retryableAssertion.ts`)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            result = fn()
            if asyncio.iscoroutine(result):
                result = await result
            return result
        except AssertionError:
            if time.monotonic() > deadline:
                raise
            await asyncio.sleep(interval)


async def wait_synced(*providers, timeout: float = 30.0) -> None:
    """Wait until every provider has completed its first sync handshake.

    Event-driven (delegates to `hocuspocus_tpu.aio.await_synced`): the
    timeout is purely a liveness bound — a loaded runner slows the
    wait, never breaks it."""
    from hocuspocus_tpu.aio import await_synced

    await await_synced(providers, timeout=timeout, what="wait_synced")


async def assert_on_update(observable, fn, event: str = "update", timeout: float = 30.0):
    """Event-driven eventual assertion: run `fn` now and again after every
    `event` emission on `observable` (e.g. a provider's Y.Doc), returning
    as soon as it stops raising AssertionError. Unlike interval polling,
    the deadline only bounds liveness — it can't race the event itself."""
    loop = asyncio.get_running_loop()
    wake = asyncio.Event()

    def handler(*args) -> None:
        loop.call_soon_threadsafe(wake.set)

    observable.on(event, handler)
    deadline = time.monotonic() + timeout
    try:
        while True:
            try:
                result = fn()
                if asyncio.iscoroutine(result):
                    result = await result
                return result
            except AssertionError:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                wake.clear()
                try:
                    await asyncio.wait_for(wake.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    pass  # final re-check, then raise from fn
    finally:
        observable.off(event, handler)


async def wait_for(predicate, timeout: float = 10.0, interval: float = 0.02) -> None:
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            raise TimeoutError("condition not met in time")
        await asyncio.sleep(interval)


class EventCollector:
    """Collects event payloads and lets tests await their arrival."""

    def __init__(self) -> None:
        self.events: list = []
        self._event = asyncio.Event()

    def __call__(self, *args: Any) -> None:
        self.events.append(args)
        self._event.set()

    async def wait(self, count: int = 1, timeout: float = 10.0) -> list:
        deadline = time.monotonic() + timeout
        while len(self.events) < count:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"expected {count} events, got {len(self.events)}"
                )
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), min(remaining, 0.5))
            except asyncio.TimeoutError:
                continue
        return self.events
