"""Everything composes: the full production extension stack in ONE
server — serve-mode TPU plane + incremental append-log persistence +
metrics + logger + webhook + throttle — driven by real providers.

Each extension is tested in isolation elsewhere; this pins their
interaction: hook priorities (Metrics 1000 > TpuMerge 900 > others),
the plane claiming broadcasts while Incremental stores deltas from the
same onChange boundary, webhook payload import on load, and unload
draining every layer. The reference composes extensions the same way
(`packages/cli/src/index.js` assembles Logger+SQLite+Webhook on one
server).
"""

import asyncio
import json

from aiohttp import web

from hocuspocus_tpu.extensions import Logger, Throttle, Webhook
from hocuspocus_tpu.extensions.incremental import IncrementalSQLite
from hocuspocus_tpu.observability import Metrics, MetricsRegistry
from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.tpu import TpuMergeExtension
from tests.utils import new_hocuspocus, new_provider, retryable_assertion


def _assert(cond):
    assert cond


async def test_full_stack_composition():
    # in-process webhook receiver
    events = []

    async def hook(request: web.Request) -> web.Response:
        events.append(json.loads(await request.text()))
        return web.Response(text="{}")

    app = web.Application()
    app.router.add_post("/hook", hook)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    hook_port = runner.addresses[0][1]

    registry = MetricsRegistry()
    ext_plane = TpuMergeExtension(
        num_docs=16, capacity=2048, flush_interval_ms=1, serve=True
    )
    incremental = IncrementalSQLite(compact_after=4)
    log_lines = []
    stack = [
        Metrics(registry=registry),
        ext_plane,
        incremental,
        Logger(log=log_lines.append),
        Webhook(
            url=f"http://127.0.0.1:{hook_port}/hook",
            secret="s3cr3t",
            debounce=10,
            events=["create", "change", "connect", "disconnect"],
        ),
        Throttle(throttle=100, considered_seconds=60),
    ]
    server = await new_hocuspocus(extensions=stack, debounce=30, max_debounce=60)
    a = new_provider(server, name="composed")
    b = new_provider(server, name="composed")
    try:
        await retryable_assertion(lambda: _assert(a.synced and b.synced))
        text = a.document.get_text("t")
        for i in range(6):
            text.insert(len(text.to_string()), f"part{i};")
        expected = "".join(f"part{i};" for i in range(6))
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == expected)
        )
        # the plane served the doc (broadcasts went through the merged path)
        assert "composed" in ext_plane._docs
        assert ext_plane.plane.counters["plane_broadcasts"] >= 1
        assert ext_plane.plane.counters["cpu_fallbacks"] == 0
        # incremental persisted deltas (and possibly compacted)
        await retryable_assertion(
            lambda: _assert(incremental.log_length("composed") >= 1)
        )
        # metrics saw the traffic; plane health gauges are exported
        sample = registry.expose()
        assert "hocuspocus_document_changes_total" in sample
        assert "hocuspocus_tpu_plane_broadcasts" in sample
        # webhook observed lifecycle events
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "change" for e in events))
        )
        # logger saw hook traffic
        assert any("New connection" in line or "changed" in line for line in log_lines)

        # reload path: destroy both, let the doc unload, rejoin and the
        # incremental log restores the content through the whole stack
        a.destroy()
        b.destroy()
        await retryable_assertion(lambda: _assert("composed" not in server.documents))
        c = new_provider(server, name="composed")
        try:
            await retryable_assertion(lambda: _assert(c.synced))
            assert c.document.get_text("t").to_string() == expected
        finally:
            c.destroy()
    finally:
        for p in (a, b):
            p.destroy()
        await server.destroy()
        await runner.cleanup()
