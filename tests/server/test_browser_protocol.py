"""The browser demo's protocol path, driven from Python byte-for-byte.

examples/browser/index.html speaks the wire protocol with a hand-rolled
client (lib0 frames, auth submessage, SyncStep1/2/Update, a per-unit
YATA text CRDT). No JS runtime exists in this image, so this test
translates that client 1:1 (same frame layout, same single-struct
update encoding, same ds-only deletes, same stored-origin full-state
reply to the server's SyncStep1) and drives it over a raw websocket —
pinning every protocol interaction the page performs against the real
server, alongside a standard provider.

Reference counterpart: the playground frontend's provider traffic
(`/root/reference/playground/frontend`) through
`packages/server/src/ClientConnection.ts:279-343` (auth queueing) and
`MessageReceiver.ts:137-213` (sync handshake).
"""

import asyncio
import random

import aiohttp

from hocuspocus_tpu.crdt.encoding import Decoder, Encoder
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

ROOT = "body"
MSG_SYNC, MSG_AUTH, MSG_SYNC_REPLY, MSG_SYNC_STATUS = 0, 2, 4, 8
STEP1, STEP2, UPDATE = 0, 1, 2


def _assert(cond):
    assert cond


class _Unit:
    __slots__ = ("c", "k", "ch", "deleted", "oc", "ok")

    def __init__(self, c, k, ch, oc, ok):
        self.c, self.k, self.ch = c, k, ch
        self.oc, self.ok = oc, ok
        self.deleted = False


class BrowserMirrorClient:
    """Python twin of the JS client in examples/browser/index.html."""

    def __init__(self, doc_name: str = "browser-demo") -> None:
        self.doc_name = doc_name
        self.client_id = random.getrandbits(28)
        self.clock = 0
        self.units: list[_Unit] = []
        self.known: dict[int, int] = {}
        self.pending: list = []
        self.pending_deletes: list = []  # (client, clock, len) awaiting targets
        self.synced = False
        self._session = None
        self._ws = None
        self._reader_task = None

    # -- crdt (mirrors integrateRun / applyDelete / drainPending) -----------

    def _idx(self, c, k):
        for i, u in enumerate(self.units):
            if u.c == c and u.k == k:
                return i
        return -1

    def _integrate(self, run) -> bool:
        c, k, text, length, oc, ok, rc, rk = run
        have = self.known.get(c, 0)
        if k + length <= have:
            return True
        if k > have:
            return False
        off = have - k
        left_idx = -1
        if oc is not None and off == 0:
            left_idx = self._idx(oc, ok)
            if left_idx < 0:
                return False
        elif off > 0:
            left_idx = self._idx(c, k + off - 1)
            if left_idx < 0:
                return False
        right_idx = len(self.units)
        if rc is not None:
            right_idx = self._idx(rc, rk)
            if right_idx < 0:
                return False
        dest = right_idx
        for i in range(left_idx + 1, right_idx):
            u = self.units[i]
            u_origin = -1 if u.oc is None else self._idx(u.oc, u.ok)
            skip = u_origin > left_idx or (u_origin == left_idx and u.c < c)
            if not skip:
                dest = i
                break
        inserted = []
        for j in range(off, length):
            inserted.append(
                _Unit(
                    c,
                    k + j,
                    0 if text is None else ord(text[j]),
                    oc if j == 0 else c,
                    ok if j == 0 else k + j - 1,
                )
            )
            if text is None:
                inserted[-1].deleted = True
        self.units[dest:dest] = inserted
        self.known[c] = k + length
        return True

    def _apply_delete(self, c, k, length):
        for u in self.units:
            if u.c == c and k <= u.k < k + length:
                u.deleted = True

    def _drain_pending(self):
        progress = True
        while progress:
            progress = False
            for run in list(self.pending):
                if self._integrate(run):
                    self.pending.remove(run)
                    progress = True
        # deletes are idempotent: re-apply until the range is known (a
        # delete may target structs that were pending when it arrived)
        for entry in list(self.pending_deletes):
            c, k, length = entry
            self._apply_delete(c, k, length)
            if self.known.get(c, 0) >= k + length:
                self.pending_deletes.remove(entry)

    def text(self) -> str:
        return "".join(chr(u.ch) for u in self.units if not u.deleted)

    # -- v1 codec (mirrors decodeUpdateAndApply / encodeRun / full state) ----

    def _apply_update(self, data: bytes):
        d = Decoder(data)
        for _ in range(d.read_var_uint()):
            num = d.read_var_uint()
            client = d.read_var_uint()
            clock = d.read_var_uint()
            for _ in range(num):
                info = d.read_uint8()
                ref = info & 0x1F
                if ref == 0:  # GC occupies its clock range
                    clock += d.read_var_uint()
                    if clock > self.known.get(client, 0):
                        self.known[client] = clock
                    continue
                if ref == 10:  # Skip: a hole, not content
                    clock += d.read_var_uint()
                    continue
                oc = ok = rc = rk = None
                if info & 0x80:
                    oc, ok = d.read_var_uint(), d.read_var_uint()
                if info & 0x40:
                    rc, rk = d.read_var_uint(), d.read_var_uint()
                if not (info & 0xC0):
                    if d.read_var_uint() == 1:
                        d.read_var_string()
                    else:
                        d.read_var_uint(), d.read_var_uint()
                    if info & 0x20:
                        d.read_var_string()
                if ref == 4:
                    text = d.read_var_string()
                    length = len(text)
                elif ref == 1:
                    text, length = None, d.read_var_uint()
                else:
                    raise AssertionError(f"unsupported ref {ref}")
                run = (client, clock, text, length, oc, ok, rc, rk)
                if not self._integrate(run):
                    self.pending.append(run)
                clock += length
        for _ in range(d.read_var_uint()):
            client = d.read_var_uint()
            for _ in range(d.read_var_uint()):
                k, length = d.read_var_uint(), d.read_var_uint()
                self._apply_delete(client, k, length)
                if self.known.get(client, 0) < k + length:
                    self.pending_deletes.append((client, k, length))
        self._drain_pending()

    @staticmethod
    def _encode_run(e: Encoder, run):
        c, k, text, _length, oc, ok, rc, rk = run
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(c)
        e.write_var_uint(k)
        info = 0x04 | (0x80 if oc is not None else 0) | (0x40 if rc is not None else 0)
        e.write_uint8(info)
        if oc is not None:
            e.write_var_uint(oc), e.write_var_uint(ok)
        if rc is not None:
            e.write_var_uint(rc), e.write_var_uint(rk)
        if oc is None and rc is None:
            e.write_var_uint(1)
            e.write_var_string(ROOT)
        e.write_var_string(text)

    def _encode_full_state(self, sv: dict) -> bytes:
        e = Encoder()
        by: dict[int, list] = {}
        for u in self.units:
            if u.k < sv.get(u.c, 0):
                continue
            by.setdefault(u.c, []).append(u)
        e.write_var_uint(len(by))
        for c in sorted(by, reverse=True):
            row = sorted(by[c], key=lambda u: u.k)
            e.write_var_uint(len(row))
            e.write_var_uint(c)
            e.write_var_uint(row[0].k)
            for u in row:
                info = 0x04 | (0x80 if u.oc is not None else 0)
                e.write_uint8(info)
                if u.oc is not None:
                    e.write_var_uint(u.oc), e.write_var_uint(u.ok)
                else:
                    e.write_var_uint(1)
                    e.write_var_string(ROOT)
                e.write_var_string(chr(u.ch))
        ds: dict[int, list] = {}
        for u in self.units:
            if u.deleted:
                ds.setdefault(u.c, []).append(u.k)
        e.write_var_uint(len(ds))
        for c in sorted(ds, reverse=True):
            ks = sorted(ds[c])
            ranges = []
            for k in ks:
                if ranges and ranges[-1][0] + ranges[-1][1] == k:
                    ranges[-1][1] += 1
                else:
                    ranges.append([k, 1])
            e.write_var_uint(c)
            e.write_var_uint(len(ranges))
            for k, l in ranges:
                e.write_var_uint(k), e.write_var_uint(l)
        return e.to_bytes()

    # -- frames + socket -----------------------------------------------------

    def _frame(self, msg_type: int, payload: bytes = b"") -> bytes:
        e = Encoder()
        e.write_var_string(self.doc_name)
        e.write_var_uint(msg_type)
        return e.to_bytes() + payload

    async def connect(self, url: str):
        self._session = aiohttp.ClientSession()
        self._ws = await self._session.ws_connect(url)
        auth = Encoder()
        auth.write_var_uint(0)
        auth.write_var_string("browser-demo")
        await self._ws.send_bytes(self._frame(MSG_AUTH, auth.to_bytes()))
        step1 = Encoder()
        step1.write_var_uint(STEP1)
        step1.write_var_uint8_array(b"\x00")  # empty state vector
        await self._ws.send_bytes(self._frame(MSG_SYNC, step1.to_bytes()))
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self):
        async for msg in self._ws:
            if msg.type != aiohttp.WSMsgType.BINARY:
                continue
            d = Decoder(msg.data)
            d.read_var_string()
            msg_type = d.read_var_uint()
            if msg_type in (MSG_SYNC, MSG_SYNC_REPLY):
                sub = d.read_var_uint()
                if sub == STEP1:
                    sv_reader = Decoder(d.read_var_uint8_array())
                    sv = {}
                    for _ in range(sv_reader.read_var_uint()):
                        client = sv_reader.read_var_uint()
                        sv[client] = sv_reader.read_var_uint()
                    reply = Encoder()
                    reply.write_var_uint(STEP2)
                    reply.write_var_uint8_array(self._encode_full_state(sv))
                    await self._ws.send_bytes(
                        self._frame(MSG_SYNC_REPLY, reply.to_bytes())
                    )
                elif sub in (STEP2, UPDATE):
                    self._apply_update(bytes(d.read_var_uint8_array()))
                    if sub == STEP2:
                        self.synced = True

    async def insert(self, pos: int, text: str):
        """Insert at VISIBLE position pos, like the page's splice diff."""
        visible = [u for u in self.units if not u.deleted]
        left = visible[pos - 1] if pos > 0 else None
        right = visible[pos] if pos < len(visible) else None
        run = (
            self.client_id,
            self.clock,
            text,
            len(text),
            left.c if left else None,
            left.k if left else 0,
            right.c if right else None,
            right.k if right else 0,
        )
        self.clock += len(text)
        assert self._integrate(run)
        e = Encoder()
        e.write_var_uint(UPDATE)
        body = Encoder()
        self._encode_run(body, run)
        body.write_var_uint(0)  # trailing (empty) delete set
        e.write_var_uint8_array(body.to_bytes())
        await self._ws.send_bytes(self._frame(MSG_SYNC, e.to_bytes()))

    async def delete(self, pos: int, length: int):
        visible = [u for u in self.units if not u.deleted]
        doomed = visible[pos : pos + length]
        for u in doomed:
            u.deleted = True
        doomed.sort(key=lambda u: (u.c, u.k))
        i = 0
        while i < len(doomed):
            j = i + 1
            while (
                j < len(doomed)
                and doomed[j].c == doomed[i].c
                and doomed[j].k == doomed[j - 1].k + 1
            ):
                j += 1
            e = Encoder()
            e.write_var_uint(UPDATE)
            body = Encoder()
            body.write_var_uint(0)  # no struct sections
            body.write_var_uint(1)
            body.write_var_uint(doomed[i].c)
            body.write_var_uint(1)
            body.write_var_uint(doomed[i].k)
            body.write_var_uint(j - i)
            e.write_var_uint8_array(body.to_bytes())
            await self._ws.send_bytes(self._frame(MSG_SYNC, e.to_bytes()))
            i = j

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._ws is not None:
            await self._ws.close()
        if self._session is not None:
            await self._session.close()


async def test_browser_client_converges_with_provider():
    server = await new_hocuspocus()
    browser = BrowserMirrorClient()
    provider = new_provider(server, name="browser-demo")
    try:
        await wait_synced(provider)
        await browser.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(browser.synced))

        await browser.insert(0, "from the browser ")
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text(ROOT).to_string() == "from the browser "
            )
        )
        provider.document.get_text(ROOT).insert(0, "provider says: ")
        await retryable_assertion(
            lambda: _assert(
                browser.text() == provider.document.get_text(ROOT).to_string()
            )
        )
        # browser-side delete (ds-only update) propagates
        await browser.delete(0, len("provider says: "))
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text(ROOT).to_string() == "from the browser "
                and browser.text() == "from the browser "
            )
        )
    finally:
        await browser.close()
        provider.destroy()
        await server.destroy()


async def test_two_browser_tabs_sync_through_server():
    """The demo's headline: two 'tabs' converge through the server."""
    server = await new_hocuspocus()
    tab_a = BrowserMirrorClient()
    tab_b = BrowserMirrorClient()
    try:
        await tab_a.connect(server.web_socket_url)
        await tab_b.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(tab_a.synced and tab_b.synced))
        await tab_a.insert(0, "hello ")
        await retryable_assertion(lambda: _assert(tab_b.text() == "hello "))
        await tab_b.insert(6, "world")
        await retryable_assertion(
            lambda: _assert(tab_a.text() == tab_b.text() == "hello world")
        )
        # concurrent same-position inserts resolve identically (YATA)
        await asyncio.gather(tab_a.insert(5, "A"), tab_b.insert(5, "B"))
        await retryable_assertion(
            lambda: _assert(
                tab_a.text() == tab_b.text() and len(tab_a.text()) == 13
            )
        )
    finally:
        await tab_a.close()
        await tab_b.close()
        await server.destroy()


async def test_late_browser_tab_cold_syncs_server_state():
    server = await new_hocuspocus()
    provider = new_provider(server, name="browser-demo")
    late = BrowserMirrorClient()
    try:
        await wait_synced(provider)
        text = provider.document.get_text(ROOT)
        text.insert(0, "existing state with emoji-free text")
        text.delete(0, 9)
        await late.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(late.synced))
        await retryable_assertion(
            lambda: _assert(late.text() == text.to_string())
        )
    finally:
        await late.close()
        provider.destroy()
        await server.destroy()


async def test_cold_sync_with_cross_section_delete_and_tombstones():
    """Regression for the review findings: a SyncStep2 whose sections
    are client-id-DESCENDING can carry (a) a high-client run whose
    origin lives in a later (lower-client) section — it goes pending —
    and (b) a delete set targeting those pending clocks. The delete
    must still land once the run integrates."""
    server = await new_hocuspocus()
    high = BrowserMirrorClient()
    low = BrowserMirrorClient()
    # force the ordering: high client id > low client id
    high.client_id = 0xFFFFFF0
    low.client_id = 0x10
    late = BrowserMirrorClient()
    try:
        await low.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(low.synced))
        await low.insert(0, "base")
        await high.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(high.synced and high.text() == "base"))
        await high.insert(4, "XY")  # origin = low's last unit
        await retryable_assertion(lambda: _assert(low.text() == "baseXY"))
        await high.delete(4, 2)  # tombstone high's own units
        await retryable_assertion(lambda: _assert(low.text() == "base"))

        # a COLD joiner receives everything in one SyncStep2 (sections
        # sorted client-descending: high's structs before low's)
        await late.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(late.synced))
        await retryable_assertion(lambda: _assert(late.text() == "base"))
        assert not late.pending, "high-client run stuck in pending"
        assert not late.pending_deletes, "delete never resolved"
    finally:
        for c in (high, low, late):
            await c.close()
        await server.destroy()
