"""The browser demo's protocol path, driven from Python byte-for-byte.

examples/browser/index.html speaks the wire protocol with a hand-rolled
client (lib0 frames, auth submessage, SyncStep1/2/Update, a per-unit
YATA rich-text CRDT with ContentFormat markers, y-awareness cursor
states). No JS runtime exists in this image, so this test translates
that client 1:1 (same frame layout, same single-struct update encoding,
same ds-only deletes, same stored-origin full-state reply to the
server's SyncStep1, same awareness payloads) and drives it over a raw
websocket — pinning every protocol interaction the page performs
against the real server, alongside a standard provider.

Reference counterpart: the playground frontend's provider traffic
(`/root/reference/playground/frontend`, Tiptap bold/italic marks +
collaboration-cursor) through
`packages/server/src/ClientConnection.ts:279-343` (auth queueing) and
`MessageReceiver.ts:137-213` (sync handshake, awareness fan-out).
"""

import asyncio
import json
import random

import aiohttp

from hocuspocus_tpu.crdt.encoding import Decoder, Encoder
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

ROOT = "body"
MSG_SYNC, MSG_AWARENESS, MSG_AUTH, MSG_QUERY_AWARENESS = 0, 1, 2, 3
MSG_SYNC_REPLY, MSG_SYNC_STATUS = 4, 8
STEP1, STEP2, UPDATE = 0, 1, 2


def _assert(cond):
    assert cond


class _Unit:
    __slots__ = ("c", "k", "kind", "ch", "fk", "fv", "deleted", "oc", "ok")

    def __init__(self, c, k, ch, oc, ok, kind="ch", fk=None, fv=None):
        self.c, self.k, self.ch = c, k, ch
        self.oc, self.ok = oc, ok
        self.kind, self.fk, self.fv = kind, fk, fv
        self.deleted = False


class BrowserMirrorClient:
    """Python twin of the JS client in examples/browser/index.html."""

    def __init__(self, doc_name: str = "browser-demo") -> None:
        self.doc_name = doc_name
        self.client_id = random.getrandbits(28)
        self.clock = 0
        self.units: list[_Unit] = []
        self.known: dict[int, int] = {}
        self.pending: list = []
        self.pending_deletes: list = []  # (client, clock, len) awaiting targets
        self.synced = False
        self.aw_clock = 0
        # awareness: clientId -> {"clock": int, "state": dict}
        self.remote_states: dict[int, dict] = {}
        self._session = None
        self._ws = None
        self._reader_task = None

    # -- crdt (mirrors integrateRun / applyDelete / drainPending) -----------

    def _idx(self, c, k):
        for i, u in enumerate(self.units):
            if u.c == c and u.k == k:
                return i
        return -1

    def _integrate(self, run) -> bool:
        c, k, text, length, oc, ok, rc, rk = run
        have = self.known.get(c, 0)
        if k + length <= have:
            return True
        if k > have:
            return False
        off = have - k
        left_idx = -1
        if oc is not None and off == 0:
            left_idx = self._idx(oc, ok)
            if left_idx < 0:
                return False
        elif off > 0:
            left_idx = self._idx(c, k + off - 1)
            if left_idx < 0:
                return False
        right_idx = len(self.units)
        if rc is not None:
            right_idx = self._idx(rc, rk)
            if right_idx < 0:
                return False
        dest = right_idx
        for i in range(left_idx + 1, right_idx):
            u = self.units[i]
            u_origin = -1 if u.oc is None else self._idx(u.oc, u.ok)
            skip = u_origin > left_idx or (u_origin == left_idx and u.c < c)
            if not skip:
                dest = i
                break
        inserted = []
        for j in range(off, length):
            j_oc = oc if j == 0 else c
            j_ok = ok if j == 0 else k + j - 1
            if isinstance(text, tuple):  # ("fmt", key, value) marker
                inserted.append(
                    _Unit(c, k + j, 0, j_oc, j_ok, kind="fmt", fk=text[1], fv=text[2])
                )
            else:
                inserted.append(
                    _Unit(c, k + j, 0 if text is None else ord(text[j]), j_oc, j_ok)
                )
                if text is None:
                    inserted[-1].deleted = True
        self.units[dest:dest] = inserted
        self.known[c] = k + length
        return True

    def _apply_delete(self, c, k, length):
        for u in self.units:
            if u.c == c and k <= u.k < k + length:
                u.deleted = True

    def _drain_pending(self):
        progress = True
        while progress:
            progress = False
            for run in list(self.pending):
                if self._integrate(run):
                    self.pending.remove(run)
                    progress = True
        # deletes are idempotent: re-apply until the range is known (a
        # delete may target structs that were pending when it arrived)
        for entry in list(self.pending_deletes):
            c, k, length = entry
            self._apply_delete(c, k, length)
            if self.known.get(c, 0) >= k + length:
                self.pending_deletes.remove(entry)

    def text(self) -> str:
        return "".join(
            chr(u.ch) for u in self.units if not u.deleted and u.kind == "ch"
        )

    def rich_chars(self) -> list:
        """Visible chars with accumulated format attributes (mirrors
        the page's richChars(): a live ContentFormat marker flips the
        attribute for everything after it)."""
        out, attrs = [], {}
        for u in self.units:
            if u.deleted:
                continue
            if u.kind == "fmt":
                if u.fv is None:
                    attrs.pop(u.fk, None)
                else:
                    attrs[u.fk] = u.fv
            else:
                out.append((chr(u.ch), dict(attrs), u))
        return out

    def rich_spans(self) -> list:
        """Coalesced (text, attrs) runs — comparable to YText.to_delta()."""
        spans = []
        for ch, attrs, _u in self.rich_chars():
            if spans and spans[-1][1] == attrs:
                spans[-1][0] += ch
            else:
                spans.append([ch, attrs])
        return [(s, a) for s, a in spans]

    def attrs_at_boundary(self, pos: int) -> dict:
        """Attributes active for the char AT visible index pos (markers
        between char pos-1 and char pos included)."""
        attrs, seen = {}, 0
        for u in self.units:
            if u.deleted:
                continue
            if u.kind == "fmt":
                if u.fv is None:
                    attrs.pop(u.fk, None)
                else:
                    attrs[u.fk] = u.fv
                continue
            if seen == pos:
                break
            seen += 1
        return attrs

    def _unit_index_of_visible(self, pos: int) -> int:
        seen = 0
        for i, u in enumerate(self.units):
            if u.deleted or u.kind == "fmt":
                continue
            if seen == pos:
                return i
            seen += 1
        return len(self.units)

    def rel_of_offset(self, pos: int):
        chars = self.rich_chars()
        return [chars[pos][2].c, chars[pos][2].k] if pos < len(chars) else None

    def offset_of_rel(self, rel):
        if rel is None:
            return len(self.rich_chars())
        for i, (_ch, _attrs, u) in enumerate(self.rich_chars()):
            if u.c == rel[0] and u.k == rel[1]:
                return i
        return None

    # -- v1 codec (mirrors decodeUpdateAndApply / encodeRun / full state) ----

    def _apply_update(self, data: bytes):
        d = Decoder(data)
        for _ in range(d.read_var_uint()):
            num = d.read_var_uint()
            client = d.read_var_uint()
            clock = d.read_var_uint()
            for _ in range(num):
                info = d.read_uint8()
                ref = info & 0x1F
                if ref == 0:  # GC occupies its clock range
                    clock += d.read_var_uint()
                    if clock > self.known.get(client, 0):
                        self.known[client] = clock
                    continue
                if ref == 10:  # Skip: a hole, not content
                    clock += d.read_var_uint()
                    continue
                oc = ok = rc = rk = None
                if info & 0x80:
                    oc, ok = d.read_var_uint(), d.read_var_uint()
                if info & 0x40:
                    rc, rk = d.read_var_uint(), d.read_var_uint()
                if not (info & 0xC0):
                    if d.read_var_uint() == 1:
                        d.read_var_string()
                    else:
                        d.read_var_uint(), d.read_var_uint()
                    if info & 0x20:
                        d.read_var_string()
                if ref == 4:
                    text = d.read_var_string()
                    length = len(text)
                elif ref == 1:
                    text, length = None, d.read_var_uint()
                elif ref == 6:  # ContentFormat: key + JSON value, 1 clock
                    key = d.read_var_string()
                    value = json.loads(d.read_var_string())
                    text, length = ("fmt", key, value), 1
                else:
                    raise AssertionError(f"unsupported ref {ref}")
                run = (client, clock, text, length, oc, ok, rc, rk)
                if not self._integrate(run):
                    self.pending.append(run)
                clock += length
        for _ in range(d.read_var_uint()):
            client = d.read_var_uint()
            for _ in range(d.read_var_uint()):
                k, length = d.read_var_uint(), d.read_var_uint()
                self._apply_delete(client, k, length)
                if self.known.get(client, 0) < k + length:
                    self.pending_deletes.append((client, k, length))
        self._drain_pending()

    @staticmethod
    def _write_content(e: Encoder, oc, ok, rc, rk, text):
        """Info byte + origins + (root parent when originless) + payload;
        shared by _encode_run and _encode_full_state (mirrors the page's
        writeContent). `text` is a str (ContentString) or a
        ("fmt", key, value) tuple (ContentFormat)."""
        ref = 0x06 if isinstance(text, tuple) else 0x04
        info = ref | (0x80 if oc is not None else 0) | (0x40 if rc is not None else 0)
        e.write_uint8(info)
        if oc is not None:
            e.write_var_uint(oc), e.write_var_uint(ok)
        if rc is not None:
            e.write_var_uint(rc), e.write_var_uint(rk)
        if oc is None and rc is None:
            e.write_var_uint(1)
            e.write_var_string(ROOT)
        if isinstance(text, tuple):
            e.write_var_string(text[1])
            e.write_var_string(json.dumps(text[2], separators=(",", ":")))
        else:
            e.write_var_string(text)

    @staticmethod
    def _encode_run(e: Encoder, run):
        c, k, text, _length, oc, ok, rc, rk = run
        e.write_var_uint(1)
        e.write_var_uint(1)
        e.write_var_uint(c)
        e.write_var_uint(k)
        BrowserMirrorClient._write_content(e, oc, ok, rc, rk, text)

    def _encode_full_state(self, sv: dict) -> bytes:
        e = Encoder()
        by: dict[int, list] = {}
        for u in self.units:
            if u.k < sv.get(u.c, 0):
                continue
            by.setdefault(u.c, []).append(u)
        e.write_var_uint(len(by))
        for c in sorted(by, reverse=True):
            row = sorted(by[c], key=lambda u: u.k)
            e.write_var_uint(len(row))
            e.write_var_uint(c)
            e.write_var_uint(row[0].k)
            for u in row:
                text = ("fmt", u.fk, u.fv) if u.kind == "fmt" else chr(u.ch)
                self._write_content(e, u.oc, u.ok, None, 0, text)
        ds: dict[int, list] = {}
        for u in self.units:
            if u.deleted:
                ds.setdefault(u.c, []).append(u.k)
        e.write_var_uint(len(ds))
        for c in sorted(ds, reverse=True):
            ks = sorted(ds[c])
            ranges = []
            for k in ks:
                if ranges and ranges[-1][0] + ranges[-1][1] == k:
                    ranges[-1][1] += 1
                else:
                    ranges.append([k, 1])
            e.write_var_uint(c)
            e.write_var_uint(len(ranges))
            for k, l in ranges:
                e.write_var_uint(k), e.write_var_uint(l)
        return e.to_bytes()

    # -- frames + socket -----------------------------------------------------

    def _frame(self, msg_type: int, payload: bytes = b"") -> bytes:
        e = Encoder()
        e.write_var_string(self.doc_name)
        e.write_var_uint(msg_type)
        return e.to_bytes() + payload

    async def connect(self, url: str):
        self._session = aiohttp.ClientSession()
        self._ws = await self._session.ws_connect(url)
        auth = Encoder()
        auth.write_var_uint(0)
        auth.write_var_string("browser-demo")
        await self._ws.send_bytes(self._frame(MSG_AUTH, auth.to_bytes()))
        step1 = Encoder()
        step1.write_var_uint(STEP1)
        step1.write_var_uint8_array(b"\x00")  # empty state vector
        await self._ws.send_bytes(self._frame(MSG_SYNC, step1.to_bytes()))
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self):
        async for msg in self._ws:
            if msg.type != aiohttp.WSMsgType.BINARY:
                continue
            d = Decoder(msg.data)
            d.read_var_string()
            msg_type = d.read_var_uint()
            if msg_type in (MSG_SYNC, MSG_SYNC_REPLY):
                sub = d.read_var_uint()
                if sub == STEP1:
                    sv_reader = Decoder(d.read_var_uint8_array())
                    sv = {}
                    for _ in range(sv_reader.read_var_uint()):
                        client = sv_reader.read_var_uint()
                        sv[client] = sv_reader.read_var_uint()
                    reply = Encoder()
                    reply.write_var_uint(STEP2)
                    reply.write_var_uint8_array(self._encode_full_state(sv))
                    await self._ws.send_bytes(
                        self._frame(MSG_SYNC_REPLY, reply.to_bytes())
                    )
                elif sub in (STEP2, UPDATE):
                    self._apply_update(bytes(d.read_var_uint8_array()))
                    if sub == STEP2:
                        self.synced = True
            elif msg_type == MSG_AWARENESS:
                aw = Decoder(bytes(d.read_var_uint8_array()))
                for _ in range(aw.read_var_uint()):
                    cid = aw.read_var_uint()
                    clock = aw.read_var_uint()
                    state = json.loads(aw.read_var_string())
                    if cid == self.client_id:
                        continue
                    prev = self.remote_states.get(cid)
                    if prev is not None and clock < prev["clock"]:
                        continue
                    if state is None:
                        self.remote_states.pop(cid, None)
                    else:
                        self.remote_states[cid] = {"clock": clock, "state": state}

    async def _send_run(self, run):
        assert self._integrate(run)
        e = Encoder()
        e.write_var_uint(UPDATE)
        body = Encoder()
        self._encode_run(body, run)
        body.write_var_uint(0)  # trailing (empty) delete set
        e.write_var_uint8_array(body.to_bytes())
        await self._ws.send_bytes(self._frame(MSG_SYNC, e.to_bytes()))

    async def insert(self, pos: int, text: str):
        """Insert at VISIBLE position pos, like the page's
        insertVisibleAt: boundaries are the unit-order neighbors of the
        pos'th visible char (format markers and tombstones at the
        boundary count — typing after a close-marker stays unstyled)."""
        ia = self._unit_index_of_visible(pos)
        left = self.units[ia - 1] if ia > 0 else None
        right = self.units[ia] if ia < len(self.units) else None
        run = (
            self.client_id,
            self.clock,
            text,
            len(text),
            left.c if left else None,
            left.k if left else 0,
            right.c if right else None,
            right.k if right else 0,
        )
        self.clock += len(text)
        await self._send_run(run)

    async def format_range(self, a: int, b: int, key: str, value):
        """Mirror of the page's toggleFormat with an explicit value:
        an opening marker {key: value} before visible char a and a
        closing marker restoring the boundary state before char b."""
        after_val = self.attrs_at_boundary(b).get(key)
        ia = self._unit_index_of_visible(a)
        ib = self._unit_index_of_visible(b)
        left1 = self.units[ia - 1] if ia > 0 else None
        right1 = self.units[ia] if ia < len(self.units) else None
        left2 = self.units[ib - 1] if ib > 0 else None
        right2 = self.units[ib] if ib < len(self.units) else None
        markers = [(left1, right1, value)]
        if json.dumps(after_val) != json.dumps(value):
            markers.append((left2, right2, after_val))
        for left, right, val in markers:
            run = (
                self.client_id,
                self.clock,
                ("fmt", key, val),
                1,
                left.c if left else None,
                left.k if left else 0,
                right.c if right else None,
                right.k if right else 0,
            )
            self.clock += 1
            await self._send_run(run)

    async def send_awareness(self, state):
        """One-client awareness update (protocol/awareness.py layout)."""
        self.aw_clock += 1
        aw = Encoder()
        aw.write_var_uint(1)
        aw.write_var_uint(self.client_id)
        aw.write_var_uint(self.aw_clock)
        aw.write_var_string(json.dumps(state, separators=(",", ":")))
        e = Encoder()
        e.write_var_uint8_array(aw.to_bytes())
        await self._ws.send_bytes(self._frame(MSG_AWARENESS, e.to_bytes()))

    async def query_awareness(self):
        await self._ws.send_bytes(self._frame(MSG_QUERY_AWARENESS))

    async def delete(self, pos: int, length: int):
        visible = [u for u in self.units if not u.deleted and u.kind == "ch"]
        doomed = visible[pos : pos + length]
        for u in doomed:
            u.deleted = True
        doomed.sort(key=lambda u: (u.c, u.k))
        i = 0
        while i < len(doomed):
            j = i + 1
            while (
                j < len(doomed)
                and doomed[j].c == doomed[i].c
                and doomed[j].k == doomed[j - 1].k + 1
            ):
                j += 1
            e = Encoder()
            e.write_var_uint(UPDATE)
            body = Encoder()
            body.write_var_uint(0)  # no struct sections
            body.write_var_uint(1)
            body.write_var_uint(doomed[i].c)
            body.write_var_uint(1)
            body.write_var_uint(doomed[i].k)
            body.write_var_uint(j - i)
            e.write_var_uint8_array(body.to_bytes())
            await self._ws.send_bytes(self._frame(MSG_SYNC, e.to_bytes()))
            i = j

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._ws is not None:
            await self._ws.close()
        if self._session is not None:
            await self._session.close()


async def test_browser_client_converges_with_provider():
    server = await new_hocuspocus()
    browser = BrowserMirrorClient()
    provider = new_provider(server, name="browser-demo")
    try:
        await wait_synced(provider)
        await browser.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(browser.synced))

        await browser.insert(0, "from the browser ")
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text(ROOT).to_string() == "from the browser "
            )
        )
        provider.document.get_text(ROOT).insert(0, "provider says: ")
        await retryable_assertion(
            lambda: _assert(
                browser.text() == provider.document.get_text(ROOT).to_string()
            )
        )
        # browser-side delete (ds-only update) propagates
        await browser.delete(0, len("provider says: "))
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text(ROOT).to_string() == "from the browser "
                and browser.text() == "from the browser "
            )
        )
    finally:
        await browser.close()
        provider.destroy()
        await server.destroy()


async def test_two_browser_tabs_sync_through_server():
    """The demo's headline: two 'tabs' converge through the server."""
    server = await new_hocuspocus()
    tab_a = BrowserMirrorClient()
    tab_b = BrowserMirrorClient()
    try:
        await tab_a.connect(server.web_socket_url)
        await tab_b.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(tab_a.synced and tab_b.synced))
        await tab_a.insert(0, "hello ")
        await retryable_assertion(lambda: _assert(tab_b.text() == "hello "))
        await tab_b.insert(6, "world")
        await retryable_assertion(
            lambda: _assert(tab_a.text() == tab_b.text() == "hello world")
        )
        # concurrent same-position inserts resolve identically (YATA)
        await asyncio.gather(tab_a.insert(5, "A"), tab_b.insert(5, "B"))
        await retryable_assertion(
            lambda: _assert(
                tab_a.text() == tab_b.text() and len(tab_a.text()) == 13
            )
        )
    finally:
        await tab_a.close()
        await tab_b.close()
        await server.destroy()


async def test_late_browser_tab_cold_syncs_server_state():
    server = await new_hocuspocus()
    provider = new_provider(server, name="browser-demo")
    late = BrowserMirrorClient()
    try:
        await wait_synced(provider)
        text = provider.document.get_text(ROOT)
        text.insert(0, "existing state with emoji-free text")
        text.delete(0, 9)
        await late.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(late.synced))
        await retryable_assertion(
            lambda: _assert(late.text() == text.to_string())
        )
    finally:
        await late.close()
        provider.destroy()
        await server.destroy()


async def test_cold_sync_with_cross_section_delete_and_tombstones():
    """Regression for the review findings: a SyncStep2 whose sections
    are client-id-DESCENDING can carry (a) a high-client run whose
    origin lives in a later (lower-client) section — it goes pending —
    and (b) a delete set targeting those pending clocks. The delete
    must still land once the run integrates."""
    server = await new_hocuspocus()
    high = BrowserMirrorClient()
    low = BrowserMirrorClient()
    # force the ordering: high client id > low client id
    high.client_id = 0xFFFFFF0
    low.client_id = 0x10
    late = BrowserMirrorClient()
    try:
        await low.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(low.synced))
        await low.insert(0, "base")
        await high.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(high.synced and high.text() == "base"))
        await high.insert(4, "XY")  # origin = low's last unit
        await retryable_assertion(lambda: _assert(low.text() == "baseXY"))
        await high.delete(4, 2)  # tombstone high's own units
        await retryable_assertion(lambda: _assert(low.text() == "base"))

        # a COLD joiner receives everything in one SyncStep2 (sections
        # sorted client-descending: high's structs before low's)
        await late.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(late.synced))
        await retryable_assertion(lambda: _assert(late.text() == "base"))
        assert not late.pending, "high-client run stuck in pending"
        assert not late.pending_deletes, "delete never resolved"
    finally:
        for c in (high, low, late):
            await c.close()
        await server.destroy()


async def test_rich_format_roundtrip_with_provider():
    """The page's toggleFormat markers land as real ContentFormat in the
    server's YText (to_delta sees attributes), and a provider-side
    YText.format comes back as markers the page's span model renders."""
    server = await new_hocuspocus()
    browser = BrowserMirrorClient()
    provider = new_provider(server, name="browser-demo")
    try:
        await wait_synced(provider)
        await browser.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(browser.synced))

        await browser.insert(0, "hello world")
        await browser.format_range(0, 5, "bold", True)

        def _delta_has_bold():
            delta = provider.document.get_text(ROOT).to_delta()
            _assert(
                delta
                == [
                    {"insert": "hello", "attributes": {"bold": True}},
                    {"insert": " world"},
                ]
            )

        await retryable_assertion(_delta_has_bold)

        # provider styles through the real YText API; the page's
        # accumulated-attrs span model must agree
        provider.document.get_text(ROOT).format(6, 5, {"italic": True})
        await retryable_assertion(
            lambda: _assert(
                browser.rich_spans()
                == [
                    ("hello", {"bold": True}),
                    (" ", {}),
                    ("world", {"italic": True}),
                ]
            )
        )

        # toggling OFF: a null-valued marker clears the attribute.
        # (compare COALESCED spans: to_delta legitimately splits ops at
        # every marker boundary, styled or not)
        def _spans(delta):
            spans = []
            for op in delta:
                attrs = op.get("attributes", {})
                if spans and spans[-1][1] == attrs:
                    spans[-1][0] += op["insert"]
                else:
                    spans.append([op["insert"], attrs])
            return [(s, a) for s, a in spans]

        await browser.format_range(0, 5, "bold", None)
        await retryable_assertion(
            lambda: _assert(
                _spans(provider.document.get_text(ROOT).to_delta())
                == [
                    ("hello ", {}),
                    ("world", {"italic": True}),
                ]
            )
        )
    finally:
        await browser.close()
        provider.destroy()
        await server.destroy()


async def test_two_tabs_rich_formatting_converges():
    """Two 'tabs' agree on styled spans; typing inside a bold range
    inherits bold, typing after the close marker stays unstyled."""
    server = await new_hocuspocus()
    tab_a = BrowserMirrorClient()
    tab_b = BrowserMirrorClient()
    try:
        await tab_a.connect(server.web_socket_url)
        await tab_b.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(tab_a.synced and tab_b.synced))

        await tab_a.insert(0, "fat text")
        await retryable_assertion(lambda: _assert(tab_b.text() == "fat text"))
        await tab_a.format_range(0, 3, "bold", True)
        await retryable_assertion(
            lambda: _assert(
                tab_b.rich_spans() == [("fat", {"bold": True}), (" text", {})]
            )
        )

        # tab B types INSIDE the bold range -> inherits bold everywhere
        await tab_b.insert(1, "l")
        await retryable_assertion(
            lambda: _assert(
                tab_a.rich_spans()
                == tab_b.rich_spans()
                == [("flat", {"bold": True}), (" text", {})]
            )
        )

        # typing at the right edge lands AFTER the close marker (the
        # unit-order boundary) -> unstyled in both tabs
        await tab_a.insert(4, "X")
        await retryable_assertion(
            lambda: _assert(
                tab_a.rich_spans()
                == tab_b.rich_spans()
                == [("flat", {"bold": True}), ("X text", {})]
            )
        )
    finally:
        await tab_a.close()
        await tab_b.close()
        await server.destroy()


async def test_awareness_cursors_roundtrip():
    """The page's awareness frames (user chip + relative-ref cursor)
    reach a standard provider, the provider's state reaches the page,
    and a late tab discovers everyone via QueryAwareness."""
    server = await new_hocuspocus()
    browser = BrowserMirrorClient()
    provider = new_provider(server, name="browser-demo")
    late = BrowserMirrorClient()
    try:
        await wait_synced(provider)
        await browser.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(browser.synced))

        await browser.insert(0, "abc")
        cursor = {"a": browser.rel_of_offset(1), "h": browser.rel_of_offset(1)}
        await browser.send_awareness(
            {"user": {"name": "pearl-7", "color": "#123456"}, "cursor": cursor}
        )

        def _provider_sees_browser():
            states = provider.awareness.get_states()
            state = states.get(browser.client_id)
            _assert(state is not None)
            _assert(state["user"]["name"] == "pearl-7")
            # the relative ref survives verbatim (opaque JSON to the server)
            _assert(state["cursor"]["h"] == [browser.client_id, 1])

        await retryable_assertion(_provider_sees_browser)

        provider.awareness.set_local_state(
            {"user": {"name": "prov", "color": "#654321"}, "cursor": None}
        )
        await retryable_assertion(
            lambda: _assert(
                any(
                    s["state"].get("user", {}).get("name") == "prov"
                    for s in browser.remote_states.values()
                )
            )
        )

        # a late tab pulls the room roster with QueryAwareness
        await late.connect(server.web_socket_url)
        await retryable_assertion(lambda: _assert(late.synced))
        await late.query_awareness()
        await retryable_assertion(
            lambda: _assert(
                {
                    s["state"]["user"]["name"]
                    for s in late.remote_states.values()
                    if s["state"].get("user")
                }
                >= {"pearl-7", "prov"}
            )
        )

        # the cursor's relative ref resolves to the right offset even
        # after concurrent edits shifted absolute positions
        await late.insert(0, "xxx")
        await retryable_assertion(lambda: _assert(browser.text() == "xxxabc"))
        state = provider.awareness.get_states()[browser.client_id]
        resolved = browser.offset_of_rel(state["cursor"]["h"])
        assert resolved == 4, f"relative cursor drifted: {resolved}"
    finally:
        await late.close()
        await browser.close()
        provider.destroy()
        await server.destroy()
