"""Per-hook server behavior (mirrors reference tests/server/* taxonomy)."""

import asyncio

import pytest

from hocuspocus_tpu.server import Extension, Payload
from tests.utils import (
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_for,
    wait_synced,
)


def _assert(cond):
    assert cond


async def test_on_connect_and_connected_fire():
    events = []

    async def on_connect(data):
        events.append(("on_connect", data.document_name))

    async def connected(data):
        events.append(("connected", data.document_name))

    server = await new_hocuspocus(on_connect=on_connect, connected=connected)
    provider = new_provider(server, name="doc")
    try:
        await wait_synced(provider)
        assert ("on_connect", "doc") in events
        assert ("connected", "doc") in events
        # onConnect runs before connected
        assert events.index(("on_connect", "doc")) < events.index(("connected", "doc"))
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_authenticate_receives_token():
    tokens = []

    async def on_authenticate(data):
        tokens.append(data.token)

    server = await new_hocuspocus(on_authenticate=on_authenticate)
    provider = new_provider(server, token="secret-token-123")
    try:
        await wait_synced(provider)
        assert tokens == ["secret-token-123"]
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_authenticate_rejection_denies_connection():
    async def on_authenticate(data):
        raise Exception("not allowed")

    server = await new_hocuspocus(on_authenticate=on_authenticate)
    provider = new_provider(server, token="bad")
    failures = []
    provider.on("authentication_failed", lambda data: failures.append(data))
    try:
        await retryable_assertion(lambda: _assert(len(failures) >= 1))
        assert not provider.synced
        assert not provider.is_authenticated
    finally:
        provider.destroy()
        await server.destroy()


async def test_context_merging_across_hooks():
    seen_contexts = []

    async def on_connect(data):
        return {"user_id": 42}

    async def on_authenticate(data):
        return {"role": "admin"}

    async def connected(data):
        seen_contexts.append(dict(data.context))

    server = await new_hocuspocus(
        on_connect=on_connect, on_authenticate=on_authenticate, connected=connected
    )
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        assert seen_contexts == [{"user_id": 42, "role": "admin"}]
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_load_document_seeds_content():
    from hocuspocus_tpu.crdt import Doc

    async def on_load_document(data):
        seed = Doc()
        seed.get_text("t").insert(0, "seeded")
        return seed

    server = await new_hocuspocus(on_load_document=on_load_document)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        await retryable_assertion(
            lambda: _assert(provider.document.get_text("t").to_string() == "seeded")
        )
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_load_document_failure_closes_connection():
    async def on_load_document(data):
        raise Exception("load failed")

    server = await new_hocuspocus(on_load_document=on_load_document)
    provider = new_provider(server)
    try:
        await asyncio.sleep(0.5)
        assert not provider.synced
        assert server.get_documents_count() == 0
    finally:
        provider.destroy()
        await server.destroy()


async def test_before_handle_message_rejection_blocks_updates():
    reject = False
    rejected = []

    async def before_handle_message(data):
        if reject:
            rejected.append(data.document_name)
            raise Exception("rejected")

    server = await new_hocuspocus(before_handle_message=before_handle_message)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        reject = True
        provider.document.get_text("t").insert(0, "x")
        await retryable_assertion(lambda: _assert(len(rejected) >= 1))
        # server must not have applied the change
        doc = server.documents.get("hocuspocus-test")
        if doc is not None:
            assert doc.get_text("t").to_string() == ""
    finally:
        provider.destroy()
        await server.destroy()


async def test_before_sync_sees_payload():
    seen = []

    async def before_sync(data):
        seen.append((data.type, bytes(data.payload)))

    server = await new_hocuspocus(before_sync=before_sync)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        await retryable_assertion(lambda: _assert(len(seen) >= 1))
        assert seen[0][0] == 0  # SyncStep1 first
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_change_payload():
    changes = []

    async def on_change(data):
        changes.append(
            {
                "name": data.document_name,
                "clients_count": data.clients_count,
                "update_len": len(data.update),
                "socket_id": data.socket_id,
            }
        )

    server = await new_hocuspocus(on_change=on_change)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "change me")
        await retryable_assertion(lambda: _assert(len(changes) >= 1))
        assert changes[0]["name"] == "hocuspocus-test"
        assert changes[0]["clients_count"] == 1
        assert changes[0]["update_len"] > 0
        assert changes[0]["socket_id"]
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_store_document_debounce_collapses_edits():
    stores = []

    async def on_store_document(data):
        stores.append(data.document_name)

    server = await new_hocuspocus(on_store_document=on_store_document, debounce=200)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        for i in range(5):
            provider.document.get_text("t").insert(0, "x")
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.5)
        assert len(stores) == 1  # five edits, one debounced store
    finally:
        provider.destroy()
        await server.destroy()


async def test_after_store_document_follows_store():
    order = []

    async def on_store_document(data):
        order.append("store")

    async def after_store_document(data):
        order.append("after")

    server = await new_hocuspocus(
        on_store_document=on_store_document,
        after_store_document=after_store_document,
        debounce=50,
    )
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        await retryable_assertion(lambda: _assert(order == ["store", "after"]))
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_disconnect_fires():
    disconnects = []

    async def on_disconnect(data):
        disconnects.append(data.document_name)

    server = await new_hocuspocus(on_disconnect=on_disconnect)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.destroy()
        await retryable_assertion(lambda: _assert(disconnects == ["hocuspocus-test"]))
    finally:
        await server.destroy()


async def test_unload_document_after_last_disconnect():
    unloads = []

    async def after_unload_document(data):
        unloads.append(data.document_name)

    server = await new_hocuspocus(after_unload_document=after_unload_document)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        assert server.get_documents_count() == 1
        provider.destroy()
        await retryable_assertion(lambda: _assert(server.get_documents_count() == 0))
        assert "hocuspocus-test" in unloads
    finally:
        await server.destroy()


async def test_before_unload_document_veto_keeps_document():
    async def before_unload_document(data):
        raise Exception("keep it")

    server = await new_hocuspocus(before_unload_document=before_unload_document)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.destroy()
        await asyncio.sleep(0.3)
        assert server.get_documents_count() == 1  # veto kept it loaded
    finally:
        server.hocuspocus.configuration.before_unload_document = None
        server.hocuspocus.configure(server.hocuspocus.configuration)
        await server.destroy()


async def test_on_request_hook_custom_response():
    import aiohttp
    from aiohttp import web

    async def on_request(data):
        data["response"] = web.Response(status=418, text="teapot")

    server = await new_hocuspocus(on_request=on_request)
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(server.http_url) as response:
                assert response.status == 418
                assert await response.text() == "teapot"
    finally:
        await server.destroy()


async def test_extension_priority_order():
    order = []

    class First(Extension):
        priority = 1000

        async def on_connect(self, data):
            order.append("first")

    class Second(Extension):
        priority = 10

        async def on_connect(self, data):
            order.append("second")

    async def on_connect(data):  # inline callback runs last
        order.append("inline")

    server = await new_hocuspocus(extensions=[Second(), First()], on_connect=on_connect)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        assert order == ["first", "second", "inline"]
    finally:
        provider.destroy()
        await server.destroy()


async def test_close_connections_resets_clients():
    server = await new_hocuspocus()
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        closes = []
        provider.on("close", lambda *args: closes.append(args))
        server.close_connections()
        await retryable_assertion(lambda: _assert(len(closes) >= 1))
    finally:
        provider.destroy()
        await server.destroy()


async def test_stateless_roundtrip():
    received_server = []

    async def on_stateless(data):
        received_server.append(data.payload)
        # reply to the client
        data.connection.send_stateless("pong:" + data.payload)

    server = await new_hocuspocus(on_stateless=on_stateless)
    provider = new_provider(server)
    received_client = []
    provider.on("stateless", lambda data: received_client.append(data["payload"]))
    try:
        await wait_synced(provider)
        provider.send_stateless("ping-1")
        await retryable_assertion(lambda: _assert(received_server == ["ping-1"]))
        await retryable_assertion(lambda: _assert(received_client == ["pong:ping-1"]))
    finally:
        provider.destroy()
        await server.destroy()


async def test_broadcast_stateless_reaches_all_clients():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    provider_b = new_provider(server)
    received = {"a": [], "b": []}
    provider_a.on("stateless", lambda data: received["a"].append(data["payload"]))
    provider_b.on("stateless", lambda data: received["b"].append(data["payload"]))
    try:
        await wait_synced(provider_a, provider_b)
        document = server.documents["hocuspocus-test"]
        document.broadcast_stateless("hello-everyone")
        await retryable_assertion(
            lambda: _assert(
                received["a"] == ["hello-everyone"] and received["b"] == ["hello-everyone"]
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_on_create_document_ydoc_options():
    seen = []

    async def on_create_document(data):
        seen.append(data.document_name)
        return {"gc": False}

    server = await new_hocuspocus(on_create_document=on_create_document)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        assert seen == ["hocuspocus-test"]
        assert server.documents["hocuspocus-test"].gc is False
    finally:
        provider.destroy()
        await server.destroy()
