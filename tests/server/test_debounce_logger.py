"""Keyed debouncer max-wait semantics and Logger flag filtering.

Mirrors reference test intent for `util/debounce.ts` (delay collapse,
max-wait force-run, executeNow) and `extension-logger` (per-hook
on/off flags, injectable sink, `[name ISO-date] message` format).
"""

import asyncio
import re

import pytest

from hocuspocus_tpu.extensions.logger import Logger
from hocuspocus_tpu.server.debounce import Debouncer
from hocuspocus_tpu.server.types import Payload


async def test_debounce_collapses_and_fires_once():
    debouncer = Debouncer()
    calls = []
    for i in range(5):
        debouncer.debounce("k", lambda i=i: calls.append(i), 30, 10000)
        await asyncio.sleep(0.005)
    assert calls == []
    assert debouncer.is_debounced("k")
    await asyncio.sleep(0.06)
    assert calls == [4]  # only the last scheduled fn ran
    assert not debouncer.is_debounced("k")


async def test_max_debounce_forces_run():
    debouncer = Debouncer()
    calls = []
    # keep re-debouncing faster than the delay; max-wait must force a run
    for _ in range(12):
        debouncer.debounce("k", lambda: calls.append(1), 50, 100)
        await asyncio.sleep(0.015)
    assert calls, "max_debounce never forced the run"


async def test_execute_now_runs_pending_and_clears():
    debouncer = Debouncer()
    calls = []
    debouncer.debounce("k", lambda: calls.append(1), 10000, 60000)
    assert debouncer.is_debounced("k")
    debouncer.execute_now("k")
    assert calls == [1]
    assert not debouncer.is_debounced("k")
    assert debouncer.execute_now("missing") is None


async def test_in_flight_covers_timer_fire_to_task_completion():
    """The unload-decision window the comment in debounce.py documents:
    between the timer popping `_timers` and the task's coroutine first
    running, the work is invisible to `is_debounced` AND to any mutex
    the coroutine will take. `in_flight` must be True for that whole
    stretch, or a caller tears down state the store still needs."""
    debouncer = Debouncer()
    started = asyncio.Event()
    release = asyncio.Event()

    async def store() -> None:
        started.set()
        await release.wait()

    task = debouncer.debounce("k", store, 0, 10000)  # fires immediately
    # the exact hazard window: timer fired (not debounced any more), the
    # coroutine has NOT run yet (no mutex held, nothing started)
    assert not debouncer.is_debounced("k")
    assert not started.is_set()
    assert debouncer.in_flight("k"), (
        "fired-but-not-started store invisible to in_flight: the unload "
        "path would drop the doc out from under the pending store"
    )
    await started.wait()
    assert debouncer.in_flight("k")  # still running
    release.set()
    await task
    assert not debouncer.in_flight("k")


async def test_timer_fired_store_cannot_race_unload(tmp_path):
    """End-to-end pin for the Debouncer.in_flight unload window: a
    store fired by the debounce timer is still pending when the last
    connection closes. handle_close must NOT unload the document — the
    store task's own finally does, after the store completed. A doc
    dropped from the registry before its state hit storage would load
    EMPTY on a fast rejoin."""
    from hocuspocus_tpu.extensions import Database
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    gate = asyncio.Event()
    store_started = asyncio.Event()
    stored: list = []

    async def slow_store(data) -> None:
        store_started.set()
        await gate.wait()
        stored.append(bytes(data["state"]))

    server = await new_hocuspocus(
        extensions=[Database(store=slow_store)], debounce=30
    )
    provider = new_provider(server, name="race-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "must survive the race")
        # wait for the debounce timer to FIRE (store coroutine started,
        # now parked on the gate with the save_mutex held)
        await asyncio.wait_for(store_started.wait(), timeout=10)
        assert server.hocuspocus.debouncer.in_flight("onStoreDocument-race-doc")
        # last connection leaves while the store is still pending
        provider.destroy()
        await asyncio.sleep(0.1)
        assert "race-doc" in server.hocuspocus.documents, (
            "unload raced the in-flight store and dropped the doc"
        )
        gate.set()
        await retryable_assertion(lambda: _assert_true(stored))
        # with the store complete and no connections, the task's finally
        # unloads the doc
        await retryable_assertion(
            lambda: _assert_true("race-doc" not in server.hocuspocus.documents)
        )
        # a rejoin loads the STORED state, not an empty doc
        rejoin = new_provider(server, name="race-doc")
        try:
            await wait_synced(rejoin)
            # the Database fetch is a no-op here, but the registry must
            # have gone through a full store-then-unload cycle
            assert stored and len(stored[0]) > 2
        finally:
            rejoin.destroy()
    finally:
        provider.destroy()
        await server.destroy()


def _assert_true(cond):
    assert cond


async def test_logger_flags_and_format():
    lines = []
    logger = Logger(log=lines.append, on_change=False)
    logger.name = "srv"
    await logger.on_change(Payload(document_name="doc"))
    await logger.on_load_document(Payload(document_name="doc"))
    text = "\n".join(lines)
    assert "doc" in text and "Loaded" in text or "load" in text.lower()
    assert "change" not in text.lower()  # flag off
    assert all(re.match(r"^\[srv \d{4}-\d{2}-\d{2}T", line) for line in lines)
