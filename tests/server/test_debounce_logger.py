"""Keyed debouncer max-wait semantics and Logger flag filtering.

Mirrors reference test intent for `util/debounce.ts` (delay collapse,
max-wait force-run, executeNow) and `extension-logger` (per-hook
on/off flags, injectable sink, `[name ISO-date] message` format).
"""

import asyncio
import re

import pytest

from hocuspocus_tpu.extensions.logger import Logger
from hocuspocus_tpu.server.debounce import Debouncer
from hocuspocus_tpu.server.types import Payload


async def test_debounce_collapses_and_fires_once():
    debouncer = Debouncer()
    calls = []
    for i in range(5):
        debouncer.debounce("k", lambda i=i: calls.append(i), 30, 10000)
        await asyncio.sleep(0.005)
    assert calls == []
    assert debouncer.is_debounced("k")
    await asyncio.sleep(0.06)
    assert calls == [4]  # only the last scheduled fn ran
    assert not debouncer.is_debounced("k")


async def test_max_debounce_forces_run():
    debouncer = Debouncer()
    calls = []
    # keep re-debouncing faster than the delay; max-wait must force a run
    for _ in range(12):
        debouncer.debounce("k", lambda: calls.append(1), 50, 100)
        await asyncio.sleep(0.015)
    assert calls, "max_debounce never forced the run"


async def test_execute_now_runs_pending_and_clears():
    debouncer = Debouncer()
    calls = []
    debouncer.debounce("k", lambda: calls.append(1), 10000, 60000)
    assert debouncer.is_debounced("k")
    debouncer.execute_now("k")
    assert calls == [1]
    assert not debouncer.is_debounced("k")
    assert debouncer.execute_now("missing") is None


async def test_logger_flags_and_format():
    lines = []
    logger = Logger(log=lines.append, on_change=False)
    logger.name = "srv"
    await logger.on_change(Payload(document_name="doc"))
    await logger.on_load_document(Payload(document_name="doc"))
    text = "\n".join(lines)
    assert "doc" in text and "Loaded" in text or "load" in text.lower()
    assert "change" not in text.lower()  # flag off
    assert all(re.match(r"^\[srv \d{4}-\d{2}-\d{2}T", line) for line in lines)
