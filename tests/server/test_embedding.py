"""Cross-framework embedding: the core serves real providers from
foreign websocket hosts.

The reference proves its `handleConnection` embedding story with
express/koa/hono/deno playground backends; here the equivalent
`Hocuspocus.handle_connection` + `CallbackWebSocketTransport` is
driven end-to-end under the `websockets` library and Tornado — full
auth/sync/edit round trips with the stock provider (an aiohttp
client), so both directions of the wire cross framework boundaries.
"""

import asyncio

from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.server import (
    CallbackWebSocketTransport,
    Hocuspocus,
    RequestInfo,
)


async def _edit_roundtrip(url: str) -> None:
    a = HocuspocusProvider(name="embedded", url=url)
    b = HocuspocusProvider(name="embedded", url=url)
    try:
        deadline = asyncio.get_event_loop().time() + 10
        while not (a.synced and b.synced):
            assert asyncio.get_event_loop().time() < deadline, "sync timeout"
            await asyncio.sleep(0.01)
        a.document.get_text("t").insert(0, "cross-framework")
        deadline = asyncio.get_event_loop().time() + 10
        while b.document.get_text("t").to_string() != "cross-framework":
            assert asyncio.get_event_loop().time() < deadline, "edit timeout"
            await asyncio.sleep(0.01)
    finally:
        a.destroy()
        b.destroy()


async def test_embed_under_websockets_library():
    import websockets

    hocuspocus = Hocuspocus()

    async def collab(ws) -> None:
        transport = CallbackWebSocketTransport(
            send_async=ws.send,
            close_async=lambda code, reason: ws.close(code=code, reason=reason),
        )
        request_info = RequestInfo(
            headers=dict(ws.request.headers), url=ws.request.path
        )
        connection = hocuspocus.handle_connection(
            transport, request_info, {"via": "websockets"}
        )
        try:
            async for message in ws:
                if isinstance(message, bytes):
                    await connection.handle_message(message)
        finally:
            transport.abort()
            await connection.handle_transport_close(1000, "")

    async with websockets.serve(collab, "127.0.0.1", 0) as server:
        port = server.sockets[0].getsockname()[1]
        await _edit_roundtrip(f"ws://127.0.0.1:{port}")
    hocuspocus.close_connections()
    await asyncio.sleep(0.1)  # let unload hooks settle


async def test_embed_under_tornado():
    import tornado.web
    import tornado.websocket

    hocuspocus = Hocuspocus()

    class CollabHandler(tornado.websocket.WebSocketHandler):
        def open(self) -> None:
            async def send(data: bytes) -> None:
                await self.write_message(data, binary=True)

            async def close(code: int, reason: str) -> None:
                tornado.websocket.WebSocketHandler.close(self, code, reason)

            self.transport = CallbackWebSocketTransport(send, close)
            request_info = RequestInfo(
                headers=dict(self.request.headers), url=self.request.uri or "/"
            )
            self.connection = hocuspocus.handle_connection(
                self.transport, request_info, {"via": "tornado"}
            )

        async def on_message(self, message) -> None:
            if isinstance(message, bytes):
                await self.connection.handle_message(message)

        def on_close(self) -> None:
            self.transport.abort()
            asyncio.ensure_future(
                self.connection.handle_transport_close(self.close_code or 1000, "")
            )

    app = tornado.web.Application([(r"/collab", CollabHandler)])
    server = app.listen(0, address="127.0.0.1")
    try:
        port = next(iter(server._sockets.values())).getsockname()[1]
        await _edit_roundtrip(f"ws://127.0.0.1:{port}/collab")
    finally:
        server.stop()
        hocuspocus.close_connections()
        await asyncio.sleep(0.1)  # let unload hooks settle
