"""Overload control plane (ISSUE 12, docs/guides/overload.md).

Covers the hysteresis degradation ladder (immediate escalation, one
rung down per hold window, never a flap), per-tenant token-bucket
admission at connect/auth (a tenant over quota cannot starve another
tenant's joins), the shared 503 + Retry-After rejection between the
drain path and RED-state admission, the provider reconnect backoff
ladder climbing across repeated 503s, RED-state ingress enforcement
(close 1013), the brownout fan-out behaviors (awareness
stretch/elision, catch-up deferral), and the /healthz + /debug/slo
surfaces (200-always convention).
"""

import asyncio
import time

import aiohttp
import pytest

from hocuspocus_tpu.observability.wire import get_wire_telemetry
from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.provider.inprocess import InProcessProviderSocket
from hocuspocus_tpu.server import OverloadExtension, RequestInfo
from hocuspocus_tpu.server.overload import (
    BROWNOUT1,
    BROWNOUT2,
    GREEN,
    RED,
    OverloadController,
    TokenBucket,
    get_overload_controller,
    resolve_tenant,
)

from tests.utils import (
    new_hocuspocus,
    new_provider,
    new_provider_websocket,
    retryable_assertion,
    wait_synced,
)


def _assert(cond):
    assert cond


@pytest.fixture(autouse=True)
def _reset_controller():
    """The controller is process-global: every test starts and ends at
    a cold, disabled GREEN."""
    controller = get_overload_controller()
    controller.reset()
    controller.disable()
    yield
    controller.reset()
    controller.disable()


# -- token bucket / tenancy ---------------------------------------------------


def test_token_bucket_refill_and_burst():
    bucket = TokenBucket(rate=10.0, burst=2)
    now = time.monotonic()
    assert bucket.take(now=now)
    assert bucket.take(now=now)
    assert not bucket.take(now=now)  # burst exhausted
    assert bucket.peek(now=now + 0.2)  # refilled ~2 tokens
    assert bucket.take(now=now + 0.2)
    # rate<=0 is unlimited
    assert all(TokenBucket(0, 1).take() for _ in range(100))


def test_resolve_tenant_precedence():
    assert resolve_tenant() == "default"
    assert resolve_tenant(headers={"x-tenant": "acme"}) == "acme"
    assert resolve_tenant(parameters={"tenant": "qp"}) == "qp"
    assert (
        resolve_tenant(context={"tenant": "ctx"}, headers={"x-tenant": "h"})
        == "ctx"
    )
    request = RequestInfo(headers={"x-tenant": "hdr"}, url="/?tenant=qp")
    assert resolve_tenant(request=request) == "hdr"


# -- the ladder ---------------------------------------------------------------


def test_ladder_escalates_immediately_and_descends_one_rung_per_hold():
    controller = OverloadController()
    controller.configure(hold_s=0.05).enable()
    controller.inject_pressure(3)
    assert controller.rung == RED, "escalation must be immediate"
    controller.inject_pressure(0)
    assert controller.rung == RED, "de-escalation must wait out the hold"
    rungs = [controller.rung]
    for _ in range(3):
        time.sleep(0.06)
        controller.sample()
        rungs.append(controller.rung)
    assert rungs == [RED, BROWNOUT2, BROWNOUT1, GREEN], rungs
    # the transition history is the monotonic descent, no flapping
    path = [(t["from_rung"], t["to_rung"]) for t in controller.transitions]
    assert path == [
        ("green", "red"),
        ("red", "brownout2"),
        ("brownout2", "brownout1"),
        ("brownout1", "green"),
    ]


def test_ladder_oscillating_signal_never_flaps():
    """A signal bouncing across the BROWNOUT-1 threshold within the
    hold window must hold the rung steady (the hysteresis guarantee)."""
    controller = OverloadController()
    controller.configure(hold_s=10.0).enable()
    for _ in range(20):
        controller.inject_pressure(1)  # at threshold
        controller.inject_pressure(0.5)  # below it (hold re-arms)
    assert controller.rung == BROWNOUT1
    assert len(controller.transitions) == 1, "one escalation, zero flaps"


def test_connect_quota_isolated_per_tenant():
    """Tenant A exhausting its connect bucket cannot starve tenant B."""
    controller = OverloadController()
    controller.configure(connect_rate=0.001, connect_burst=2).enable()
    assert controller.admit_connect("a") is None
    assert controller.admit_connect("a") is None
    assert controller.admit_connect("a") == "tenant-quota"
    # B's bucket is untouched
    assert controller.admit_connect("b") is None
    # upgrade-path PEEK does not consume B's remaining budget...
    assert controller.admit_upgrade("b") is None
    assert controller.admit_connect("b") is None  # ...so this still admits
    assert controller.admit_upgrade("a") == "tenant-quota"


# -- connect/auth admission through the real handshake ------------------------


async def _join(server, name, tenant):
    """Attach a provider under `tenant`; returns (provider, socket,
    outcome) where outcome is 'synced' or 'denied'."""
    socket = InProcessProviderSocket(
        server, request=RequestInfo(headers={"x-tenant": tenant})
    )
    provider = HocuspocusProvider(name=name, websocket_provider=socket)
    denied = asyncio.Event()
    provider.on("authentication_failed", lambda *a: denied.set())
    provider.attach()
    for _ in range(500):
        if provider.synced:
            return provider, socket, "synced"
        if denied.is_set():
            return provider, socket, "denied"
        await asyncio.sleep(0.01)
    return provider, socket, "timeout"


async def test_tenant_quota_rejects_without_starving_other_tenants():
    server = await new_hocuspocus(
        extensions=[OverloadExtension(connect_rate=0.001, connect_burst=2)]
    )
    cleanup = []
    try:
        outcomes_a = []
        for i in range(3):
            provider, socket, outcome = await _join(server, f"doc-a{i}", "a")
            cleanup.append((provider, socket))
            outcomes_a.append(outcome)
        assert outcomes_a == ["synced", "synced", "denied"]
        # tenant B joins fine AFTER A was refused
        provider, socket, outcome = await _join(server, "doc-b", "b")
        cleanup.append((provider, socket))
        assert outcome == "synced"
        controller = get_overload_controller()
        assert controller.rejected_total.value(
            scope="connect", reason="tenant_quota"
        ) == 1
    finally:
        for provider, socket in cleanup:
            provider.destroy()
            socket.destroy()
        await server.destroy()


async def test_red_refuses_new_channels_but_keeps_existing_ones():
    server = await new_hocuspocus(extensions=[OverloadExtension()])
    cleanup = []
    try:
        provider, socket, outcome = await _join(server, "doc-ok", "t")
        cleanup.append((provider, socket))
        assert outcome == "synced"
        get_overload_controller().inject_pressure(3)  # RED
        provider2, socket2, outcome2 = await _join(server, "doc-red", "t")
        cleanup.append((provider2, socket2))
        assert outcome2 == "denied"
        # the established channel keeps working at RED (admitted work
        # is never shed)
        text = provider.document.get_text("t")
        text.insert(0, "still-served")
        await retryable_assertion(
            lambda: _assert(
                server.hocuspocus.documents["doc-ok"]
                .get_text("t")
                .to_string()
                == "still-served"
            )
        )
    finally:
        for provider, socket in cleanup:
            provider.destroy()
            socket.destroy()
        await server.destroy()


# -- the shared 503 + Retry-After rejection -----------------------------------


async def _upgrade_503(server) -> "tuple[int, str]":
    """Attempt a websocket upgrade; returns (status, retry_after)."""
    async with aiohttp.ClientSession() as session:
        try:
            ws = await session.ws_connect(server.web_socket_url)
        except aiohttp.WSServerHandshakeError as error:
            return error.status, error.headers.get("Retry-After", "")
        await ws.close()
        return 101, ""


async def test_red_and_drain_emit_identical_503_rejections():
    """The satellite contract: RED-state admission and Server.drain()
    share one rejection helper — same status, same Retry-After."""
    server = await new_hocuspocus(extensions=[OverloadExtension()])
    try:
        get_overload_controller().inject_pressure(3)
        red_status, red_retry = await _upgrade_503(server)
        assert (red_status, red_retry) == (503, "1")
        controller = get_overload_controller()
        assert controller.rejected_total.value(scope="upgrade", reason="red") == 1
        get_overload_controller().inject_pressure(0)
        controller.reset()  # back to GREEN so only drain rejects below
        await server.drain(timeout_secs=0.5)
        drain_status, drain_retry = await _upgrade_503(server)
        assert (drain_status, drain_retry) == (red_status, red_retry)
        assert (
            controller.rejected_total.value(scope="upgrade", reason="draining")
            == 1
        )
    finally:
        await server.destroy()


async def test_provider_backoff_ladder_keeps_climbing_across_503s():
    """Repeated 503s must climb the reconnect ladder — no
    thundering-herd re-dial at a fixed floor (the PR-9 flap ladder
    extended to quota rejections)."""
    server = await new_hocuspocus(extensions=[OverloadExtension()])
    get_overload_controller().inject_pressure(3)  # RED: every upgrade 503s
    socket = new_provider_websocket(server)
    attempts: list[int] = []

    def recording_backoff(attempt: int) -> float:
        attempts.append(attempt)
        return 0.01

    socket._backoff_delay = recording_backoff
    provider = HocuspocusProvider(name="doc-backoff", websocket_provider=socket)
    try:
        provider.attach()
        await retryable_assertion(lambda: _assert(len(attempts) >= 4))
        # strictly climbing: each consecutive failure raises the ladder
        assert attempts == sorted(attempts)
        assert attempts[-1] > attempts[0]
        assert not provider.synced
    finally:
        provider.destroy()
        socket.destroy()
        await server.destroy()


# -- message-ingress enforcement ----------------------------------------------


async def test_ingress_quota_closes_1013_at_red():
    wire = get_wire_telemetry()
    wire.enable()
    closes_before = wire.channel_closes.value(code="1013")
    server = await new_hocuspocus(
        extensions=[OverloadExtension(message_rate=0.001, message_burst=3)]
    )
    provider, socket, outcome = await _join(server, "doc-ingress", "t")
    try:
        assert outcome == "synced"
        controller = get_overload_controller()
        controller.inject_pressure(3)  # RED
        # burn through the burst: each edit ships at least one frame
        for i in range(8):
            provider.document.get_text("t").insert(0, "x")
            await asyncio.sleep(0.01)
        await retryable_assertion(
            lambda: _assert(
                wire.channel_closes.value(code="1013") > closes_before
            )
        )
        assert controller.rejected_total.value(
            scope="message", reason="tenant_quota"
        ) > 0
    finally:
        provider.destroy()
        socket.destroy()
        await server.destroy()


# -- brownout fan-out behaviors -----------------------------------------------


async def test_brownout2_elides_awareness_fanout():
    server = await new_hocuspocus(extensions=[OverloadExtension()])
    provider_a, socket_a, _ = await _join(server, "doc-aw", "t")
    provider_b, socket_b, _ = await _join(server, "doc-aw", "t")
    try:
        controller = get_overload_controller()
        shed_before = controller.shed_total.value(reason="awareness_elided")
        controller.inject_pressure(2)  # BROWNOUT-2
        provider_a.set_awareness_field("cursor", {"pos": 1})
        await retryable_assertion(
            lambda: _assert(
                controller.shed_total.value(reason="awareness_elided")
                > shed_before
            )
        )
        # de-escalate and prove presence reconverges
        controller.inject_pressure(0)
        controller.reset()
        controller.enable()
        provider_a.set_awareness_field("cursor", {"pos": 2})

        def b_sees_cursor():
            states = provider_b.awareness.get_states()
            _assert(
                any(
                    (state or {}).get("cursor") == {"pos": 2}
                    for state in states.values()
                )
            )

        await retryable_assertion(b_sees_cursor)
    finally:
        for provider, socket in (
            (provider_a, socket_a),
            (provider_b, socket_b),
        ):
            provider.destroy()
            socket.destroy()
        await server.destroy()


async def test_brownout1_stretches_awareness_tick():
    server = await new_hocuspocus(extensions=[OverloadExtension()])
    provider_a, socket_a, _ = await _join(server, "doc-st", "t")
    provider_b, socket_b, _ = await _join(server, "doc-st", "t")
    try:
        controller = get_overload_controller()
        stretched_before = controller.shed_total.value(
            reason="awareness_stretched"
        )
        controller.inject_pressure(1)  # BROWNOUT-1
        provider_a.set_awareness_field("cursor", {"pos": 9})
        await retryable_assertion(
            lambda: _assert(
                controller.shed_total.value(reason="awareness_stretched")
                > stretched_before
            )
        )

        # the stretched tick still DELIVERS (deferred, not dropped)
        def b_sees_cursor():
            states = provider_b.awareness.get_states()
            _assert(
                any(
                    (state or {}).get("cursor") == {"pos": 9}
                    for state in states.values()
                )
            )

        await retryable_assertion(b_sees_cursor)
    finally:
        for provider, socket in (
            (provider_a, socket_a),
            (provider_b, socket_b),
        ):
            provider.destroy()
            socket.destroy()
        await server.destroy()


async def test_brownout2_defers_catchup_exit_until_pressure_eases():
    """A catch-up tier drain at BROWNOUT-2 must stay in elision and
    retry; the exit proceeds once the ladder descends."""
    from hocuspocus_tpu.server.document import Document
    from hocuspocus_tpu.server.fanout import CatchupTier

    controller = get_overload_controller()
    controller.configure(hold_s=0.02, catchup_retry_s=0.05).enable()
    document = Document("catchup-doc")

    class _Transport:
        is_closed = False

    class _Conn:
        transport = _Transport()

    connection = _Conn()
    connection.document = document
    tier = CatchupTier(connection)
    tier.active = True
    controller.inject_pressure(2)  # BROWNOUT-2
    deferred_before = controller.shed_total.value(reason="catchup_deferred")
    tier._on_drain()
    assert tier.active, "exit must be deferred at BROWNOUT-2"
    assert (
        controller.shed_total.value(reason="catchup_deferred")
        > deferred_before
    )
    assert tier._retry_handle is not None
    controller.inject_pressure(0)
    for _ in range(3):
        await asyncio.sleep(0.03)
        controller.sample()
    await retryable_assertion(lambda: _assert(not tier.active), timeout=3)


# -- health / debug surfaces --------------------------------------------------


async def test_healthz_always_200_and_carries_rung_plus_shed_reasons():
    """The repo-wide /healthz convention: degraded still answers 200 —
    the body carries the ladder rung and active shed reasons."""
    from hocuspocus_tpu.observability import Metrics

    server = await new_hocuspocus(
        extensions=[Metrics(), OverloadExtension()]
    )
    try:
        controller = get_overload_controller()
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/healthz") as response:
                assert response.status == 200
                body = await response.json()
                assert body["status"] == "ok"
            controller.inject_pressure(2)
            controller.shed("awareness_elided")
            async with session.get(f"{server.http_url}/healthz") as response:
                assert response.status == 200, "degraded must still be 200"
                body = await response.json()
                assert body["status"] == "degraded"
                section = body["extensions"]["OverloadExtension"]
                assert section["rung"] == 2
                assert section["state"] == "brownout2"
                assert "awareness_elided" in section["shed_reasons"]
            async with session.get(f"{server.http_url}/debug/slo") as response:
                assert response.status == 200
                body = await response.json()
                assert body["overload"]["state"] == "brownout2"
                assert body["overload"]["signals"]["injected"]["rung"] == 2
    finally:
        await server.destroy()


async def test_overload_metrics_exposed():
    from hocuspocus_tpu.observability import Metrics

    server = await new_hocuspocus(extensions=[Metrics(), OverloadExtension()])
    try:
        get_overload_controller().inject_pressure(1)
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                text = await response.text()
        assert "hocuspocus_overload_state 1" in text
        assert 'hocuspocus_overload_transitions_total{' in text
        assert 'hocuspocus_overload_signal{signal="injected"}' in text
    finally:
        await server.destroy()


async def test_soft_quota_drop_heals_via_sync_step1():
    """Below RED an over-quota frame is dropped but never silently:
    the server answers with a rate-limited SyncStep1, the client's
    Step2 reply re-offers what the drops lost, and the document
    reconverges once the bucket refills."""
    server = await new_hocuspocus(
        extensions=[OverloadExtension(message_rate=5.0, message_burst=2)]
    )
    provider, socket, outcome = await _join(server, "doc-heal", "t")
    try:
        assert outcome == "synced"
        text = provider.document.get_text("t")
        # burst well past the bucket at GREEN: some frames are dropped
        for i in range(8):
            text.insert(len(text), chr(ord("a") + i))
            await asyncio.sleep(0.005)
        controller = get_overload_controller()
        await retryable_assertion(
            lambda: _assert(
                controller.shed_total.value(reason="messages_throttled") > 0
            )
        )
        # the heal exchange recovers every dropped edit server-side
        await retryable_assertion(
            lambda: _assert(
                server.hocuspocus.documents["doc-heal"]
                .get_text("t")
                .to_string()
                == "abcdefgh"
            ),
            timeout=15,
        )
    finally:
        provider.destroy()
        socket.destroy()
        await server.destroy()


async def test_fanout_close_with_parked_awareness_timer_unwedges():
    """close() while an awareness-stretch timer is parked must reset
    the tick flag — a straggler enqueue racing destroy would otherwise
    park forever behind a cancelled timer."""
    from hocuspocus_tpu.server.document import Document

    controller = get_overload_controller()
    controller.configure(awareness_stretch_ms=5000.0).enable()
    controller.inject_pressure(1)  # BROWNOUT-1: awareness ticks park
    document = Document("fanout-close-doc")
    fanout = document.fanout
    fanout.queue_awareness([1])
    assert fanout._delay_handle is not None
    assert fanout._scheduled
    fanout.close()
    assert fanout._delay_handle is None
    assert not fanout._scheduled, "a cancelled parked tick must not wedge"
    document.destroy()
