"""CLI smoke test: boot the binary, connect a provider, shut down."""

import asyncio
import os
import signal
import sys

from hocuspocus_tpu.provider import HocuspocusProvider
from tests.utils import wait_for


async def test_cli_serves_connections(tmp_path, unused_tcp_port=None):
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    process = await asyncio.create_subprocess_exec(
        sys.executable,
        "-m",
        "hocuspocus_tpu.cli",
        "--port",
        str(port),
        "--host",
        "127.0.0.1",
        "--sqlite",
        str(tmp_path / "cli.db"),
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )
    provider = None
    try:
        provider = HocuspocusProvider(name="cli-doc", url=f"ws://127.0.0.1:{port}")
        await wait_for(lambda: provider.synced, timeout=20)
        provider.document.get_text("t").insert(0, "via cli")
        await wait_for(lambda: not provider.has_unsynced_changes, timeout=10)
    finally:
        if provider is not None:
            provider.destroy()
        process.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(process.wait(), 10)
        except asyncio.TimeoutError:
            process.kill()
