"""CLI smoke test: boot the binary, connect a provider, shut down."""

import asyncio
import contextlib
import os
import signal
import socket
import sys

from hocuspocus_tpu.provider import HocuspocusProvider
from tests.utils import wait_for


@contextlib.asynccontextmanager
async def _launch_cli(*extra_args: str):
    """Boot `python -m hocuspocus_tpu.cli` on a free port; yield the port."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    process = await asyncio.create_subprocess_exec(
        sys.executable,
        "-m",
        "hocuspocus_tpu.cli",
        "--port",
        str(port),
        "--host",
        "127.0.0.1",
        *extra_args,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )
    try:
        yield port
    finally:
        process.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(process.wait(), 10)
        except asyncio.TimeoutError:
            process.kill()


async def test_cli_serves_connections(tmp_path):
    async with _launch_cli("--sqlite", str(tmp_path / "cli.db")) as port:
        provider = None
        try:
            provider = HocuspocusProvider(name="cli-doc", url=f"ws://127.0.0.1:{port}")
            await wait_for(lambda: provider.synced, timeout=20)
            provider.document.get_text("t").insert(0, "via cli")
            await wait_for(lambda: not provider.has_unsynced_changes, timeout=10)
        finally:
            if provider is not None:
                provider.destroy()


async def test_cli_tpu_serve_mode():
    """--tpu-serve boots a serve-mode plane; two providers converge
    through plane broadcasts over the CLI-launched server."""
    async with _launch_cli(
        "--tpu-serve", "--tpu-docs", "64", "--tpu-capacity", "512",
        "--tpu-flush-interval", "1", "--tpu-broadcast-interval", "1"
    ) as port:
        a = b = None
        try:
            a = HocuspocusProvider(name="cli-tpu", url=f"ws://127.0.0.1:{port}")
            b = HocuspocusProvider(name="cli-tpu", url=f"ws://127.0.0.1:{port}")
            await wait_for(lambda: a.synced and b.synced, timeout=30)
            a.document.get_text("t").insert(0, "served by the plane")
            await wait_for(
                lambda: b.document.get_text("t").to_string() == "served by the plane",
                timeout=20,
            )
        finally:
            for p in (a, b):
                if p is not None:
                    p.destroy()


async def test_cli_trace_flags_serve_debug_endpoints():
    """--trace boots the server with lifecycle tracing + the metrics
    extension: a client edit becomes a causally-linked trace at
    /debug/trace and per-stage e2e histograms on /metrics."""
    import json

    import aiohttp

    async with _launch_cli(
        "--tpu-serve", "--tpu-docs", "16", "--tpu-capacity", "512",
        "--tpu-flush-interval", "1", "--tpu-broadcast-interval", "1",
        "--trace", "--trace-max-spans", "1024", "--trace-sample", "1",
    ) as port:
        provider = None
        try:
            provider = HocuspocusProvider(
                name="cli-traced", url=f"ws://127.0.0.1:{port}"
            )
            await wait_for(lambda: provider.synced, timeout=30)
            provider.document.get_text("t").insert(0, "trace via cli")
            await wait_for(lambda: not provider.has_unsynced_changes, timeout=10)

            async def traced() -> bool:
                async with aiohttp.ClientSession() as session:
                    async with session.get(
                        f"http://127.0.0.1:{port}/debug/trace"
                    ) as response:
                        if response.status != 200:
                            return False
                        trace = json.loads(await response.text())
                return any(
                    e["name"] == "update.broadcast"
                    for e in trace.get("traceEvents", [])
                )

            import asyncio as _asyncio

            # keep editing while we poll: the CLI boots the SUPERVISED
            # plane, so an edit landing before the runtime hot-attaches
            # rides the CPU path untraced — later edits get captured
            # (and stamped) once the plane is READY
            ok = False
            for attempt in range(120):
                if await traced():
                    ok = True
                    break
                if attempt % 5 == 4:
                    provider.document.get_text("t").insert(0, "x")
                await _asyncio.sleep(0.25)
            assert ok

            async with aiohttp.ClientSession() as session:
                async with session.get(
                    f"http://127.0.0.1:{port}/metrics"
                ) as response:
                    body = await response.text()
            assert "hocuspocus_tpu_update_e2e_seconds_bucket" in body
            assert 'stage="total"' in body
        finally:
            if provider is not None:
                provider.destroy()


async def test_cli_sharded_serve_flags():
    """--tpu-shards/--tpu-arena boot the doc-partitioned serve-mode
    server from the CLI; docs on different shards converge end to end."""
    async with _launch_cli(
        "--tpu-serve", "--tpu-shards", "2", "--tpu-arena", "rle",
        "--tpu-docs", "16", "--tpu-capacity", "512",
        "--tpu-flush-interval", "1", "--tpu-broadcast-interval", "1",
    ) as port:
        providers = []
        try:
            for d in range(4):
                w = HocuspocusProvider(name=f"shard-{d}", url=f"ws://127.0.0.1:{port}")
                r = HocuspocusProvider(name=f"shard-{d}", url=f"ws://127.0.0.1:{port}")
                providers += [w, r]
            await wait_for(lambda: all(p.synced for p in providers), timeout=40)
            for d in range(4):
                providers[2 * d].document.get_text("t").insert(0, f"doc {d} content")
            await wait_for(
                lambda: all(
                    providers[2 * d + 1].document.get_text("t").to_string()
                    == f"doc {d} content"
                    for d in range(4)
                ),
                timeout=25,
            )
        finally:
            for p in providers:
                p.destroy()


async def test_cli_wal_and_drain_flags(tmp_path):
    """--wal-dir boots the durability plane; SIGTERM drains: dirty docs
    are stored before exit, so a cold reboot serves the edits even with
    a debounce window that never fired."""
    wal_dir = str(tmp_path / "wal")
    db = str(tmp_path / "cli-wal.db")
    async with _launch_cli(
        "--wal-dir", wal_dir, "--sqlite", db, "--drain-timeout-secs", "5"
    ) as port:
        provider = None
        try:
            provider = HocuspocusProvider(
                name="wal-cli-doc", url=f"ws://127.0.0.1:{port}"
            )
            await wait_for(lambda: provider.synced, timeout=20)
            provider.document.get_text("t").insert(0, "drained durably")
            await wait_for(lambda: not provider.has_unsynced_changes, timeout=10)
            await asyncio.sleep(0.2)  # let the WAL group commit land
        finally:
            if provider is not None:
                provider.destroy()
    # the context manager SIGTERMed the process: drain stored the doc
    async with _launch_cli("--wal-dir", wal_dir, "--sqlite", db) as port:
        reader = None
        try:
            reader = HocuspocusProvider(
                name="wal-cli-doc", url=f"ws://127.0.0.1:{port}"
            )
            await wait_for(lambda: reader.synced, timeout=20)
            await wait_for(
                lambda: str(reader.document.get_text("t")) == "drained durably",
                timeout=10,
            )
        finally:
            if reader is not None:
                reader.destroy()
