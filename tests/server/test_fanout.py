"""Broadcast fan-out engine (server/fanout.py): per-tick coalescing,
catch-up tiering, batched transport drains, shared frames.

The acceptance bar is CONVERGENCE EQUIVALENCE: coalesced + tiered
delivery must yield byte-identical document state to per-frame
delivery for every client — including clients that entered catch-up
mode mid-burst — while sending strictly fewer frames.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    encode_state_as_update,
)
from hocuspocus_tpu.crdt.encoding import Decoder
from hocuspocus_tpu.observability.wire import get_wire_telemetry
from hocuspocus_tpu.protocol.frames import parse_frame_header
from hocuspocus_tpu.protocol.message import MessageType
from hocuspocus_tpu.protocol.sync import (
    MESSAGE_YJS_SYNC_STEP2,
    MESSAGE_YJS_UPDATE,
    coalesce_updates,
)
from hocuspocus_tpu.server.connection import Connection
from hocuspocus_tpu.server.document import Document
from hocuspocus_tpu.server.transports import CallbackWebSocketTransport


def _apply_frame(doc: Doc, data: bytes) -> None:
    """Apply a server broadcast frame to a client-side doc (ignores
    awareness/stateless frames)."""
    _name, mtype, offset = parse_frame_header(data)
    if mtype not in (int(MessageType.Sync), int(MessageType.SyncReply)):
        return
    decoder = Decoder(data)
    decoder.pos = offset
    sub = decoder.read_var_uint()
    if sub in (MESSAGE_YJS_UPDATE, MESSAGE_YJS_SYNC_STEP2):
        apply_update(doc, decoder.read_var_uint8_array())


class FakeClient:
    """A real Connection + CallbackWebSocketTransport whose writer
    applies delivered frames to a client-side Doc. `gate` (when given)
    blocks the writer — the slow-consumer lever."""

    def __init__(self, document: Document, gate: asyncio.Event = None) -> None:
        self.doc = Doc()
        self.gate = gate
        self.frames: list[bytes] = []
        self.update_frames = 0

        async def send_async(data: bytes) -> None:
            if self.gate is not None:
                await self.gate.wait()
            self.frames.append(data)
            _name, mtype, _ = parse_frame_header(data)
            if mtype in (int(MessageType.Sync), int(MessageType.SyncReply)):
                self.update_frames += 1
            _apply_frame(self.doc, data)

        async def close_async(code: int, reason: str) -> None:
            pass

        self.transport = CallbackWebSocketTransport(send_async, close_async)
        self.connection = Connection(
            self.transport, None, document, f"sock-{id(self)}", {}
        )

    async def drained(self) -> None:
        while not self.transport.queue.empty():
            await asyncio.sleep(0.001)


@pytest.fixture
def low_watermark():
    wire = get_wire_telemetry()
    old = wire.backpressure_watermark
    wire.backpressure_watermark = 4
    yield wire
    wire.backpressure_watermark = old


# -- coalescing ------------------------------------------------------------


async def test_burst_coalesces_to_one_frame_per_tick():
    """N same-tick updates -> ONE update frame per connection, shared
    as the same bytes object across the audience."""
    document = Document("coalesce")
    clients = [FakeClient(document) for _ in range(3)]
    text = document.get_text("t")
    for i in range(5):
        text.insert(len(text), f"chunk-{i} ")
    await asyncio.sleep(0)  # tick flush
    for client in clients:
        await client.drained()
    for client in clients:
        assert client.update_frames == 1, "burst must coalesce to one frame"
        assert client.doc.get_text("t").to_string() == text.to_string()
    # the SAME frame object fans out to the whole audience (encode once)
    frames = {id(client.frames[-1]) for client in clients}
    assert len(frames) == 1


async def test_audience_snapshot_taken_once_per_tick():
    """One tick carrying updates AND awareness copies the registry
    exactly once."""
    document = Document("snapshot")
    FakeClient(document)
    calls = {"n": 0}
    real = document.get_connections

    def counting():
        calls["n"] += 1
        return real()

    document.get_connections = counting
    document.get_text("t").insert(0, "hello")
    document.awareness.set_local_state({"user": "a"})
    await asyncio.sleep(0)
    assert calls["n"] == 1, "update + awareness passes must share one snapshot"


async def test_broadcast_stateless_builds_frame_once():
    document = Document("stateless")
    clients = [FakeClient(document) for _ in range(4)]
    document.broadcast_stateless("server-push")
    for client in clients:
        await client.drained()
    payloads = [client.frames[-1] for client in clients]
    assert all(p is payloads[0] for p in payloads), "one shared frame object"
    _name, mtype, _ = parse_frame_header(payloads[0])
    assert mtype == int(MessageType.Stateless)


def test_coalesce_updates_merge_failure_returns_none():
    assert coalesce_updates([b"\x00garbage", b"\x01junk"]) is None


def test_no_loop_flush_is_immediate():
    """Direct/test use without a running loop: broadcast is synchronous
    (the old Document behavior)."""
    document = Document("direct")
    received = []

    class Conn:
        transport = object()

        def send(self, data):
            received.append(data)

    document.connections[Conn.transport] = {"clients": set(), "connection": Conn()}
    document.get_text("t").insert(0, "x")
    assert received, "no-loop path must fan out immediately"


# -- batched transport drains ---------------------------------------------


async def test_writer_drains_whole_queue_per_wake_as_batch():
    batches = []
    release = asyncio.Event()

    async def send_batch(frames):
        await release.wait()
        batches.append(list(frames))

    async def close_async(code, reason):
        pass

    transport = CallbackWebSocketTransport(
        lambda data: None, close_async, send_batch_async=send_batch
    )
    for i in range(6):
        transport.send(b"frame-%d" % i)
    release.set()
    await asyncio.sleep(0.01)
    # first wake may catch 1..6 frames; the union must be everything
    # and the batch count strictly less than the frame count
    assert sum(len(b) for b in batches) == 6
    assert len(batches) < 6
    transport.abort()


async def test_bounded_queue_overflow_closes_transport():
    wire = get_wire_telemetry()
    before = sum(wire.send_queue_overflows._values.values())
    closed = {}
    gate = asyncio.Event()

    async def send_async(data):
        await gate.wait()

    async def close_async(code, reason):
        closed["code"] = code
        closed["reason"] = reason

    transport = CallbackWebSocketTransport(send_async, close_async, max_queue=8)
    for i in range(20):
        transport.send(b"x" * 4)
    assert transport.is_closed, "overflow policy must close the transport"
    after = sum(wire.send_queue_overflows._values.values())
    assert after == before + 1
    gate.set()
    await asyncio.sleep(0.05)
    assert closed["code"] == 1013


async def test_drain_listener_fires_once_after_queue_empties():
    fired = []

    async def send_async(data):
        pass

    async def close_async(code, reason):
        pass

    transport = CallbackWebSocketTransport(send_async, close_async)
    transport.add_drain_listener(lambda: fired.append(1))
    transport.send(b"a")
    transport.send(b"b")
    await asyncio.sleep(0.05)
    assert fired == [1], "one-shot: exactly one notification"
    transport.send(b"c")
    await asyncio.sleep(0.05)
    assert fired == [1], "must re-register for another notification"
    transport.abort()


# -- catch-up tiering ------------------------------------------------------


async def test_slow_consumer_enters_and_exits_catchup_tier(low_watermark):
    """A stalled socket crosses the watermark -> tier entry (frames
    elided); on drain -> ONE SV-diff frame heals it."""
    document = Document("tier")
    gate = asyncio.Event()  # starts unset: writer stalls immediately
    slow = FakeClient(document, gate=gate)
    fast = FakeClient(document)
    text = document.get_text("t")
    for i in range(12):
        text.insert(len(text), f"word{i} ")
        await asyncio.sleep(0)  # one tick per update: 12 frames
    assert slow.connection.catchup.active, "watermark crossing must enter tier"
    queued_at_entry = slow.transport.queue.qsize()
    # while tiered, further broadcasts are elided for the slow socket
    for i in range(10):
        text.insert(len(text), f"late{i} ")
        await asyncio.sleep(0)
    assert slow.transport.queue.qsize() <= queued_at_entry + 1
    gate.set()  # socket recovers
    for _ in range(500):
        await asyncio.sleep(0.002)
        if not slow.connection.catchup.active and slow.transport.queue.empty():
            break
    assert not slow.connection.catchup.active, "drain must exit the tier"
    await fast.drained()
    await asyncio.sleep(0.01)
    server_bytes = encode_state_as_update(document)
    assert encode_state_as_update(slow.doc) == server_bytes
    assert encode_state_as_update(fast.doc) == server_bytes
    # the catch-up frame replaced the elided stream: far fewer frames
    assert slow.update_frames < fast.update_frames


async def test_tier_exit_covers_updates_whose_frames_never_fanned_out(low_watermark):
    """Regression: updates applied to the document but whose broadcast
    frames trail (plane-captured, window deferred to the flush timer)
    must still reach a tiered connection. A diff from an entry-time
    document SV would omit them forever; the full-state catch-up frame
    cannot."""

    class CapturingSource:
        """Plane stand-in: claims every update (suppressing CPU
        fan-out), never broadcasts — the worst-case deferral."""

        def try_capture(self, document, update, origin):
            return True

    document = Document("deferred")
    gate = asyncio.Event()
    slow = FakeClient(document, gate=gate)
    text = document.get_text("t")
    # stream enough frames to cross the watermark and enter the tier
    for i in range(10):
        text.insert(len(text), f"w{i} ")
        await asyncio.sleep(0)
    assert slow.connection.catchup.active
    # now an update lands that is CAPTURED (no frame ever fans out)
    document.broadcast_source = CapturingSource()
    text.insert(len(text), "CAPTURED-NEVER-BROADCAST ")
    await asyncio.sleep(0)
    document.broadcast_source = None
    gate.set()
    for _ in range(500):
        await asyncio.sleep(0.002)
        if (
            not slow.connection.catchup.active
            and slow.transport.queue.empty()
            and slow.connection.catchup._exit_task is None
        ):
            break
    assert encode_state_as_update(slow.doc) == encode_state_as_update(document)
    assert "CAPTURED-NEVER-BROADCAST" in slow.doc.get_text("t").to_string()


async def test_tier_counts_transitions(low_watermark):
    wire = get_wire_telemetry()
    wire.enable()
    try:
        entries0 = wire.catchup_tier_transitions.value(transition="enter")
        exits0 = wire.catchup_tier_transitions.value(transition="exit")
        document = Document("tier-count")
        gate = asyncio.Event()
        slow = FakeClient(document, gate=gate)
        text = document.get_text("t")
        for i in range(10):
            text.insert(len(text), "x" * 8)
            await asyncio.sleep(0)
        assert slow.connection.catchup.active
        gate.set()
        for _ in range(500):
            await asyncio.sleep(0.002)
            if not slow.connection.catchup.active:
                break
        assert wire.catchup_tier_transitions.value(transition="enter") == entries0 + 1
        assert wire.catchup_tier_transitions.value(transition="exit") == exits0 + 1
    finally:
        wire.disable()


# -- the convergence fuzz (acceptance criterion) ---------------------------


async def test_fuzz_coalesced_and_tiered_delivery_converges(low_watermark):
    """N clients under random bursty writes — one flapping into/out of
    catch-up tier mid-stream, one control applying every raw update
    per-frame — all converge to byte-identical state."""
    rng = random.Random(1234)
    document = Document("fuzz")
    gate = asyncio.Event()
    gate.set()
    clients = [FakeClient(document) for _ in range(5)]
    slow = FakeClient(document, gate=gate)

    # per-frame control: byte-identical convergence proves coalesced
    # delivery equivalent to the reference's per-update fan-out
    control = Doc()
    document.on(
        "update", lambda update, origin, doc, txn: apply_update(control, update)
    )

    text = document.get_text("t")
    for rnd in range(60):
        for _ in range(rng.randint(1, 5)):  # same-tick burst
            pos = rng.randint(0, len(text))
            text.insert(pos, rng.choice("abcdefgh") * rng.randint(1, 4))
            if len(text) > 6 and rng.random() < 0.35:
                text.delete(rng.randint(0, len(text) - 3), rng.randint(1, 2))
        if rnd in (10, 35):
            gate.clear()  # stall mid-burst -> tier entry
        if rnd in (25, 50):
            gate.set()  # recover -> SV-diff catch-up
        await asyncio.sleep(0)
        if rng.random() < 0.3:
            await asyncio.sleep(0)  # vary tick boundaries
    gate.set()
    for _ in range(1000):
        await asyncio.sleep(0.002)
        if (
            all(c.transport.queue.empty() for c in clients + [slow])
            and not slow.connection.catchup.active
        ):
            break

    server_bytes = encode_state_as_update(document)
    assert encode_state_as_update(control) == server_bytes
    for i, client in enumerate(clients + [slow]):
        assert encode_state_as_update(client.doc) == server_bytes, f"client {i}"
    assert slow.connection.catchup.active is False
    # coalescing saved real frames: every client saw fewer update
    # frames than raw updates were produced
    raw_updates = 60 * 3  # rough lower bound on average burst size
    assert clients[0].update_frames < raw_updates


async def test_plane_broadcast_rides_tick_and_closes_trace_at_last_enqueue():
    """Document.queue_broadcast defers to the tick and fires
    on_complete with the last-socket-enqueue timestamp."""
    import time

    document = Document("plane-tick")
    client = FakeClient(document)
    marks: list[float] = []
    update = None

    captured = []
    probe = Doc()
    probe.on("update", lambda u, *a: captured.append(u))
    probe.get_text("t").insert(0, "window")
    update = captured[0]

    t0 = time.perf_counter()
    document.queue_broadcast(update, on_complete=marks.append)
    assert not marks, "fan-out must defer to the tick, not run inline"
    await asyncio.sleep(0)
    assert len(marks) == 1 and marks[0] >= t0
    await client.drained()
    assert client.doc.get_text("t").to_string() == "window"
