"""E2E: connect, sync, broadcast — the minimum end-to-end slice."""

import asyncio

import pytest

from tests.utils import (
    EventCollector,
    wait_synced,
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_for,
)


async def test_provider_syncs_with_server():
    server = await new_hocuspocus()
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        assert provider.synced
        assert server.get_documents_count() == 1
    finally:
        provider.destroy()
        await server.destroy()


async def test_edit_propagates_between_two_providers():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    provider_b = new_provider(server)
    try:
        await wait_synced(provider_a, provider_b)

        provider_a.document.get_text("t").insert(0, "hello from A")
        await retryable_assertion(
            lambda: _assert_text(provider_b, "hello from A")
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


def _assert_text(provider, expected):
    assert provider.document.get_text("t").to_string() == expected


async def test_late_joiner_receives_existing_content():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    try:
        await wait_synced(provider_a)
        provider_a.document.get_text("t").insert(0, "existing")
        await asyncio.sleep(0.1)

        provider_b = new_provider(server)
        try:
            await wait_synced(provider_b)
            await retryable_assertion(lambda: _assert_text(provider_b, "existing"))
        finally:
            provider_b.destroy()
    finally:
        provider_a.destroy()
        await server.destroy()


async def test_concurrent_edits_converge():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    provider_b = new_provider(server)
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "AAA")
        provider_b.document.get_text("t").insert(0, "BBB")

        def converged():
            a = provider_a.document.get_text("t").to_string()
            b = provider_b.document.get_text("t").to_string()
            assert a == b and "AAA" in a and "BBB" in a

        await retryable_assertion(converged)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_unsynced_changes_acked():
    server = await new_hocuspocus()
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        assert provider.has_unsynced_changes
        await wait_for(lambda: not provider.has_unsynced_changes)
    finally:
        provider.destroy()
        await server.destroy()


async def test_document_count_and_connection_count():
    server = await new_hocuspocus()
    provider_a = new_provider(server, name="doc-1")
    provider_b = new_provider(server, name="doc-2")
    try:
        await wait_synced(provider_a, provider_b)
        assert server.get_documents_count() == 2
        assert server.get_connections_count() == 2
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_http_request_default_response():
    import aiohttp

    server = await new_hocuspocus()
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(server.http_url) as response:
                assert response.status == 200
                assert "hocuspocus" in (await response.text()).lower()
    finally:
        await server.destroy()


async def test_awareness_propagates():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    provider_b = new_provider(server)
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.set_awareness_field("user", {"name": "ada"})

        def b_sees_a():
            states = provider_b.awareness.get_states()
            assert any(
                state.get("user", {}).get("name") == "ada" for state in states.values()
            )

        await retryable_assertion(b_sees_a)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_awareness_burst_coalesces_to_one_frame_per_tick():
    """N awareness updates landing in one event-loop iteration fan out
    as ONE frame per connection carrying every changed client's current
    state (the reference re-encodes and sends per update)."""
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    server = await new_hocuspocus()
    providers = [new_provider(server, name="aware-burst") for _ in range(5)]
    observer = new_provider(server, name="aware-burst")
    try:
        await wait_synced(*providers, observer)
        document = server.documents["aware-burst"]
        sends = {"n": 0}
        real_flush = document.fanout.flush

        def counting_flush():
            sends["n"] += 1
            real_flush()

        document.fanout.flush = counting_flush

        # burst: each provider's awareness message arrives separately,
        # but several get applied within the same loop iterations
        for i, p in enumerate(providers):
            p.set_awareness_field("user", {"name": f"u{i}"})

        def all_seen():
            states = observer.awareness.get_states()
            names = {
                (state or {}).get("user", {}).get("name")
                for state in states.values()
            }
            assert {f"u{i}" for i in range(5)} <= names

        await retryable_assertion(all_seen)
        # coalescing bound: flushes can never exceed awareness events,
        # and the frame count must stay small (one per tick, not per
        # client-message retransmit)
        assert 1 <= sends["n"] <= 10, sends
    finally:
        for p in providers + [observer]:
            p.destroy()
        await server.destroy()
