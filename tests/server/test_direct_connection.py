"""DirectConnection: server-side in-process editing (reference
tests/server/openDirectConnection.ts patterns)."""

import asyncio

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_direct_connection_edits_and_stores():
    stores = []

    async def on_store_document(data):
        stores.append(data.socket_id)

    server = await new_hocuspocus(on_store_document=on_store_document)
    direct = await server.open_direct_connection("direct-doc", {"admin": True})
    try:
        await direct.transact(lambda doc: doc.get_text("t").insert(0, "from server"))
        # direct transact stores immediately (socket_id "server")
        assert stores == ["server"]
        assert server.documents["direct-doc"].get_text("t").to_string() == "from server"
    finally:
        await direct.disconnect()
        await server.destroy()


async def test_direct_connection_broadcasts_to_clients():
    server = await new_hocuspocus()
    provider = new_provider(server, name="shared")
    direct = await server.open_direct_connection("shared")
    try:
        await wait_synced(provider)
        await direct.transact(lambda doc: doc.get_text("t").insert(0, "server says hi"))
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text("t").to_string() == "server says hi"
            )
        )
    finally:
        await direct.disconnect()
        provider.destroy()
        await server.destroy()


async def test_direct_connection_disconnect_unloads():
    server = await new_hocuspocus()
    direct = await server.open_direct_connection("ephemeral")
    assert server.get_documents_count() == 1
    assert server.get_connections_count() == 1
    await direct.disconnect()
    await retryable_assertion(lambda: _assert(server.get_documents_count() == 0))
    assert server.get_connections_count() == 0
    await server.destroy()


async def test_direct_connection_counts_as_connection_keeping_doc_loaded():
    server = await new_hocuspocus()
    provider = new_provider(server, name="kept")
    direct = await server.open_direct_connection("kept")
    try:
        await wait_synced(provider)
        provider.destroy()
        await asyncio.sleep(0.3)
        # provider gone but the direct connection keeps the doc loaded
        assert server.get_documents_count() == 1
    finally:
        await direct.disconnect()
        await server.destroy()
