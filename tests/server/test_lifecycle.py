"""Server lifecycle hooks and resilience behaviors.

Completes the per-hook taxonomy of reference `tests/server/*`: onUpgrade,
onListen/onDestroy, afterLoadDocument, onAwarenessUpdate, address
properties, websocket-error resilience, and destroy() flush semantics.
"""

from __future__ import annotations

import asyncio

import aiohttp

from hocuspocus_tpu.server import Extension, Payload
from tests.utils import (
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_for,
    wait_synced,
)


async def test_on_listen_receives_port():
    ports = []

    async def on_listen(data):
        ports.append(data.port)

    server = await new_hocuspocus(on_listen=on_listen)
    try:
        assert ports == [server.port]
        assert server.port > 0
    finally:
        await server.destroy()


async def test_on_destroy_fires_once():
    events = []

    async def on_destroy(data):
        events.append("destroy")

    server = await new_hocuspocus(on_destroy=on_destroy)
    await server.destroy()
    assert events == ["destroy"]


async def test_on_upgrade_rejection_refuses_websocket():
    async def on_upgrade(data):
        raise ValueError("nope")

    server = await new_hocuspocus(on_upgrade=on_upgrade)
    try:
        async with aiohttp.ClientSession() as session:
            try:
                ws = await session.ws_connect(server.web_socket_url)
                await ws.close()
                raised = False
            except aiohttp.WSServerHandshakeError as error:
                raised = True
                assert error.status == 403
        assert raised
        assert server.get_connections_count() == 0
    finally:
        await server.destroy()


async def test_after_load_document_follows_on_load():
    order = []

    async def on_load_document(data):
        order.append("on_load")

    async def after_load_document(data):
        order.append("after_load")

    server = await new_hocuspocus(
        on_load_document=on_load_document, after_load_document=after_load_document
    )
    provider = new_provider(server, name="doc")
    try:
        await wait_synced(provider)
        assert order == ["on_load", "after_load"]
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_awareness_update_hook():
    updates = []

    async def on_awareness_update(data):
        updates.append((data.document_name, len(data.states)))

    server = await new_hocuspocus(on_awareness_update=on_awareness_update)
    provider = new_provider(server, name="aware-doc")
    try:
        await wait_synced(provider)
        provider.set_awareness_field("user", {"name": "alice"})
        await retryable_assertion(lambda: _assert(len(updates) > 0))
        assert updates[-1][0] == "aware-doc"
    finally:
        provider.destroy()
        await server.destroy()


async def test_server_address_properties():
    server = await new_hocuspocus()
    try:
        assert server.http_url.startswith("http://127.0.0.1:")
        assert server.web_socket_url.startswith("ws://127.0.0.1:")
        address = server.address
        assert address["port"] == server.port
    finally:
        await server.destroy()


async def test_garbage_frame_closes_offender_but_server_survives():
    """A malformed binary frame must not take down the process — reference
    resilience behavior (`packages/server/src/Server.ts:71-80`,
    `Connection.ts:188-213`)."""
    server = await new_hocuspocus()
    provider = new_provider(server, name="healthy-doc")
    try:
        await wait_synced(provider)
        async with aiohttp.ClientSession() as session:
            ws = await session.ws_connect(server.web_socket_url)
            await ws.send_bytes(b"\xff\xfe\xfd garbage")
            await asyncio.sleep(0.2)
            await ws.close()

        # healthy connection still works end-to-end after the garbage frame
        provider.document.get_text("t").insert(0, "still alive")
        await retryable_assertion(
            lambda: _assert(
                server.documents.get("healthy-doc") is not None
                and str(server.documents["healthy-doc"].get_text("t")) == "still alive"
            )
        )
    finally:
        provider.destroy()
        await server.destroy()


async def test_destroy_flushes_pending_store():
    """destroy() waits for debounced stores: no edits may be lost on
    graceful shutdown (reference `Server.ts:200-221`)."""
    from hocuspocus_tpu.crdt import encode_state_as_update

    stored = []

    async def on_store_document(data):
        # like the Database extension, persist the full doc state
        # (reference `Database.ts:55-60`; `state` only exists on the
        # Database store() payload, not the generic hook payload)
        stored.append(encode_state_as_update(data.document))

    server = await new_hocuspocus(
        on_store_document=on_store_document, debounce=5_000
    )
    provider = new_provider(server, name="flush-doc")
    await wait_synced(provider)
    provider.document.get_text("t").insert(0, "must persist")
    await wait_for(lambda: provider.unsynced_changes == 0)
    provider.destroy()
    await server.destroy()
    assert stored, "pending debounced store was dropped on destroy"


async def test_connection_timeout_closes_dead_socket():
    # server pings on `timeout` interval; a provider that never answers
    # cannot be simulated at this level, but the keepalive configuration
    # must round-trip into the websocket heartbeat
    server = await new_hocuspocus(timeout=1_500)
    try:
        assert server.configuration.timeout == 1_500
    finally:
        await server.destroy()


def _assert(cond):
    assert cond


async def test_oversized_frame_closes_with_message_too_big():
    """Frames over stateless_payload_limit close that socket (1009);
    the server and other clients keep working."""
    import aiohttp

    from hocuspocus_tpu.server import Configuration, Server
    from tests.utils import new_provider, wait_for

    server = Server(Configuration(quiet=True, stateless_payload_limit=4096))
    await server.listen(port=0)
    try:
        provider = new_provider(server, name="survivor")
        await wait_for(lambda: provider.synced)

        session = aiohttp.ClientSession()
        ws = await session.ws_connect(server.web_socket_url)
        await ws.send_bytes(b"\x03big\x00" + b"x" * 20000)
        msg = await ws.receive(timeout=5)
        assert msg.type in (aiohttp.WSMsgType.CLOSE, aiohttp.WSMsgType.CLOSED)
        if msg.type == aiohttp.WSMsgType.CLOSE:
            assert msg.data == 1009
        await session.close()

        provider.document.get_text("t").insert(0, "still alive")
        await wait_for(lambda: not provider.has_unsynced_changes)
        provider.destroy()
    finally:
        await server.destroy()


async def test_invalid_opcode_closes_with_protocol_error():
    """A malformed ws frame (reserved opcode) must NOT be mislabeled
    1009 MessageTooBig; the server replies 1002 and stays up."""
    import base64
    import os as _os
    from urllib.parse import urlparse

    from hocuspocus_tpu.server import Configuration, Server
    from tests.utils import new_provider, wait_for

    server = Server(Configuration(quiet=True))
    await server.listen(port=0)
    try:
        parsed = urlparse(server.web_socket_url)
        reader, writer = await asyncio.open_connection(parsed.hostname, parsed.port)
        key = base64.b64encode(_os.urandom(16)).decode()
        writer.write(
            (
                f"GET / HTTP/1.1\r\nHost: {parsed.hostname}:{parsed.port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        await writer.drain()
        handshake = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), timeout=5)
        assert b"101" in handshake.split(b"\r\n", 1)[0]

        # FIN + reserved opcode 0x3, masked, zero-length payload
        writer.write(bytes([0x83, 0x80, 0, 0, 0, 0]))
        await writer.drain()

        frame = await asyncio.wait_for(reader.readexactly(2), timeout=5)
        assert frame[0] & 0x0F == 0x08, "expected a close frame"
        length = frame[1] & 0x7F
        payload = await asyncio.wait_for(reader.readexactly(length), timeout=5)
        close_code = int.from_bytes(payload[:2], "big")
        assert close_code == 1002, f"expected 1002 protocol error, got {close_code}"
        writer.close()

        # server survives: a healthy provider still syncs
        provider = new_provider(server, name="pe-survivor")
        await wait_for(lambda: provider.synced)
        provider.destroy()
    finally:
        await server.destroy()
