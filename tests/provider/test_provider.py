"""Provider behavior: multiplexing, reconnect, readonly, force sync."""

import asyncio

import pytest

from hocuspocus_tpu.provider import HocuspocusProvider, HocuspocusProviderWebsocket
from tests.utils import (
    new_hocuspocus,
    new_provider,
    new_provider_websocket,
    retryable_assertion,
    wait_for,
    wait_synced,
)


def _assert(cond):
    assert cond


async def test_two_documents_multiplexed_on_one_socket():
    server = await new_hocuspocus()
    socket = new_provider_websocket(server)
    provider_a = HocuspocusProvider(name="doc-a", websocket_provider=socket)
    provider_a.attach()
    provider_b = HocuspocusProvider(name="doc-b", websocket_provider=socket)
    provider_b.attach()
    try:
        await wait_synced(provider_a, provider_b)
        assert server.get_documents_count() == 2
        # one underlying socket => one connection counted
        assert server.get_connections_count() == 1
        provider_a.document.get_text("t").insert(0, "A content")
        provider_b.document.get_text("t").insert(0, "B content")
        await retryable_assertion(
            lambda: _assert(
                server.documents["doc-a"].get_text("t").to_string() == "A content"
                and server.documents["doc-b"].get_text("t").to_string() == "B content"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        socket.destroy()
        await server.destroy()


async def test_provider_reconnects_and_resyncs():
    server = await new_hocuspocus()
    port = server.port
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "before restart")
        await asyncio.sleep(0.2)
        # simulate server crash + restart on the same port
        await server.destroy()
        assert not provider.websocket_provider.should_connect is False  # still wants to connect
        from hocuspocus_tpu.server import Configuration, Server

        server2 = Server(Configuration(quiet=True))
        await server2.listen(port=port)
        # offline edit while reconnecting
        provider.document.get_text("t").insert(0, "offline! ")
        await wait_for(lambda: provider.synced, timeout=20)
        await retryable_assertion(
            lambda: _assert(
                server2.documents["hocuspocus-test"].get_text("t").to_string()
                == "offline! before restart"
            ),
            timeout=15,
        )
        await server2.destroy()
    finally:
        provider.destroy()


async def test_read_only_connection_cannot_write():
    async def on_authenticate(data):
        data.connection_config.read_only = True

    server = await new_hocuspocus(on_authenticate=on_authenticate)
    provider = new_provider(server)
    try:
        await wait_for(lambda: provider.is_authenticated)
        assert provider.authorized_scope == "readonly"
        provider.document.get_text("t").insert(0, "should not apply")
        await asyncio.sleep(0.5)
        doc = server.documents.get("hocuspocus-test")
        assert doc is not None
        assert doc.get_text("t").to_string() == ""
    finally:
        provider.destroy()
        await server.destroy()


async def test_force_sync():
    server = await new_hocuspocus()
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        # server-side direct edit does not proactively reach an idle
        # provider's doc until a sync runs... it does broadcast, so
        # instead verify force_sync round trip completes
        provider.force_sync()
        await wait_for(lambda: provider.synced)
    finally:
        provider.destroy()
        await server.destroy()


async def test_has_unsynced_changes_lifecycle():
    server = await new_hocuspocus()
    provider = new_provider(server)
    events = []
    provider.on("unsynced_changes", lambda data: events.append(data["number"]))
    try:
        await wait_synced(provider)
        # "synced" fires on SyncStep2 receipt; the initial unsynced count
        # (startSync's reset to 1) drains one round-trip later via the
        # SyncStatus ack (reference HocuspocusProvider.ts:251-270)
        await wait_for(lambda: not provider.has_unsynced_changes)
        provider.document.get_text("t").insert(0, "x")
        assert provider.has_unsynced_changes
        await wait_for(lambda: not provider.has_unsynced_changes)
        assert 0 in events
    finally:
        provider.destroy()
        await server.destroy()


async def test_awareness_error_when_disabled():
    server = await new_hocuspocus()
    provider = new_provider(server, awareness=None)
    try:
        from hocuspocus_tpu.provider import AwarenessError

        with pytest.raises(AwarenessError):
            provider.set_awareness_field("user", {"name": "x"})
    finally:
        provider.destroy()
        await server.destroy()


async def test_observe_via_provider():
    server = await new_hocuspocus()
    provider_a = new_provider(server)
    provider_b = new_provider(server)
    deltas = []
    try:
        await wait_synced(provider_a, provider_b)
        provider_b.document.get_text("t").observe(
            lambda event, tr: deltas.append(event.delta)
        )
        provider_a.document.get_text("t").insert(0, "watched")
        await retryable_assertion(lambda: _assert(deltas == [[{"insert": "watched"}]]))
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server.destroy()


async def test_authentication_scope_read_write():
    server = await new_hocuspocus()
    provider = new_provider(server)
    scopes = []
    provider.on("authenticated", lambda data: scopes.append(data["scope"]))
    try:
        await wait_synced(provider)
        assert scopes == ["read-write"]
        assert provider.is_authenticated
    finally:
        provider.destroy()
        await server.destroy()


async def test_reconnect_backoff_capped_exponential_with_jitter():
    """Reconnect pacing is part of the provider configuration:
    min/max_reconnect_delay_ms bound a capped exponential ladder, and
    jitter draws uniformly inside it (a reconnect herd spreads instead
    of thundering)."""
    socket = HocuspocusProviderWebsocket(
        url="ws://127.0.0.1:9",  # never connected: auto_connect off
        auto_connect=False,
        delay=100,
        factor=2,
        min_reconnect_delay_ms=50,
        max_reconnect_delay_ms=400,
        jitter=False,
    )
    try:
        assert socket.min_reconnect_delay_ms == 50
        assert socket.max_reconnect_delay_ms == 400
        # deterministic (jitter off): 100, 200, 400, then capped at 400
        delays_ms = [socket._backoff_delay(a) * 1000 for a in (1, 2, 3, 4, 9)]
        assert delays_ms == [100, 200, 400, 400, 400]
        socket.jitter = True
        for attempt in (1, 2, 3, 8):
            ceiling = min(100 * (2 ** (attempt - 1)), 400)
            for _ in range(50):
                delay_ms = socket._backoff_delay(attempt) * 1000
                assert 50 <= delay_ms <= max(ceiling, 50) + 1e-6
    finally:
        socket.destroy()


async def test_provider_exposes_reconnect_delay_configuration():
    provider = HocuspocusProvider(
        name="backoff-doc",
        url="ws://127.0.0.1:9",
        min_reconnect_delay_ms=25,
        max_reconnect_delay_ms=900,
    )
    try:
        assert provider.websocket_provider.min_reconnect_delay_ms == 25
        assert provider.websocket_provider.max_reconnect_delay_ms == 900
    finally:
        provider.destroy()
