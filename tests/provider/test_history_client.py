"""HistoryClient + awareness-cursor helpers: the client-side DX layer
over the History extension and relative positions."""

import pytest

from hocuspocus_tpu.extensions import History
from hocuspocus_tpu.provider import HistoryClient, HistoryError

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_history_client_full_flow():
    server = await new_hocuspocus(extensions=[History()])
    writer = new_provider(server, name="hc-doc")
    reviewer = new_provider(server, name="hc-doc")
    history = HistoryClient(reviewer)
    try:
        await wait_synced(writer, reviewer)
        text = writer.document.get_text("t")
        text.insert(0, "checkpoint me")
        await retryable_assertion(
            lambda: _assert(reviewer.document.get_text("t").to_string() == "checkpoint me")
        )

        version = await history.checkpoint("v1")
        assert version["label"] == "v1"

        text.insert(0, "NEW: ")
        await retryable_assertion(
            lambda: _assert(
                reviewer.document.get_text("t").to_string() == "NEW: checkpoint me"
            )
        )

        versions = await history.list()
        assert [v["label"] for v in versions] == ["v1"]

        old = await history.preview(version["id"])
        assert old.get_text("t").to_string() == "checkpoint me"

        delta = await history.diff(version["id"], root="t")
        added = [
            op["insert"]
            for op in delta
            if op.get("attributes", {}).get("ychange", {}).get("type") == "added"
        ]
        assert added == ["NEW: "]

        await history.restore(version["id"])
        await retryable_assertion(
            lambda: _assert(
                writer.document.get_text("t").to_string() == "checkpoint me"
            )
        )

        with pytest.raises(HistoryError):
            await history.preview(99999)
    finally:
        history.destroy()
        writer.destroy()
        reviewer.destroy()
        await server.destroy()


async def test_awareness_cursor_helpers_roundtrip():
    server = await new_hocuspocus()
    a = new_provider(server, name="cursor-doc")
    b = new_provider(server, name="cursor-doc")
    try:
        await wait_synced(a, b)
        ta = a.document.get_text("t")
        ta.insert(0, "the quick brown fox")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "the quick brown fox"
            )
        )

        # A selects "quick" (4..9); B resolves it against ITS doc copy
        a.set_awareness_cursor(ta, 4, 9)

        def _b_sees_cursor():
            states = b.awareness.get_states()
            state = states.get(a.document.client_id)
            _assert(state is not None and "cursor" in state)
            resolved = b.resolve_awareness_cursor(state["cursor"], b.document)
            _assert(resolved == {"anchor": 4, "head": 9})

        await retryable_assertion(_b_sees_cursor)

        # concurrent edits shift the selection but not its TARGET text
        b.document.get_text("t").insert(0, ">>> ")
        await retryable_assertion(
            lambda: _assert(ta.to_string().startswith(">>> "))
        )
        state = b.awareness.get_states()[a.document.client_id]
        resolved = b.resolve_awareness_cursor(state["cursor"], b.document)
        assert resolved == {"anchor": 8, "head": 13}
        text = b.document.get_text("t").to_string()
        assert text[resolved["anchor"]:resolved["head"]] == "quick"

        # malformed fields resolve to None, never raise
        assert b.resolve_awareness_cursor("junk", b.document) is None
        assert b.resolve_awareness_cursor({"anchor": "zz"}, b.document) is None
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()
