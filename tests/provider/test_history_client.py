"""HistoryClient + awareness-cursor helpers: the client-side DX layer
over the History extension and relative positions."""

import pytest

from hocuspocus_tpu.extensions import History
from hocuspocus_tpu.provider import HistoryClient, HistoryError

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_history_client_full_flow():
    server = await new_hocuspocus(extensions=[History()])
    writer = new_provider(server, name="hc-doc")
    reviewer = new_provider(server, name="hc-doc")
    history = HistoryClient(reviewer)
    try:
        await wait_synced(writer, reviewer)
        text = writer.document.get_text("t")
        text.insert(0, "checkpoint me")
        await retryable_assertion(
            lambda: _assert(reviewer.document.get_text("t").to_string() == "checkpoint me")
        )

        version = await history.checkpoint("v1")
        assert version["label"] == "v1"

        text.insert(0, "NEW: ")
        await retryable_assertion(
            lambda: _assert(
                reviewer.document.get_text("t").to_string() == "NEW: checkpoint me"
            )
        )

        versions = await history.list()
        assert [v["label"] for v in versions] == ["v1"]

        old = await history.preview(version["id"])
        assert old.get_text("t").to_string() == "checkpoint me"

        delta = await history.diff(version["id"], root="t")
        added = [
            op["insert"]
            for op in delta
            if op.get("attributes", {}).get("ychange", {}).get("type") == "added"
        ]
        assert added == ["NEW: "]

        await history.restore(version["id"])
        await retryable_assertion(
            lambda: _assert(
                writer.document.get_text("t").to_string() == "checkpoint me"
            )
        )

        with pytest.raises(HistoryError):
            await history.preview(99999)
    finally:
        history.destroy()
        writer.destroy()
        reviewer.destroy()
        await server.destroy()


async def test_awareness_cursor_helpers_roundtrip():
    server = await new_hocuspocus()
    a = new_provider(server, name="cursor-doc")
    b = new_provider(server, name="cursor-doc")
    try:
        await wait_synced(a, b)
        ta = a.document.get_text("t")
        ta.insert(0, "the quick brown fox")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "the quick brown fox"
            )
        )

        # A selects "quick" (4..9); B resolves it against ITS doc copy
        a.set_awareness_cursor(ta, 4, 9)

        def _b_sees_cursor():
            states = b.awareness.get_states()
            state = states.get(a.document.client_id)
            _assert(state is not None and "cursor" in state)
            resolved = b.resolve_awareness_cursor(state["cursor"], b.document)
            _assert(resolved == {"anchor": 4, "head": 9})

        await retryable_assertion(_b_sees_cursor)

        # concurrent edits shift the selection but not its TARGET text
        b.document.get_text("t").insert(0, ">>> ")
        await retryable_assertion(
            lambda: _assert(ta.to_string().startswith(">>> "))
        )
        state = b.awareness.get_states()[a.document.client_id]
        resolved = b.resolve_awareness_cursor(state["cursor"], b.document)
        assert resolved == {"anchor": 8, "head": 13}
        text = b.document.get_text("t").to_string()
        assert text[resolved["anchor"]:resolved["head"]] == "quick"

        # malformed fields resolve to None, never raise
        assert b.resolve_awareness_cursor("junk", b.document) is None
        assert b.resolve_awareness_cursor({"anchor": "zz"}, b.document) is None
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_history_client_rid_correlation_is_exact():
    """Regression (ADVICE.md): errors were routed to the OLDEST pending
    future and broadcasts matched by kind alone, so another client's
    concurrent checkpoint/restore could resolve (or an error reject)
    the wrong awaitable. The rid echo makes correlation exact."""
    import asyncio
    import json as _json

    from hocuspocus_tpu.provider.history import HistoryClient, HistoryError

    class FakeProvider:
        def __init__(self):
            self.sent = []
            self.handlers = []

        def on(self, event, handler):
            self.handlers.append(handler)

        def off(self, event, handler):
            self.handlers.remove(handler)

        def send_stateless(self, payload):
            self.sent.append(_json.loads(payload))

        def deliver(self, event: dict):
            for handler in list(self.handlers):
                handler({"payload": _json.dumps(event)})

    provider = FakeProvider()
    client = HistoryClient(provider, timeout=5.0)

    checkpoint_task = asyncio.ensure_future(client.checkpoint("mine"))
    preview_task = asyncio.ensure_future(client.preview(123))
    await asyncio.sleep(0)  # let both requests register + send
    assert len(provider.sent) == 2
    checkpoint_rid = provider.sent[0]["rid"]
    preview_rid = provider.sent[1]["rid"]
    assert checkpoint_rid and preview_rid and checkpoint_rid != preview_rid

    # ANOTHER client's broadcast (foreign rid) must not resolve ours
    provider.deliver(
        {"event": "history.checkpointed", "id": 99, "label": "theirs",
         "ts": 1.0, "rid": "someone-else-7"}
    )
    await asyncio.sleep(0)
    assert not checkpoint_task.done()

    # the error for the PREVIEW must reject the preview future, not the
    # oldest pending one (the checkpoint)
    provider.deliver(
        {"event": "history.error", "error": "unknown version", "rid": preview_rid}
    )
    await asyncio.sleep(0)
    assert not checkpoint_task.done()
    try:
        await preview_task
        raise AssertionError("preview should have raised HistoryError")
    except HistoryError as error:
        assert "unknown version" in str(error)

    # our own broadcast (our rid) resolves our checkpoint with OUR id
    provider.deliver(
        {"event": "history.checkpointed", "id": 2, "label": "mine",
         "ts": 2.0, "rid": checkpoint_rid}
    )
    version = await checkpoint_task
    assert version["id"] == 2 and version["label"] == "mine"

    # a store-minted broadcast (rid-less, origin "store") must NOT
    # resolve a pending rid-bearing checkpoint via the legacy fallback
    checkpoint_task2 = asyncio.ensure_future(client.checkpoint("mine-2"))
    await asyncio.sleep(0)
    rid2 = provider.sent[-1]["rid"]
    provider.deliver(
        {"event": "history.checkpointed", "id": 7, "label": "store",
         "ts": 3.0, "origin": "store"}
    )
    await asyncio.sleep(0)
    assert not checkpoint_task2.done(), (
        "store-minted broadcast must not satisfy a pending request"
    )
    provider.deliver(
        {"event": "history.checkpointed", "id": 3, "label": "mine-2",
         "ts": 4.0, "rid": rid2}
    )
    assert (await checkpoint_task2)["id"] == 3

    # rid-less events (legacy server) still resolve by kind in send order
    list_task = asyncio.ensure_future(client.list())
    await asyncio.sleep(0)
    provider.deliver({"event": "history.versions", "versions": [{"id": 1}]})
    assert await list_task == [{"id": 1}]

    client.destroy()
