"""Provider event surface (mirrors reference tests/provider/* taxonomy):
authentication-failed, stateless, synced/status events, observe_deep.
"""

from __future__ import annotations

import asyncio

from hocuspocus_tpu.server import Payload
from tests.utils import (
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_for,
    wait_synced,
)


async def test_on_authentication_failed_event():
    async def on_authenticate(data):
        raise ValueError("wrong token")

    server = await new_hocuspocus(on_authenticate=on_authenticate)
    failures = []
    provider = new_provider(
        server,
        token="bad",
        on_authentication_failed=lambda data: failures.append(data["reason"]),
    )
    try:
        await retryable_assertion(lambda: _assert(len(failures) >= 1))
        assert provider.is_authenticated is False
        assert provider.synced is False
    finally:
        provider.destroy()
        await server.destroy()


async def test_on_authenticated_event_carries_scope():
    events = []
    server = await new_hocuspocus()
    provider = new_provider(
        server, on_authenticated=lambda data: events.append(data["scope"])
    )
    try:
        await wait_synced(provider)
        assert events == ["read-write"]
        assert provider.authorized_scope == "read-write"
    finally:
        provider.destroy()
        await server.destroy()


async def test_server_to_client_stateless():
    """Server pushes a stateless payload; provider on_stateless fires."""
    server = await new_hocuspocus()
    received = []
    provider = new_provider(
        server,
        name="stateless-doc",
        on_stateless=lambda data: received.append(data["payload"]),
    )
    try:
        await wait_synced(provider)
        document = server.documents["stateless-doc"]
        document.broadcast_stateless('{"kind":"server-push"}')
        await retryable_assertion(
            lambda: _assert(received == ['{"kind":"server-push"}'])
        )
    finally:
        provider.destroy()
        await server.destroy()


async def test_synced_event_fires_once_per_connection():
    server = await new_hocuspocus()
    events = []
    provider = new_provider(
        server, on_synced=lambda data: events.append(data["state"])
    )
    try:
        await wait_synced(provider)
        await asyncio.sleep(0.2)
        assert events == [True]
    finally:
        provider.destroy()
        await server.destroy()


async def test_observe_deep_sees_nested_changes():
    server = await new_hocuspocus()
    a = new_provider(server, name="deep-doc")
    b = new_provider(server, name="deep-doc")
    try:
        await wait_synced(a, b)
        seen = []
        b.document.get_map("root").observe_deep(
            lambda events, transaction: seen.append(len(events))
        )
        amap = a.document.get_map("root")
        amap.set("title", "hello")
        await retryable_assertion(
            lambda: _assert(b.document.get_map("root").get("title") == "hello")
        )
        assert seen, "observe_deep callback never fired"
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_status_events_on_connect_and_disconnect():
    server = await new_hocuspocus()
    statuses = []
    provider = new_provider(
        server, on_status=lambda data: statuses.append(data["status"])
    )
    try:
        await wait_synced(provider)
        assert "connected" in [str(s) for s in statuses] or statuses
    finally:
        provider.destroy()
        await server.destroy()


async def test_unsynced_changes_event_stream():
    server = await new_hocuspocus()
    numbers = []
    provider = new_provider(
        server, on_unsynced_changes=lambda data: numbers.append(data["number"])
    )
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        await wait_for(lambda: provider.unsynced_changes == 0)
        assert any(n > 0 for n in numbers), numbers
        assert numbers[-1] == 0
    finally:
        provider.destroy()
        await server.destroy()


def _assert(cond):
    assert cond
