"""InProcessProviderSocket: full provider semantics with no OS socket.

The socket-free path must be indistinguishable from the websocket path
for sync, multiplexing, auth hooks, awareness, and teardown — it backs
the at-scale load harness (hocuspocus_tpu.loadgen), so any divergence
here would make the 100k-doc measurements unrepresentative.
"""

import asyncio

from hocuspocus_tpu.protocol.close_events import CloseEvent
from hocuspocus_tpu.provider import HocuspocusProvider, InProcessProviderSocket
from hocuspocus_tpu.server import Configuration, Hocuspocus
from tests.utils import retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_inprocess_provider_syncs_and_edits():
    server = Hocuspocus(Configuration(quiet=True))
    socket = InProcessProviderSocket(server)
    provider = HocuspocusProvider(name="inproc-doc", websocket_provider=socket)
    provider.attach()
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "hello inproc")
        await retryable_assertion(
            lambda: _assert(
                server.documents["inproc-doc"].get_text("t").to_string()
                == "hello inproc"
            )
        )
        # server -> client direction
        direct = await server.open_direct_connection("inproc-doc")
        await direct.transact(
            lambda doc: doc.get_text("t").insert(0, "server says ")
        )
        await direct.disconnect()
        await retryable_assertion(
            lambda: _assert(
                provider.document.get_text("t").to_string()
                == "server says hello inproc"
            )
        )
    finally:
        provider.destroy()
        socket.destroy()


async def test_inprocess_socket_multiplexes_documents():
    server = Hocuspocus(Configuration(quiet=True))
    socket = InProcessProviderSocket(server)
    providers = [
        HocuspocusProvider(name=f"mux-{i}", websocket_provider=socket)
        for i in range(4)
    ]
    for p in providers:
        p.attach()
    try:
        await wait_synced(*providers)
        assert server.get_documents_count() == 4
        # one underlying connection => one socket id
        assert server.get_connections_count() == 1
        for i, p in enumerate(providers):
            p.document.get_text("t").insert(0, f"doc {i}")
        await retryable_assertion(
            lambda: _assert(
                all(
                    server.documents[f"mux-{i}"].get_text("t").to_string()
                    == f"doc {i}"
                    for i in range(4)
                )
            )
        )
    finally:
        for p in providers:
            p.destroy()
        socket.destroy()


async def test_inprocess_socket_runs_auth_hooks():
    seen_tokens = []

    async def on_authenticate(payload):
        seen_tokens.append(payload.token)
        if payload.token != "let-me-in":
            raise CloseEvent(4401, "Unauthorized")
        return {"user": "authed"}

    contexts = []

    async def connected(payload):
        contexts.append(payload.context)

    server = Hocuspocus(
        Configuration(
            quiet=True, on_authenticate=on_authenticate, connected=connected
        )
    )
    good_socket = InProcessProviderSocket(server)
    good = HocuspocusProvider(
        name="auth-doc", websocket_provider=good_socket, token="let-me-in"
    )
    good.attach()
    bad_socket = InProcessProviderSocket(server)
    denied = []
    bad = HocuspocusProvider(
        name="auth-doc",
        websocket_provider=bad_socket,
        token="wrong",
        on_authentication_failed=lambda data: denied.append(data),
    )
    bad.attach()
    try:
        await wait_synced(good)
        await retryable_assertion(lambda: _assert(len(denied) == 1))
        assert seen_tokens == ["let-me-in", "wrong"] or seen_tokens == [
            "wrong",
            "let-me-in",
        ]
        assert contexts and contexts[0].get("user") == "authed"
    finally:
        good.destroy()
        bad.destroy()
        good_socket.destroy()
        bad_socket.destroy()


async def test_inprocess_socket_awareness_propagates():
    server = Hocuspocus(Configuration(quiet=True))
    socket_a = InProcessProviderSocket(server)
    socket_b = InProcessProviderSocket(server)
    a = HocuspocusProvider(name="aw-doc", websocket_provider=socket_a)
    b = HocuspocusProvider(name="aw-doc", websocket_provider=socket_b)
    a.attach()
    b.attach()
    try:
        await wait_synced(a, b)
        a.set_awareness_field("user", {"name": "alice"})
        await retryable_assertion(
            lambda: _assert(
                any(
                    state.get("user", {}).get("name") == "alice"
                    for state in b.awareness.get_states().values()
                )
            )
        )
    finally:
        a.destroy()
        b.destroy()
        socket_a.destroy()
        socket_b.destroy()


async def test_inprocess_socket_destroy_disconnects_and_unloads():
    server = Hocuspocus(Configuration(quiet=True, unload_immediately=True))
    socket = InProcessProviderSocket(server)
    provider = HocuspocusProvider(name="bye-doc", websocket_provider=socket)
    provider.attach()
    await wait_synced(provider)
    provider.document.get_text("t").insert(0, "x")
    await asyncio.sleep(0.05)
    provider.destroy()
    socket.destroy()
    await retryable_assertion(
        lambda: _assert(server.get_documents_count() == 0)
    )
    assert server.get_connections_count() == 0
