"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the multichip
path; bench.py uses the real chip). These env vars must be set before any
jax import, hence here at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin registers itself regardless of JAX_PLATFORMS; the
# config route reliably pins the test backend to the virtual CPU mesh.
import jax

jax.config.update("jax_platforms", "cpu")

import asyncio
import inspect

import pytest


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio_auto: run coroutine test via asyncio.run")


def pytest_collection_modifyitems(items):
    for item in items:
        if inspect.iscoroutinefunction(getattr(item, "function", None)):
            item.add_marker(pytest.mark.asyncio_auto)


@pytest.hookimpl(hookwrapper=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal asyncio test support (no pytest-asyncio dependency)."""
    fn = pyfuncitem.function
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=60))
        pyfuncitem.obj = lambda *a, **k: None
    yield
