"""Observability: tracer spans, metrics registry, /metrics endpoint.

The reference has no equivalent subsystem (SURVEY.md §5.1/§5.5); these
tests cover the capability the TPU build adds on top.
"""

from __future__ import annotations

import aiohttp

from hocuspocus_tpu.observability import (
    Metrics,
    MetricsRegistry,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def test_tracer_records_spans_with_attributes():
    tracer = Tracer(enabled=True, max_spans=8)
    with tracer.span("outer", document="doc-a") as span:
        span.set("bytes", 42)
    spans = tracer.export()
    assert len(spans) == 1
    assert spans[0]["name"] == "outer"
    assert spans[0]["attributes"] == {"document": "doc-a", "bytes": 42}
    assert spans[0]["duration_ms"] >= 0


def test_tracer_disabled_is_noop():
    tracer = Tracer(enabled=False)
    with tracer.span("nope") as span:
        span.set("ignored", 1)
    assert len(tracer) == 0


def test_tracer_ring_buffer_bounded():
    tracer = Tracer(enabled=True, max_spans=4)
    for i in range(10):
        with tracer.span(f"s{i}"):
            pass
    spans = tracer.export()
    assert len(spans) == 4
    assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]


def test_device_span_works_without_profiler():
    tracer = Tracer(enabled=True)
    with tracer.device_span("merge", slots=4) as span:
        span.set("integrated", 128)
    assert tracer.export()[0]["attributes"]["integrated"] == 128


def test_global_tracer_enable_disable():
    tracer = enable_tracing(max_spans=16)
    try:
        assert get_tracer() is tracer
        with tracer.span("x"):
            pass
        assert len(tracer) == 1
    finally:
        disable_tracing()
        tracer.clear()


def test_metrics_counter_and_gauge_exposition():
    reg = MetricsRegistry()
    c = reg.counter("demo_total", "Demo counter")
    c.inc()
    c.inc(2, kind="sync")
    g = reg.gauge("demo_current", "Demo gauge", fn=lambda: 3)
    text = reg.expose()
    assert "# TYPE demo_total counter" in text
    assert "demo_total 1" in text
    assert 'demo_total{kind="sync"} 2' in text
    assert "demo_current 3" in text
    assert g.value() == 3


def test_metrics_histogram_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "Latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="0.1"} 2' in text
    assert 'lat_seconds_bucket{le="1"} 3' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_count 4" in text
    assert h.count == 4


async def test_metrics_extension_counts_lifecycle_and_serves_endpoint():
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="metrics-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "hello")

        await retryable_assertion(lambda: _assert_positive(metrics.changes.value()))
        assert metrics.connects.value() == 1
        assert metrics.loads.value() == 1

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                assert response.status == 200
                body = await response.text()
        assert "hocuspocus_connections 1" in body
        assert "hocuspocus_documents 1" in body
        assert "hocuspocus_connects_total 1" in body
        assert "hocuspocus_document_loads_total 1" in body
        assert "hocuspocus_document_load_seconds_count 1" in body

        # non-metrics requests still get the default response
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/other") as response:
                assert response.status == 200
                assert "Welcome" in await response.text()
        assert metrics.http_requests.value() == 1
    finally:
        provider.destroy()
        await server.destroy()

    assert metrics.disconnects.value() == 1
    assert metrics.unloads.value() == 1


async def test_tracing_captures_message_spans_end_to_end():
    tracer = enable_tracing(max_spans=512)
    tracer.clear()
    server = await new_hocuspocus()
    provider = new_provider(server, name="traced-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "traced")

        def has_spans():
            names = {s["name"] for s in tracer.export()}
            assert "message.apply" in names, names
            assert any(n.startswith("hooks.") for n in names), names

        await retryable_assertion(has_spans)
        apply_spans = [
            s for s in tracer.export() if s["name"] == "message.apply"
        ]
        assert all(s["attributes"]["document"] == "traced-doc" for s in apply_spans)
        assert all(s["attributes"]["bytes"] > 0 for s in apply_spans)
    finally:
        disable_tracing()
        tracer.clear()
        provider.destroy()
        await server.destroy()


def _assert_positive(value: float) -> None:
    assert value > 0


async def test_metrics_exposes_tpu_plane_counters():
    """A serve-mode plane's health counters surface on /metrics."""
    import aiohttp

    from hocuspocus_tpu.observability import Metrics
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    ext = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics, ext])
    provider = new_provider(server, name="metered")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "counted")

        def broadcasted():
            assert ext.plane.counters["plane_broadcasts"] >= 1

        await retryable_assertion(broadcasted)
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                body = await response.text()
        lines = body.splitlines()
        assert any(
            line.startswith("hocuspocus_tpu_plane_broadcasts ") for line in lines
        )
        assert "hocuspocus_tpu_plane_docs_retired_unsupported 0" in lines
        assert "hocuspocus_tpu_plane_arena_rows_in_use 1" in lines
        assert any(
            line.startswith("hocuspocus_tpu_plane_ops_integrated ") for line in lines
        )
    finally:
        provider.destroy()
        await server.destroy()


async def test_supervisor_metrics_visible_in_prometheus_exposition():
    """Plane supervisor surface (tpu/supervisor.py): state, breaker
    transitions and canary latency must land in the /metrics text so a
    balancer/alerting stack can watch plane health (ISSUE acceptance)."""
    from hocuspocus_tpu.tpu import SupervisedTpuMergeExtension

    metrics = Metrics()
    ext = SupervisedTpuMergeExtension(
        serve=True,
        num_docs=8,
        capacity=256,
        flush_interval_ms=1,
        init_timeout=60.0,
        watchdog_interval=0.05,
        canary_deadline=1.0,
    )
    server = await new_hocuspocus(extensions=[metrics, ext])
    provider = new_provider(server, name="sup-metrics")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "observe me")
        # READY + at least one SUCCESSFUL canary probe (latency recorded)
        await retryable_assertion(
            lambda: _assert_positive(
                (ext.supervisor.state == "ready")
                and (ext.supervisor.last_canary_latency is not None)
            )
        )

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                assert response.status == 200
                body = await response.text()

        # supervisor state gauge: 1 == ready
        assert "hocuspocus_tpu_supervisor_state 1" in body
        assert "hocuspocus_tpu_supervisor_breaker_state 0" in body
        # the boot transition was recorded with exact labels
        assert (
            'hocuspocus_tpu_supervisor_transitions_total{from_state="initializing",to_state="ready"} 1'
            in body
        )
        # breaker transition counter is present (zero so far)
        assert "hocuspocus_tpu_supervisor_breaker_transitions_total" in body
        # canary latency: histogram observed at least once + last-value gauge
        count_line = next(
            line
            for line in body.splitlines()
            if line.startswith("hocuspocus_tpu_supervisor_canary_seconds_count")
        )
        assert int(count_line.split()[-1]) >= 1
        assert "hocuspocus_tpu_supervisor_canary_latency_seconds" in body
        # the plane's own counters bound at hot-attach time
        assert "hocuspocus_tpu_plane_cpu_fallbacks" in body
        assert "hocuspocus_tpu_plane_arena_rows_in_use" in body
    finally:
        provider.destroy()
        await server.destroy()
