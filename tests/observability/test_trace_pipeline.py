"""End-to-end update lifecycle tracing: trace-id propagation through the
flush pipeline, Perfetto/Chrome export, slow-span promotion, labelled
histograms, Prometheus exposition conformance, flight recorder, and the
/debug endpoints.

The reference has none of this (SURVEY.md §5.1/§5.5); these tests cover
the instrumentation layer the TPU build adds so perf PRs are measurable
instead of anecdotal.
"""

from __future__ import annotations

import json

import aiohttp
import pytest

from hocuspocus_tpu.crdt import Doc, encode_state_as_update
from hocuspocus_tpu.observability import (
    FlightRecorder,
    Histogram,
    Metrics,
    MetricsRegistry,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_flight_recorder,
    get_tracer,
)
from hocuspocus_tpu.observability.metrics import _fmt_value

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

STAGES = ("queue_wait", "build", "upload", "device", "readback", "broadcast")
# updates arriving through the websocket edge additionally carry the
# ingress stage (ws receive -> decode -> apply -> capture), so the e2e
# span truly runs socket -> broadcast
WS_STAGES = ("ingress",) + STAGES


def _make_update(text: str = "hello") -> bytes:
    doc = Doc()
    doc.get_text("t").insert(0, text)
    return encode_state_as_update(doc)


def _fresh_traced_plane(num_docs: int = 8, capacity: int = 256):
    from hocuspocus_tpu.tpu.merge_plane import MergePlane

    tracer = Tracer(enabled=True, max_spans=256)
    plane = MergePlane(num_docs=num_docs, capacity=capacity)
    plane.update_traces.tracer = tracer
    return plane, tracer


# -- trace-id propagation ------------------------------------------------------


def test_trace_id_propagates_through_flush_and_broadcast_stages():
    """One update -> six contiguous stage spans sharing one trace id,
    whose durations sum exactly to the end-to-end latency (the
    acceptance invariant for the lifecycle pipeline)."""
    plane, tracer = _fresh_traced_plane()
    hist = Histogram("e2e_seconds", "e2e")
    plane.update_traces.histogram = hist

    plane.register("traced")
    plane.enqueue_update("traced", _make_update())
    trace_id = plane.note_trace("traced")
    assert trace_id is not None
    assert plane.flush() > 0
    assert plane.update_traces.finish("traced") == 1

    spans = [s for s in tracer.export() if s["name"].startswith("update.")]
    assert {s["name"] for s in spans} == {f"update.{st}" for st in STAGES}
    assert {s["trace_id"] for s in spans} == {trace_id}
    broadcast = next(s for s in spans if s["name"] == "update.broadcast")
    e2e_ms = broadcast["attributes"]["e2e_ms"]
    stage_sum = sum(s["duration_ms"] for s in spans)
    assert stage_sum == pytest.approx(e2e_ms, abs=0.01)
    # every stage observed once, plus the total series
    for stage in STAGES:
        assert hist.series_count(stage=stage) == 1
    assert hist.series_count(stage="total") == 1


def test_trace_sampling_one_in_n():
    plane, tracer = _fresh_traced_plane()
    tracer.sample = 4
    plane.register("sampled")
    ids = [plane.note_trace("sampled") for _ in range(8)]
    stamped = [i for i in ids if i is not None]
    assert len(stamped) == 2
    assert ids[0] is not None  # the first update is always sampled


def test_trace_book_drops_on_retire():
    plane, tracer = _fresh_traced_plane()
    plane.register("doomed")
    plane.enqueue_update("doomed", _make_update())
    assert plane.note_trace("doomed") is not None
    plane.retire_doc("doomed", "capacity")
    assert not plane.update_traces.active()


def test_trace_book_disabled_costs_nothing():
    from hocuspocus_tpu.tpu.merge_plane import MergePlane

    plane = MergePlane(num_docs=4, capacity=128)
    plane.update_traces.tracer = Tracer(enabled=False)
    plane.register("quiet")
    plane.enqueue_update("quiet", _make_update())
    assert plane.note_trace("quiet") is None
    assert not plane.update_traces.active()
    plane.flush()
    assert plane.update_traces.finish("quiet") == 0


async def test_ingress_mark_is_isolated_per_dispatch_task():
    """Concurrent dispatches from different sockets run as different
    asyncio tasks whose hook chains await mid-dispatch: one task's
    ingress mark must never be adopted or cleared by another's
    (regression: the mark was once a shared tracer attribute)."""
    import asyncio

    tracer = Tracer(enabled=True)
    observed = {}

    async def dispatch(name: str, mark: float) -> None:
        tracer.ingress_mark = mark
        try:
            await asyncio.sleep(0.01)  # hook-chain await: tasks interleave
            observed[name] = tracer.ingress_mark
        finally:
            tracer.ingress_mark = None

    await asyncio.gather(dispatch("a", 111.0), dispatch("b", 222.0))
    assert observed == {"a": 111.0, "b": 222.0}
    assert tracer.ingress_mark is None


# -- Perfetto / Chrome trace export --------------------------------------------


def test_chrome_trace_export_schema():
    tracer = Tracer(enabled=True, max_spans=64)
    with tracer.span("outer", doc="d") as sp:
        sp.set("bytes", 12)
    tracer.event("instant.thing", detail="x")
    tracer.add_span("staged", 1.0, 1.5, trace_id=42, doc="d")

    trace = tracer.export_chrome_trace()
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    # the CPU profiler's recent-sample ring merges into the export when
    # the process-global profiler is running (its own schema, asserted
    # in test_profiler_costs.py) — the span schema below is about the
    # tracer's events only
    events = [
        e
        for e in trace["traceEvents"]
        if e.get("cat") != "profiler"
    ]
    # metadata record + three spans
    assert len(events) == 4
    assert events[0]["ph"] == "M"
    for event in events:
        assert {"name", "ph", "pid", "tid"} <= set(event)
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert {e["name"] for e in complete} == {"outer", "staged"}
    assert [e["name"] for e in instants] == ["instant.thing"]
    for event in complete:
        assert event["dur"] >= 0
        assert isinstance(event["ts"], float)
    staged = next(e for e in complete if e["name"] == "staged")
    assert staged["args"]["trace_id"] == 42
    assert staged["dur"] == pytest.approx(0.5e6)
    json.loads(json.dumps(trace))  # valid JSON end to end


# -- slow spans ----------------------------------------------------------------


def test_slow_spans_promoted_even_after_ring_wrap():
    tracer = Tracer(enabled=True, max_spans=2)
    tracer.slow_ms = 0.0  # everything is slow
    seen = []
    tracer.on_slow.append(lambda sp: seen.append(sp.name))
    for i in range(5):
        with tracer.span(f"site{i}"):
            pass
    assert len(tracer) == 2  # ring wrapped...
    assert len(seen) == 5  # ...but every slow span was promoted


def test_slow_span_threshold_filters():
    tracer = Tracer(enabled=True)
    tracer.slow_ms = 10_000.0
    hits = []
    tracer.on_slow.append(hits.append)
    with tracer.span("fast"):
        pass
    assert hits == []
    tracer.add_span("synthetic", 0.0, 20.0)  # 20s
    assert [sp.name for sp in hits] == ["synthetic"]


# -- enable_tracing ring preservation ------------------------------------------


def test_enable_tracing_preserves_ring_size_on_repeat_calls():
    tracer = enable_tracing(max_spans=16)
    try:
        assert tracer._spans.maxlen == 16
        again = enable_tracing()  # no size given: must NOT rebuild
        assert again is tracer
        assert tracer._spans.maxlen == 16
        enable_tracing(max_spans=32)
        assert tracer._spans.maxlen == 32
    finally:
        disable_tracing()
        tracer.clear()
        enable_tracing(max_spans=4096)
        disable_tracing()


# -- labelled histograms -------------------------------------------------------


def test_histogram_labels_exposition_and_bisect_buckets():
    hist = Histogram("stage_seconds", "Stage latency", buckets=(0.01, 0.1, 1.0))
    hist.observe(0.005, stage="build")
    hist.observe(0.05, stage="build")
    hist.observe(0.5, stage="device")
    hist.observe(0.1, stage="device")  # exactly on a bound: le-inclusive
    lines = list(hist.expose())
    assert 'stage_seconds_bucket{le="0.01",stage="build"} 1' in lines
    assert 'stage_seconds_bucket{le="0.1",stage="build"} 2' in lines
    assert 'stage_seconds_bucket{le="+Inf",stage="build"} 2' in lines
    assert 'stage_seconds_bucket{le="0.1",stage="device"} 1' in lines
    assert 'stage_seconds_bucket{le="1",stage="device"} 2' in lines
    assert 'stage_seconds_count{stage="build"} 2' in lines
    assert 'stage_seconds_count{stage="device"} 2' in lines
    assert hist.count == 4  # aggregate across series
    assert hist.series_count(stage="build") == 2


def test_histogram_unlabelled_stays_compatible():
    reg = MetricsRegistry()
    hist = reg.histogram("lat_seconds", "Latency", buckets=(0.01, 0.1, 1.0))
    for value in (0.005, 0.05, 0.5, 5.0):
        hist.observe(value)
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_count 4" in text


def test_histogram_quantile_interpolation():
    hist = Histogram("q_seconds", "", buckets=(0.1, 0.2, 0.4))
    for _ in range(100):
        hist.observe(0.15, stage="s")
    q50 = hist.quantile(0.5, stage="s")
    assert 0.1 <= q50 <= 0.2
    # missing/empty series: the documented 0.0 sentinel, never an
    # exception — callers that must distinguish "no data" guard on
    # series_count first (FleetView rollups skip empty peers entirely)
    assert hist.quantile(0.5, stage="missing") == 0.0
    assert hist.series_count(stage="missing") == 0


def test_histogram_quantile_degenerate_labelsets_return_sentinel():
    """The satellite guard: empty or single/zero-bucket label sets must
    return the documented 0.0 sentinel (or the last finite bound when
    every observation overflows it) instead of degenerate bisect
    behavior."""
    # no finite buckets at all: every observation lands in +Inf and no
    # bound can localize a quantile — sentinel, not None/IndexError
    unbucketed = Histogram("raw_seconds", "", buckets=())
    unbucketed.observe(0.5, stage="s")
    assert unbucketed.quantile(0.99, stage="s") == 0.0
    assert unbucketed.quantile(0.5) == 0.0  # missing unlabelled series
    # single bucket: in-range mass interpolates within [0, bound]...
    single = Histogram("one_seconds", "", buckets=(0.1,))
    for _ in range(10):
        single.observe(0.05, stage="s")
    assert 0.0 <= single.quantile(0.5, stage="s") <= 0.1
    # ...and overflow mass reports the last finite bound (the best the
    # bucket resolution can say), never an index past the bucket list
    overflow = Histogram("over_seconds", "", buckets=(0.1,))
    for _ in range(10):
        overflow.observe(5.0, stage="s")
    assert overflow.quantile(0.99, stage="s") == 0.1


# -- _fmt_value ----------------------------------------------------------------


def test_fmt_value_shortest_round_trip():
    assert _fmt_value(0.25) == "0.25"
    assert _fmt_value(0.1) == "0.1"
    assert _fmt_value(3.0) == "3"
    assert _fmt_value(float("inf")) == "+Inf"
    assert _fmt_value(float("-inf")) == "-Inf"
    assert _fmt_value(1e-09) in ("1e-09", "1e-9")
    # accumulated float error keeps only the digits it needs — and the
    # output always parses back to the exact same double
    for value in (0.1 + 0.2, 1 / 3, 2.5e-7, 123456.789, 1e300):
        text = _fmt_value(value)
        assert float(text) == value
        mantissa = text.split("e")[0].lstrip("-0.")
        assert sum(c.isdigit() for c in mantissa) <= 17  # ≤17 significant digits
    # and never MORE digits than the value needs: a shorter string that
    # still round-trips must not exist
    assert _fmt_value(0.1 + 0.2) == "0.30000000000000004"
    assert _fmt_value(0.5) == "0.5"


# -- Prometheus exposition conformance -----------------------------------------


def _parse_exposition(body: str):
    """-> (families: name -> {help, type, samples}), asserting the
    HELP -> TYPE -> samples ordering per family as it parses."""
    families: dict = {}
    current = None
    for line in body.splitlines():
        if not line or line.startswith("# tracer"):
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"help": line, "type": None, "samples": []}
            current = name
        elif line.startswith("# TYPE "):
            name = line.split()[2]
            assert name == current, f"TYPE {name} not directly after its HELP"
            assert families[name]["type"] is None
            families[name]["type"] = line.split()[3]
        elif line.startswith("#"):
            continue
        else:
            sample_name = line.split("{")[0].split()[0]
            assert current is not None and sample_name.startswith(current), line
            assert families[current]["type"] is not None, line  # TYPE before samples
            families[current]["samples"].append(line)
    return families


def _bucket_series(samples: list[str]):
    """bucket samples -> {labels-without-le: [(le, cumulative)]}"""
    import re

    series: dict = {}
    for line in samples:
        if "_bucket{" not in line:
            continue
        labels_part = line[line.index("{") + 1 : line.rindex("}")]
        value = float(line.rsplit(None, 1)[1])
        labels = dict(
            (m.group(1), m.group(2))
            for m in re.finditer(r'(\w+)="((?:[^"\\]|\\.)*)"', labels_part)
        )
        le = labels.pop("le")
        key = tuple(sorted(labels.items()))
        series.setdefault(key, []).append(
            (float("inf") if le == "+Inf" else float(le), value)
        )
    return series


async def test_metrics_scrape_is_prometheus_conformant():
    """Full /metrics scrape: HELP/TYPE ordering, label escaping,
    histogram bucket monotonicity with a labelled histogram live."""
    metrics = Metrics()
    # exercise escaping + labelled series before the scrape
    metrics.registry.counter("esc_total", "Escapes").inc(
        label='quote " backslash \\ newline \n end'
    )
    metrics.update_e2e.observe(0.003, stage="build")
    metrics.update_e2e.observe(0.5, stage="build")
    metrics.update_e2e.observe(0.02, stage="device")
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="conformance")
    try:
        await wait_synced(provider)
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                assert response.status == 200
                body = await response.text()
    finally:
        provider.destroy()
        await server.destroy()

    families = _parse_exposition(body)
    # every family has HELP, then TYPE, then at least one sample
    for name, family in families.items():
        assert family["type"] in ("counter", "gauge", "histogram"), name
        assert family["samples"], name
    # escaping: backslash, quote and newline all escaped in the output
    esc_line = next(s for s in families["esc_total"]["samples"] if "{" in s)
    assert '\\"' in esc_line and "\\\\" in esc_line and "\\n" in esc_line
    assert "\n" not in esc_line  # the raw newline never leaks
    # histogram bucket monotonicity (every labelled series, le ascending)
    histo_families = [f for n, f in families.items() if f["type"] == "histogram"]
    assert histo_families
    checked = 0
    for family in histo_families:
        for key, buckets in _bucket_series(family["samples"]).items():
            assert buckets == sorted(buckets, key=lambda b: b[0]), key
            values = [v for _, v in buckets]
            assert values == sorted(values), (key, values)
            assert buckets[-1][0] == float("inf")
            checked += 1
    assert checked >= 3
    # the labelled e2e histogram made it into the exposition
    assert any(
        'stage="build"' in s
        for s in families["hocuspocus_tpu_update_e2e_seconds"]["samples"]
    )


# -- flight recorder -----------------------------------------------------------


def test_flight_recorder_bounded_rings_and_lru():
    recorder = FlightRecorder(max_docs=2, max_events=3)
    for i in range(5):
        recorder.record("a", f"e{i}")
    assert len(recorder.events("a")) == 3  # per-doc ring bounded
    assert recorder.events("a")[-1]["event"] == "e4"
    recorder.record("b", "x")
    recorder.record("c", "y")  # evicts the least-recently-eventful doc
    assert len(recorder) == 2
    assert recorder.events("a") == []
    assert recorder.evicted_docs == 1
    summary = recorder.docs()
    assert summary[0]["doc"] == "c"  # most recent first
    assert summary[0]["last_event"] == "y"


def test_flight_recorder_records_plane_lifecycle():
    from hocuspocus_tpu.tpu.merge_plane import MergePlane

    recorder = get_flight_recorder()
    recorder.forget("fr-doc")
    plane = MergePlane(num_docs=4, capacity=128)
    plane.register("fr-doc")
    plane.enqueue_update("fr-doc", _make_update())
    plane.retire_doc("fr-doc", "capacity")
    events = [e["event"] for e in recorder.events("fr-doc")]
    assert "retire" in events
    retire = next(e for e in recorder.events("fr-doc") if e["event"] == "retire")
    assert retire["reason"] == "capacity"


# -- live server: /debug endpoints + acceptance flow ---------------------------


async def test_traced_update_served_from_debug_endpoints():
    """Acceptance: with tracing enabled, a single client update produces
    a causally-linked trace retrievable from /debug/trace as valid
    Chrome trace-event JSON — including the update.ingress stage, since
    the update arrived through the websocket edge — and
    hocuspocus_tpu_update_e2e_seconds appears in /metrics with
    per-stage labels; the flight recorder answers /debug/docs and
    /debug/docs/<name>. The span-sum invariant covers all SEVEN stages:
    they still sum exactly to the e2e latency, now measured from the
    frame receive."""
    from hocuspocus_tpu.tpu import TpuMergeExtension

    tracer = enable_tracing(max_spans=2048)
    tracer.clear()
    get_flight_recorder().forget("traced-live")
    ext = TpuMergeExtension(
        num_docs=8, capacity=512, flush_interval_ms=1,
        broadcast_interval_ms=1, serve=True,
    )
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics, ext])
    provider = new_provider(server, name="traced-live")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "trace me")

        def full_trace():
            spans = [
                s for s in tracer.export() if s["name"].startswith("update.")
            ]
            by_id: dict = {}
            for span in spans:
                by_id.setdefault(span["trace_id"], set()).add(span["name"])
            complete = [
                tid
                for tid, names in by_id.items()
                if names == {f"update.{st}" for st in WS_STAGES}
            ]
            assert complete, by_id
            return complete[0]

        trace_id = await retryable_assertion(full_trace)
        spans = [
            s
            for s in tracer.export()
            if s["name"].startswith("update.") and s["trace_id"] == trace_id
        ]
        broadcast = next(s for s in spans if s["name"] == "update.broadcast")
        assert sum(s["duration_ms"] for s in spans) == pytest.approx(
            broadcast["attributes"]["e2e_ms"], abs=0.01
        )

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/debug/trace") as response:
                assert response.status == 200
                trace = json.loads(await response.text())
            assert "traceEvents" in trace
            update_events = [
                e
                for e in trace["traceEvents"]
                if e["name"].startswith("update.")
                and e.get("args", {}).get("trace_id") == trace_id
            ]
            assert len(update_events) == len(WS_STAGES)
            for event in update_events:
                assert event["ph"] in ("X", "i")
                assert "ts" in event and "pid" in event and "tid" in event

            async with session.get(f"{server.http_url}/metrics") as response:
                body = await response.text()
            assert 'hocuspocus_tpu_update_e2e_seconds_bucket{le=' in body
            for stage in WS_STAGES + ("total",):
                assert f'stage="{stage}"' in body

            async with session.get(
                f"{server.http_url}/debug/docs/traced-live"
            ) as response:
                doc_events = json.loads(await response.text())
            assert doc_events["doc"] == "traced-live"
            assert "load" in [e["event"] for e in doc_events["events"]]

            async with session.get(f"{server.http_url}/debug/docs") as response:
                overview = json.loads(await response.text())
            assert "busiest" in overview and "docs" in overview
            assert any(d["doc"] == "traced-live" for d in overview["docs"])
    finally:
        disable_tracing()
        tracer.clear()
        provider.destroy()
        await server.destroy()


async def test_slow_span_counter_in_metrics():
    """--trace-slow-ms promotion lands in the labelled slow-span counter
    on /metrics even with a tiny (always-wrapping) ring."""
    tracer = enable_tracing(max_spans=4)
    tracer.clear()
    tracer.slow_ms = 0.0  # promote everything
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="slow-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")

        def promoted():
            assert metrics.slow_spans.value(site="message.apply") >= 1

        await retryable_assertion(promoted)
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                body = await response.text()
        assert 'hocuspocus_tpu_slow_spans_total{site="message.apply"}' in body
    finally:
        tracer.slow_ms = None
        disable_tracing()
        tracer.clear()
        provider.destroy()
        await server.destroy()


# -- tracing overhead guard ----------------------------------------------------


@pytest.mark.slow
def test_tracing_overhead_on_sparse_flush_under_5_percent():
    """Disabled-vs-enabled tracing on a miniature sparse-load flush
    loop: the lifecycle pipeline must stay within the 5% overhead
    budget (the acceptance bound for the sparse-load bench)."""
    import time

    import numpy as np

    from hocuspocus_tpu.tpu.kernels import KIND_INSERT, NONE_CLIENT
    from hocuspocus_tpu.tpu.lowering import DenseOp
    from hocuspocus_tpu.tpu.merge_plane import MergePlane

    num_docs, busy, ops_per_doc, run = 256, 8, 4, 8

    def build(traced: bool):
        plane = MergePlane(num_docs=num_docs, capacity=4096, max_slots_per_flush=4)
        plane.update_traces.tracer = Tracer(enabled=traced, max_spans=512)
        slots = []
        for d in range(num_docs):
            doc = plane.register(f"d{d}")
            slots.append(plane._alloc_seq(doc, ("root", "t")))
        plane.warmup_compiles((plane._k_buckets()[-1], plane._bucket_b(busy)))
        return plane, slots, np.zeros(num_docs, np.int64)

    def run_cycles(plane, slots, clocks, traced: bool, cycles: int) -> float:
        rng = np.random.default_rng(7)
        start = time.perf_counter()
        for _ in range(cycles):
            subset = rng.choice(num_docs, size=busy, replace=False)
            for s in subset:
                slot = slots[s]
                queue = plane.queues[slot]
                for _ in range(ops_per_doc):
                    clock = int(clocks[s])
                    queue.append(
                        DenseOp(
                            kind=KIND_INSERT, client=7, clock=clock, run_len=run,
                            left_client=7 if clock else NONE_CLIENT,
                            left_clock=clock - 1 if clock else 0,
                        )
                    )
                    clocks[s] += run
                plane.projected_len[slot] += ops_per_doc * run
                plane._busy_slots.add(slot)
                if traced:
                    plane.note_trace(f"d{s}")
            plane.flush()
            if traced:
                plane.update_traces.finish_all()
        return time.perf_counter() - start

    cycles = 40
    best = {False: float("inf"), True: float("inf")}
    for _ in range(3):
        for traced in (False, True):
            plane, slots, clocks = build(traced)
            run_cycles(plane, slots, clocks, traced, 4)  # warm
            best[traced] = min(
                best[traced], run_cycles(plane, slots, clocks, traced, cycles)
            )
    # 5% relative budget plus a tiny absolute floor for timer noise
    assert best[True] <= best[False] * 1.05 + 0.005, best
