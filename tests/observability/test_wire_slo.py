"""Socket-to-silicon observability: wire-path telemetry, the SLO
burn-rate engine, build-info/exposition conformance and connection-churn
coverage.

The reference has no metrics at all (SURVEY.md §5.5); these tests cover
the observation boundary this build extends in both directions — from
the capture seam out to the websocket edge, and up into the SLO layer
that decides "healthy enough for millions of users".
"""

from __future__ import annotations

import asyncio
import json

import aiohttp
import pytest

from hocuspocus_tpu.observability import (
    Counter,
    Gauge,
    Histogram,
    Metrics,
    MetricsRegistry,
    SloEngine,
    counter_ratio_slo,
    fraction_slo,
    get_flight_recorder,
    get_wire_telemetry,
    latency_slo,
)

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _totals(counter: Counter) -> float:
    return sum(counter._values.values())


# -- wire-path telemetry (live server) -----------------------------------------


async def test_wire_counters_cover_ingress_egress_and_sync_steps():
    """A provider's sync handshake + one edit light the per-MessageType
    ingress/egress counters, byte counters, handle-latency histogram
    and the sync-step latency histogram."""
    wire = get_wire_telemetry()
    before_in = _totals(wire.messages_in)
    before_out = _totals(wire.messages_out)
    before_bytes_in = _totals(wire.bytes_in)
    handle_before = wire.handle_seconds.count
    step1_before = wire.sync_step_seconds.series_count(step="step1")
    update_before = wire.sync_step_seconds.series_count(step="update")
    auth_before = wire.auth_seconds.series_count(outcome="ok")

    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="wire-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "wire me")

        def counted():
            assert wire.sync_step_seconds.series_count(step="update") > update_before

        await retryable_assertion(counted)
    finally:
        provider.destroy()
        await server.destroy()

    assert _totals(wire.messages_in) > before_in
    assert _totals(wire.messages_out) > before_out
    assert _totals(wire.bytes_in) > before_bytes_in
    assert wire.handle_seconds.count > handle_before
    # the handshake exercised SyncStep1 and the auth hook chain
    assert wire.sync_step_seconds.series_count(step="step1") > step1_before
    assert wire.auth_seconds.series_count(outcome="ok") > auth_before
    # per-type labels exist (Sync rides the handshake + the edit)
    assert wire.messages_in.value(type="Sync") > 0


async def test_connection_churn_close_codes_and_no_queue_leaks():
    """Connection churn (sockets opened/closed by close code) is
    counted, the send-queue depth gauge returns to zero after an abrupt
    mid-session disconnect, and no transport leaks into the gauge's
    tracked set (counter-leak regression for mid-message disconnects)."""
    wire = get_wire_telemetry()
    opened_before = _totals(wire.sockets_opened)
    closed_before = _totals(wire.sockets_closed)

    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    providers = [new_provider(server, name=f"churn-{i}") for i in range(3)]
    try:
        await wait_synced(*providers)
        for i, provider in enumerate(providers):
            provider.document.get_text("t").insert(0, f"edit {i}")
        # abrupt teardown with edits potentially still in flight
        for provider in providers:
            provider.destroy()

        def churned():
            opened = _totals(wire.sockets_opened) - opened_before
            closed = _totals(wire.sockets_closed) - closed_before
            assert opened >= 3
            # every socket this test opened was also counted closed —
            # nothing leaks open in the churn accounting
            assert closed >= opened

        await retryable_assertion(churned)
    finally:
        for provider in providers:
            provider.destroy()
        await server.destroy()

    def drained():
        # the depth gauge reads live queues: after every socket died,
        # it must return to zero (no stranded transports in the gauge)
        assert wire.send_queue_depth.value() == 0

    await retryable_assertion(drained)
    # close codes are labelled: at least one labelled series exists and
    # every label parses as an integer close code
    codes = [dict(key).get("code") for key in wire.sockets_closed._values]
    assert codes
    assert all(code is None or code.lstrip("-").isdigit() for code in codes)


async def test_flight_recorder_connect_disconnect_audience_history():
    """GET /debug/docs/<name> shows connect/disconnect events with the
    resulting connection count, next to the merge history."""
    recorder = get_flight_recorder()
    recorder.forget("audience-doc")
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="audience-doc")
    second = None
    try:
        await wait_synced(provider)
        second = new_provider(server, name="audience-doc")
        await wait_synced(second)

        def connected_twice():
            events = [
                e for e in recorder.events("audience-doc") if e["event"] == "connect"
            ]
            assert len(events) >= 2
            return events

        events = await retryable_assertion(connected_twice)
        assert events[-1]["connections"] == 2
        second.destroy()

        def disconnected():
            events = [
                e
                for e in recorder.events("audience-doc")
                if e["event"] == "disconnect"
            ]
            assert events
            return events

        events = await retryable_assertion(disconnected)
        assert events[-1]["connections"] == 1

        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{server.http_url}/debug/docs/audience-doc"
            ) as response:
                payload = json.loads(await response.text())
        kinds = [e["event"] for e in payload["events"]]
        assert "connect" in kinds and "disconnect" in kinds
    finally:
        provider.destroy()
        if second is not None:
            second.destroy()
        await server.destroy()


async def test_mini_redis_pubsub_fanout_counters():
    from hocuspocus_tpu.net.mini_redis import MiniRedis

    wire = get_wire_telemetry()
    wire.enable()
    publishes_before = _totals(wire.pubsub_publishes)
    deliveries_before = _totals(wire.pubsub_deliveries)
    dropped_before = _totals(wire.pubsub_dropped)

    redis = await MiniRedis().start()
    try:
        sub_reader, sub_writer = await asyncio.open_connection("127.0.0.1", redis.port)
        sub_writer.write(b"*2\r\n$9\r\nSUBSCRIBE\r\n$4\r\nchan\r\n")
        await sub_writer.drain()
        await sub_reader.readexactly(len(b"*3\r\n$9\r\nsubscribe\r\n$4\r\nchan\r\n:1\r\n"))

        pub_reader, pub_writer = await asyncio.open_connection("127.0.0.1", redis.port)
        pub_writer.write(b"*3\r\n$7\r\nPUBLISH\r\n$4\r\nchan\r\n$5\r\nhello\r\n")
        await pub_writer.drain()
        assert await pub_reader.readexactly(4) == b":1\r\n"

        # injected fault: the next publish vanishes and is counted
        redis.drop_publishes = 1
        pub_writer.write(b"*3\r\n$7\r\nPUBLISH\r\n$4\r\nchan\r\n$5\r\nlost!\r\n")
        await pub_writer.drain()
        assert await pub_reader.readexactly(4) == b":0\r\n"

        assert _totals(wire.pubsub_publishes) - publishes_before == 1
        assert _totals(wire.pubsub_deliveries) - deliveries_before == 1
        assert _totals(wire.pubsub_dropped) - dropped_before == 1
        sub_writer.close()
        pub_writer.close()
    finally:
        await redis.stop()


# -- SLO engine (unit) ---------------------------------------------------------


def _fake_clock():
    state = {"now": 0.0}

    def advance(seconds: float) -> None:
        state["now"] += seconds

    return (lambda: state["now"]), advance


def test_slo_burn_rate_multi_window():
    """30% of events bad against a 1% budget -> burn 30 on both windows
    once an hour of samples exists; the multi-window rule breaches."""
    clock, advance = _fake_clock()
    hist = Histogram("h", "", buckets=(0.01, 0.05, 0.1))
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(latency_slo("e2e", hist, threshold_s=0.05, objective=0.99))
    for _ in range(250):
        advance(15.0)
        for _ in range(70):
            hist.observe(0.005, stage="total")
        for _ in range(30):
            hist.observe(0.5, stage="total")
        engine.sample()
    status = engine.status()
    windows = status["slos"]["e2e"]["windows"]
    assert windows["5m"]["burn_rate"] == pytest.approx(30.0, rel=0.01)
    assert windows["1h"]["burn_rate"] == pytest.approx(30.0, rel=0.01)
    assert windows["5m"]["covered_s"] == pytest.approx(300.0, abs=16)
    assert status["slos"]["e2e"]["breaching"] is True
    assert status["healthy"] is False
    # gauges updated at sample time, labelled per (slo, window)
    assert engine.burn_gauge.value(slo="e2e", window="5m") == pytest.approx(
        30.0, rel=0.01
    )


def test_slo_short_burst_does_not_breach_long_window():
    """A 5-minute error burst trips the short window but not the hour
    window -> no breach (the multi-window rule suppresses blips)."""
    clock, advance = _fake_clock()
    total, bad = Counter("t", ""), Counter("b", "")
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(counter_ratio_slo("err", total, bad, objective=0.99))
    for tick in range(240):  # one hour, clean
        advance(15.0)
        total.inc(100)
        engine.sample()
    for tick in range(20):  # five minutes, 100% bad
        advance(15.0)
        total.inc(100)
        bad.inc(100)
        engine.sample()
    status = engine.status()["slos"]["err"]
    assert status["windows"]["5m"]["burn_rate"] > 14.4
    assert status["windows"]["1h"]["burn_rate"] < 14.4
    assert status["breaching"] is False


def test_slo_no_traffic_reports_none_and_never_breaches():
    clock, advance = _fake_clock()
    hist = Histogram("h", "")
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(latency_slo("quiet", hist, threshold_s=0.05))
    for _ in range(10):
        advance(15.0)
        engine.sample()
    status = engine.status()["slos"]["quiet"]
    assert status["windows"]["5m"]["burn_rate"] is None
    assert status["breaching"] is False
    assert engine.status()["healthy"] is True


def test_slo_fraction_probe_counts_sampled_time():
    clock, advance = _fake_clock()
    state = {"open": False}
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(fraction_slo("breaker", lambda: state["open"], objective=0.99))
    for tick in range(40):
        state["open"] = tick >= 20  # open for the second half
        advance(15.0)
        engine.sample()
    stat = engine.status()["slos"]["breaker"]["windows"]["5m"]
    # the last 5 minutes were fully open -> error rate 1.0, burn 100
    assert stat["error_rate"] == pytest.approx(1.0)
    assert stat["burn_rate"] == pytest.approx(100.0)


def test_latency_slo_threshold_snaps_to_bucket_bound():
    """An off-bound threshold snaps to the nearest bucket bound —
    counting is exact at bounds and silently wrong everywhere else —
    and the effective value is surfaced in the description."""
    from hocuspocus_tpu.observability.slo import snap_to_bucket

    hist = Histogram("h", "", buckets=(0.01, 0.05, 0.1))
    assert snap_to_bucket(hist, 0.06) == 0.05
    assert snap_to_bucket(hist, 0.09) == 0.1
    assert snap_to_bucket(hist, 0.05) == 0.05
    target = latency_slo("snapped", hist, threshold_s=0.06)
    assert "snapped from 60ms" in target.description
    # observations in (0.05, 0.06] would be miscounted at an unsnapped
    # threshold; at the snapped 0.05 bound they are honestly bad
    for _ in range(10):
        hist.observe(0.02, stage="total")
    total, bad = target.collect()
    assert (total, bad) == (10, 0)


async def test_redis_bus_messages_excluded_from_wire_ingress():
    """Messages applied with connection=None (the redis fan-out path)
    must not inflate the wire error-rate denominator."""
    from hocuspocus_tpu.crdt import Doc, encode_state_as_update
    from hocuspocus_tpu.protocol.message import IncomingMessage, OutgoingMessage
    from hocuspocus_tpu.server.document import Document
    from hocuspocus_tpu.server.message_receiver import MessageReceiver

    wire = get_wire_telemetry()
    wire.enable()
    before = _totals(wire.messages_in)

    source = Doc()
    source.get_text("t").insert(0, "bus")
    frame = (
        OutgoingMessage("bus-doc")
        .create_sync_message()
        .write_update(encode_state_as_update(source))
    )
    message = IncomingMessage(frame.to_bytes())
    message.read_var_string()  # document name, as the redis path does
    document = Document("bus-doc")
    await MessageReceiver(message).apply(document, None, reply=lambda data: None)
    assert str(document.get_text("t")) == "bus"
    assert _totals(wire.messages_in) == before  # not counted


def test_egress_frame_parse_cached_by_identity():
    """One broadcast frame sent to N connections parses its header
    once; a different frame re-parses."""
    from hocuspocus_tpu.protocol.frames import build_update_frame
    from hocuspocus_tpu.observability.wire import WireTelemetry

    wire = WireTelemetry()
    wire.enable()
    frame = build_update_frame("doc", b"\x00\x00")
    for _ in range(5):
        wire.record_egress_frame(frame)
    assert wire.messages_out.value(type="Sync") == 5
    assert wire._egress_last_frame is frame
    other = build_update_frame("doc", b"\x01\x00")
    wire.record_egress_frame(other)
    assert wire._egress_last_frame is other
    assert wire.messages_out.value(type="Sync") == 6


def test_slo_maybe_sample_respects_cadence():
    clock, advance = _fake_clock()
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(fraction_slo("x", lambda: False))
    assert engine.maybe_sample() is True
    assert engine.maybe_sample() is False  # same instant
    advance(5.0)
    assert engine.maybe_sample() is False  # under the cadence
    advance(15.0)
    assert engine.maybe_sample() is True


# -- /debug/slo + health folding (live server) ---------------------------------


async def test_debug_slo_endpoint_and_health_agree():
    """Acceptance: GET /debug/slo returns computed 5m/1h burn rates for
    the e2e-latency and error-rate SLOs, and /healthz folds the same
    verdict into the health payload."""
    metrics = Metrics(slo_sample_interval_s=0.0)  # sample on every read
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="slo-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "healthy traffic")
        await asyncio.sleep(0)
        metrics.slo.maybe_sample()  # anchor sample
        provider.document.get_text("t").insert(5, " more")

        def more_messages():
            # traffic must exist between two samples for a window delta
            assert get_wire_telemetry().messages_in.value(type="Sync") > 0

        await retryable_assertion(more_messages)

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/debug/slo") as response:
                assert response.status == 200
                payload = json.loads(await response.text())

        assert payload["healthy"] is True
        for name in ("update_e2e_latency", "wire_error_rate"):
            slo = payload["slos"][name]
            assert set(slo["windows"]) == {"5m", "1h"}
            assert "burn_rate" in slo["windows"]["5m"]
            assert "burn_rate" in slo["windows"]["1h"]
            assert slo["breaching"] is False
        # the error-rate SLO saw real traffic and computed a number
        err_5m = payload["slos"]["wire_error_rate"]["windows"]["5m"]
        assert err_5m["total"] > 0
        assert err_5m["burn_rate"] is not None

        # health folding: the Metrics extension contributes an SLO
        # section and the top-level verdict agrees
        health = server.hocuspocus.get_health()
        assert health["status"] == "ok"
        slo_health = health["extensions"]["Metrics"]
        assert slo_health["state"] == "ok"
        assert slo_health["degraded"] is False
        assert "update_e2e_latency" in slo_health["slos"]
    finally:
        provider.destroy()
        await server.destroy()


async def test_breaching_slo_degrades_health():
    """A sustained burning SLO downgrades get_health() to degraded —
    the SLO story and the supervisor/healthz story agree."""
    metrics = Metrics(slo_sample_interval_s=0.0)
    server = await new_hocuspocus(extensions=[metrics])
    try:
        await server.hocuspocus.ensure_configured()
        # synthetic sustained burn: a fake clock walks a full hour of
        # bad samples (coverage-gated breaching needs real history)
        clock, advance = _fake_clock()
        metrics.slo._clock = clock
        metrics.slo.sample_interval_s = 15.0
        total, bad = Counter("syn_t", ""), Counter("syn_b", "")
        metrics.slo.add(counter_ratio_slo("synthetic_burn", total, bad, objective=0.99))
        for _ in range(250):
            advance(15.0)
            total.inc(100)
            bad.inc(100)
            metrics.slo.sample()
        health = server.hocuspocus.get_health()
        assert health["status"] == "degraded"
        assert "synthetic_burn" in health["extensions"]["Metrics"]["breaching"]
    finally:
        await server.destroy()


def test_startup_blip_cannot_breach_without_full_window_coverage():
    """60s after boot, an error burst must NOT mark the server degraded:
    the 1h window has no coverage yet, so it can't vote — a load
    balancer must never drain a freshly restarted instance over a
    transient reconnect blip."""
    clock, advance = _fake_clock()
    total, bad = Counter("t", ""), Counter("b", "")
    engine = SloEngine(sample_interval_s=15.0, clock=clock)
    engine.add(counter_ratio_slo("err", total, bad, objective=0.999))
    for _ in range(4):  # one minute of uptime, 2% errors (burn 20)
        advance(15.0)
        total.inc(25)
        bad.inc(1)
        engine.sample()
    status = engine.status()["slos"]["err"]
    assert status["windows"]["1h"]["burn_rate"] is not None  # burning...
    assert status["windows"]["1h"]["covered_s"] < 3600
    assert status["breaching"] is False  # ...but can't page yet
    # once a full hour of sustained burn exists, it DOES page
    for _ in range(240):
        advance(15.0)
        total.inc(25)
        bad.inc(1)
        engine.sample()
    assert engine.status()["slos"]["err"]["breaching"] is True


# -- build info, exposition conformance ----------------------------------------


async def test_build_info_and_exposition_content_type():
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/metrics") as response:
                assert response.status == 200
                content_type = response.headers["Content-Type"]
                body = await response.text()
        # Prometheus text exposition format version on the wire
        assert "text/plain" in content_type
        assert "version=0.0.4" in content_type
        build_line = next(
            line
            for line in body.splitlines()
            if line.startswith("hocuspocus_tpu_build_info{")
        )
        assert 'version="' in build_line
        assert 'backend="' in build_line
        assert 'device_count="' in build_line
        assert build_line.endswith(" 1")
        # wire + SLO families made it into the exposition
        assert "hocuspocus_wire_messages_in_total" in body
        assert "hocuspocus_tpu_slo_burn_rate" in body
        assert "hocuspocus_tpu_compile_seconds" in body
    finally:
        await server.destroy()


def test_exposition_order_is_deterministic():
    """Labelled series render sorted regardless of insertion order, so
    consecutive scrapes diff cleanly."""
    def build(order):
        reg = MetricsRegistry()
        counter = reg.counter("zz_total", "Z")
        gauge = reg.gauge("aa_current", "A")
        for label in order:
            counter.inc(3, shard=label)
            gauge.set(1.0, slo=label, window="5m")
        return reg.expose()

    forward = build(["a", "b", "c"])
    backward = build(["c", "b", "a"])
    assert forward == backward
    lines = [l for l in forward.splitlines() if not l.startswith("#")]
    assert lines == sorted(lines)  # names + sorted labels sort stably


def test_gauge_label_series():
    gauge = Gauge("g", "labelled gauge")
    gauge.set(2.5, slo="a", window="5m")
    gauge.set(1.0, window="1h", slo="a")  # kwargs order must not matter
    gauge.inc(0.5, slo="a", window="1h")
    assert gauge.value(slo="a", window="5m") == 2.5
    assert gauge.value(slo="a", window="1h") == 1.5
    lines = list(gauge.expose())
    assert 'g{slo="a",window="1h"} 1.5' in lines
    assert 'g{slo="a",window="5m"} 2.5' in lines
    # unlabelled compatibility: fresh gauge still exposes a zero sample
    empty = Gauge("e", "")
    assert list(empty.expose())[-1] == "e 0"


def test_registry_register_adopts_and_rejects_collisions():
    reg = MetricsRegistry()
    counter = Counter("adopted_total", "")
    reg.register(counter)
    reg.register(counter)  # same object: idempotent
    counter.inc(2)
    assert "adopted_total 2" in reg.expose()
    with pytest.raises(ValueError):
        reg.register(Counter("adopted_total", ""))
