"""Sampling CPU profiler + per-frame cost ledger (ISSUE 19).

Covers the continuous host-path profiler (folded-stack determinism,
the one-burst-per-lag-episode latch, the <1% overhead guard), the cost
ledger's reconciliation against wire telemetry byte counters, the
`/debug/costs` + `/debug/profile/cpu` endpoints over real HTTP with the
PR-15 stamped header, the PR-6 deterministic metric registration pin,
and the headroom number riding on fleet digests.
"""

from __future__ import annotations

import re
import threading
import time

import aiohttp
import pytest

from hocuspocus_tpu.observability import Metrics, get_cost_ledger, get_profiler
from hocuspocus_tpu.observability.costs import CostLedger, LOOP_SITES
from hocuspocus_tpu.observability.flight_recorder import get_flight_recorder
from hocuspocus_tpu.observability.profiler import SamplingProfiler
from hocuspocus_tpu.observability.wire import get_wire_telemetry

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

_FOLDED_LINE = re.compile(r"^\S+ \d+$")


@pytest.fixture(autouse=True)
def _quiesce_profiler():
    """Metrics.on_configure starts the process-wide 99 Hz sampler and
    enables the cost ledger; quiesce both after each test here so
    perf-sensitive suites that run later (tracer overhead budgets)
    aren't sharing their GIL with the sampler or paying ledger
    record() on every frame."""
    yield
    get_profiler().stop()
    ledger = get_cost_ledger()
    ledger.disable()
    ledger.reset()


# -- profiler core -------------------------------------------------------------


def test_folded_stacks_deterministic_under_thread_churn():
    """Worker pools churn through numbered thread names; the folded
    table must aggregate them under digit-normalized roots, every line
    must stay `stack count`-parseable, and two reads of a quiesced
    profiler must be byte-identical (sorted output)."""
    profiler = SamplingProfiler(hz=500.0, ring_size=64)
    stop = threading.Event()

    def churn() -> None:
        while not stop.is_set():
            sum(i * i for i in range(200))
            time.sleep(0.001)

    threads = [
        threading.Thread(target=churn, name=f"Thread-{i}", daemon=True)
        for i in range(7, 12)
    ]
    for t in threads:
        t.start()
    profiler.start()
    try:
        deadline = time.time() + 5.0
        while profiler.stats()["samples"] < 20 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        profiler.stop()
        stop.set()
        for t in threads:
            t.join(timeout=2.0)

    text = profiler.collapsed()
    assert text, "no samples folded"
    lines = text.splitlines()
    assert all(_FOLDED_LINE.match(line) for line in lines), lines[:5]
    roots = {line.split(" ")[0].split(";")[0] for line in lines}
    # churn threads folded into ONE normalized root, not one per thread
    assert "Thread-N" in roots
    assert not any(re.search(r"\d", root) for root in roots), roots
    # deterministic: a quiesced profiler reads back byte-identical
    assert profiler.collapsed() == text
    assert profiler.stats()["samples"] >= 20


def test_burst_capture_fires_once_per_lag_episode():
    """The episode latch: repeated over-threshold lag readings produce
    ONE burst; re-arm happens only below half the threshold (the
    brownout ladder's hysteresis shape); each burst lands a
    `__profiler__` flight-recorder event with the top culprit stack."""
    profiler = SamplingProfiler(hz=0)  # steady sampler off; bursts only
    profiler.burst_s = 0.02
    profiler.burst_hz = 500.0
    profiler.burst_trigger_ms = 200.0
    recorder = get_flight_recorder()
    before = len(recorder.events("__profiler__"))

    def wait_burst_done() -> None:
        thread = profiler._burst_thread
        if thread is not None:
            thread.join(timeout=5.0)

    for _ in range(5):  # a whole episode of over-threshold ticks
        profiler.note_loop_lag(350.0)
    assert profiler.stats()["bursts_triggered"] == 1
    profiler.note_loop_lag(150.0)  # above half: still latched
    assert profiler.stats()["bursts_triggered"] == 1
    wait_burst_done()
    profiler.note_loop_lag(50.0)  # below half: re-armed
    profiler.note_loop_lag(400.0)  # next episode
    assert profiler.stats()["bursts_triggered"] == 2
    wait_burst_done()

    assert profiler.bursts_counter.value() == 2.0
    events = recorder.events("__profiler__")[before:]
    bursts = [e for e in events if e.get("event") == "lag_burst"]
    assert len(bursts) == 2
    assert bursts[0]["lag_ms"] == 350.0
    assert bursts[0]["samples"] > 0
    assert bursts[0]["top_stack"]  # the culprit stack rode along
    last = profiler.stats()["last_burst"]
    assert last is not None and last["lag_ms"] == 400.0


@pytest.mark.slow
def test_profiler_overhead_under_one_percent():
    """The always-on guard: at the default 99 Hz the measured sampling
    overhead (walk time / wall time) stays under 1% while threads are
    actually running."""
    profiler = SamplingProfiler(hz=99.0)
    stop = threading.Event()

    def busy() -> None:
        while not stop.is_set():
            sum(i for i in range(500))
            time.sleep(0.002)

    threads = [
        threading.Thread(target=busy, name=f"busy-{i}", daemon=True)
        for i in range(4)
    ]
    for t in threads:
        t.start()
    profiler.start()
    try:
        time.sleep(2.0)
    finally:
        profiler.stop()
        stop.set()
        for t in threads:
            t.join(timeout=2.0)
    overhead = profiler.overhead_fraction()
    assert profiler.stats()["samples"] > 50
    assert overhead < 0.01, f"profiler overhead {overhead:.4f} >= 1%"


# -- cost ledger ---------------------------------------------------------------


def test_headroom_model_sums_only_loop_sites():
    """Detail sites are slices INSIDE frame_decode and off-loop work
    runs on executor threads — neither may enter the headroom sum, or
    the model double-charges the frame."""
    ledger = CostLedger().enable()
    for site in LOOP_SITES:
        ledger.record(site, "Sync", 250_000)  # 0.25ms each -> 1ms/frame
    ledger.record("apply_update", "Sync", 10_000_000)  # inside decode
    ledger.record("wal_append", "Sync", 50_000_000)  # executor thread
    assert ledger.ingress_frames() == 1
    assert ledger.loop_ns_per_frame() == pytest.approx(1_000_000)
    assert ledger.headroom_frames_per_s() == pytest.approx(1000.0)
    table = ledger.table(wire=None)
    assert table["headroom_frames_per_s"] == 1000.0
    assert {row["site"] for row in table["rows"]} >= set(LOOP_SITES)


async def test_cost_ledger_bytes_reconcile_with_wire_counters():
    """Both frame_decode and wire ingress account THE SAME window and
    byte count in server/message_receiver.py — their per-type byte
    deltas over a live-traffic window must agree exactly."""
    ledger = get_cost_ledger()
    wire = get_wire_telemetry()
    metrics = Metrics()  # on_configure enables both
    server = await new_hocuspocus(extensions=[metrics])
    ledger_before = wire.bytes_in.value(type="Sync"), ledger.bytes.value(
        site="frame_decode", type="Sync"
    )
    provider = new_provider(server, name="cost-doc")
    try:
        await wait_synced(provider)
        for i in range(8):
            provider.document.get_text("t").insert(0, f"edit {i} ")

        def reconciles() -> None:
            wire_delta = wire.bytes_in.value(type="Sync") - ledger_before[0]
            ledger_delta = (
                ledger.bytes.value(site="frame_decode", type="Sync")
                - ledger_before[1]
            )
            assert wire_delta > 0
            assert ledger_delta == wire_delta
            # and the ledger attributed work below the decode (the
            # edits land as Update frames, so wait for the applies too)
            assert ledger.frames.value(site="apply_update", type="Sync") > 0

        await retryable_assertion(reconciles)
    finally:
        provider.destroy()
        await server.destroy()
        get_profiler().stop()  # don't leave the sampler on for later tests


async def test_debug_costs_and_cpu_profile_over_http():
    """`/debug/costs` and `/debug/profile/cpu` over real HTTP: stamped
    JSON payloads ({generated_utc, role, node_id} — the PR-15 header on
    the unified /debug/profile/{device,cpu} namespace), a populated cost
    table with positive headroom after traffic, and valid collapsed
    text under ?format=collapsed with the stamp in X- headers."""
    metrics = Metrics()
    server = await new_hocuspocus(extensions=[metrics])
    provider = new_provider(server, name="profiled-doc")
    try:
        await wait_synced(provider)
        for i in range(6):
            provider.document.get_text("t").insert(0, f"probe {i} ")
        await retryable_assertion(
            lambda: _assert_positive(
                get_cost_ledger().frames.value(site="frame_decode", type="Sync")
            )
        )

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server.http_url}/debug/costs") as response:
                assert response.status == 200
                costs = await response.json()
            async with session.get(
                f"{server.http_url}/debug/profile/cpu"
            ) as response:
                assert response.status == 200
                cpu = await response.json()
            async with session.get(
                f"{server.http_url}/debug/profile/cpu",
                params={"format": "collapsed"},
            ) as response:
                assert response.status == 200
                assert response.content_type == "text/plain"
                folded_headers = dict(response.headers)
                folded = await response.text()

        for payload in (costs, cpu):
            for key in ("generated_utc", "role", "node_id"):
                assert key in payload, (key, sorted(payload))
        assert costs["enabled"] is True
        sites = {row["site"] for row in costs["rows"]}
        assert "frame_decode" in sites
        assert costs["headroom_frames_per_s"] > 0
        assert costs["top_costs"], "empty attribution after live traffic"
        # quantiles only for types with observed series (sentinel guard)
        assert "Sync" in costs["wire_handle_quantiles_ms"]

        assert cpu["stats"]["running"] is True
        for line in folded.strip().splitlines():
            assert _FOLDED_LINE.match(line), line
        assert "X-Generated-Utc" in folded_headers
        assert "X-Node-Id" in folded_headers
    finally:
        provider.destroy()
        await server.destroy()
        get_profiler().stop()  # don't leave the sampler on for later tests


def _assert_positive(value: float) -> None:
    assert value > 0


# -- registration + fleet ------------------------------------------------------


def test_profiler_and_ledger_metrics_register_deterministically():
    """PR-6 pin: the profiler/ledger series adopt into the registry via
    register() and expose in sorted-name order; re-instantiating the
    extension (same process singletons) must not raise on the name
    collision."""
    metrics = Metrics()
    metrics2 = Metrics()  # adoption is idempotent across instances
    text = metrics.registry.expose()
    for name in (
        "hocuspocus_profile_frame_cost_ns",
        "hocuspocus_profile_frames_total",
        "hocuspocus_profile_frame_bytes_total",
        "hocuspocus_profile_headroom_frames_per_s",
        "hocuspocus_profile_overhead_fraction",
        "hocuspocus_profile_samples_total",
        "hocuspocus_profile_lag_bursts_total",
    ):
        assert f"# TYPE {name}" in text, name
    # deterministic series ordering: HELP headers appear sorted by name
    names = [
        line.split(" ")[2]
        for line in text.splitlines()
        if line.startswith("# TYPE ")
    ]
    assert names == sorted(names)
    assert metrics2.registry is not metrics.registry or True  # both valid


def test_fleet_digest_carries_headroom():
    """The headroom number rides on fleet digests so /debug/fleet shows
    per-node sustainable frames/s (the ISSUE's fleet acceptance)."""
    from hocuspocus_tpu.observability.fleet import FleetView, build_digest

    ledger = get_cost_ledger()
    ledger.reset()
    ledger.enable()
    try:
        for site in LOOP_SITES:
            ledger.record(site, "Sync", 500_000)
        digest = build_digest(role="cell", node_id="cell-9", interval_s=0.1)
        assert digest["headroom_frames_per_s"] == pytest.approx(500.0)
        view = FleetView()
        view.enable()
        view.ingest(digest)
        peers = view.status()["peers"]
        entry = peers["cell-9"]
        assert entry["headroom_frames_per_s"] == pytest.approx(500.0)
    finally:
        ledger.disable()
        ledger.reset()


async def test_wire_saturation_scenario_attaches_evidence():
    """The wire_saturation scenario (BENCH_SUITE member) lands
    extra.wire_saturation: per-rung offered vs achieved frames/s, the
    headroom model's rate and a non-empty attribution — and passes on
    CPU at CI scale."""
    from hocuspocus_tpu.loadgen.runner import run_scenario
    from hocuspocus_tpu.loadgen.scenarios import BENCH_SUITE, get_scenario

    assert "wire_saturation" in BENCH_SUITE
    scenario = get_scenario("wire_saturation", num_docs=4, phase_ms=400)
    result = await run_scenario(scenario, seed=3, time_scale=4.0)
    assert result["verdict"] == "pass", result["slo"]["breached_targets"]
    evidence = result["extra"]["wire_saturation"]
    assert len(evidence["rungs"]) == 4
    for rung in evidence["rungs"]:
        assert rung["achieved_frames_per_s"] > 0
    assert evidence["sustained_frames_per_s"] > 0
    assert evidence["headroom_frames_per_s"] > 0
    assert evidence["top_costs"], "empty cost attribution"
    assert {c["site"] for c in evidence["top_costs"]} <= {
        "frame_decode",
        "frame_encode",
        "coalesce",
        "fanout_tick",
        "varint_header",
        "apply_update",
        "wal_append",
    }
    # the scenario hands the next run a cold ledger (teardown contract)
    assert get_cost_ledger().enabled is False
