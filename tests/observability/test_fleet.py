"""Fleet observability plane (ISSUE 15): cross-tier trace propagation
over the relay lane (edge→cell→edge span chain summing exactly to the
edge-to-edge e2e, clock-skew folding, old-envelope fallback), telemetry
federation (digests on the control channel, FleetView rollups,
stale/down/epoch-skew transitions in the __fleet__ ring), the
`/debug/fleet` endpoint over real HTTP on a 2-edge × 2-cell topology,
and the consistent attributable /debug header."""

from __future__ import annotations

import asyncio
import json

import aiohttp
import pytest

from hocuspocus_tpu.edge import (
    CellIngressExtension,
    EdgeGatewayExtension,
    EdgeServer,
    relay,
)
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import RedisSubscriber
from hocuspocus_tpu.observability import (
    ClockOffsetEstimator,
    FleetView,
    Metrics,
    build_digest,
    disable_tracing,
    enable_tracing,
    get_fleet_view,
    get_flight_recorder,
    get_tracer,
)
from hocuspocus_tpu.observability.fleet import TraceReturnOutbox
from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.provider.inprocess import InProcessProviderSocket
from hocuspocus_tpu.server import Configuration, Server
from hocuspocus_tpu.server.overload import get_overload_controller
from hocuspocus_tpu.tpu import TpuMergeExtension

from tests.utils import wait_for

# the full cross-tier chain: four edge-side stages + the existing seven
CELL_STAGES = (
    "ingress",
    "queue_wait",
    "build",
    "upload",
    "device",
    "readback",
    "broadcast",
)
FLEET_SPAN_NAMES = {
    f"update.{stage}"
    for stage in ("edge_ingress", "relay_out", "relay_return", "edge_egress")
    + CELL_STAGES
}


@pytest.fixture(autouse=True)
def _fleet_isolation():
    get_fleet_view().reset()
    controller = get_overload_controller()
    controller.reset()
    yield
    get_fleet_view().reset()
    controller.reset()
    disable_tracing()
    get_tracer().clear()


class FleetTopology:
    """2 cells (full serve planes + Metrics) × N edges over MiniRedis —
    the acceptance topology with observability lit on every role."""

    def __init__(self) -> None:
        self.redis = None
        self.cells = []  # (Server, CellIngressExtension, Metrics)
        self.edges = []  # (EdgeServer, EdgeGatewayExtension, Metrics)
        self.sockets = []
        self.providers = []

    async def start(self, cells=2, edges=2):
        self.redis = await MiniRedis().start()
        host, port = "127.0.0.1", self.redis.port
        for i in range(cells):
            ingress = CellIngressExtension(
                cell_id=f"cell-{i}", host=host, port=port, announce_interval_s=0.2
            )
            plane = TpuMergeExtension(
                num_docs=8,
                capacity=512,
                flush_interval_ms=1,
                broadcast_interval_ms=1,
                serve=True,
            )
            metrics = Metrics()
            server = Server(
                Configuration(quiet=True, extensions=[metrics, ingress, plane])
            )
            await server.listen(port=0)
            self.cells.append((server, ingress, metrics))
        for i in range(edges):
            gateway_ext = EdgeGatewayExtension(
                edge_id=f"edge-{i}", host=host, port=port, digest_interval_s=0.2
            )
            metrics = Metrics()
            server = EdgeServer(
                Configuration(quiet=True, extensions=[metrics, gateway_ext])
            )
            await server.listen(port=0)
            self.edges.append((server, gateway_ext, metrics))
        for _, gateway_ext, _ in self.edges:
            await wait_for(
                lambda g=gateway_ext: len(g.gateway.router.healthy_cells())
                == cells
            )
        return self

    def provider(self, edge_index, name):
        socket = InProcessProviderSocket(self.edges[edge_index][0])
        self.sockets.append(socket)
        provider = HocuspocusProvider(name=name, websocket_provider=socket)
        provider.attach()
        self.providers.append(provider)
        return provider

    async def close(self):
        for provider in self.providers:
            provider.destroy()
        for socket in self.sockets:
            socket.destroy()
        await asyncio.sleep(0)
        for server, *_ in self.edges + self.cells:
            await server.destroy()
        if self.redis is not None:
            await self.redis.stop()


def _fleet_trace_spans(tracer):
    """-> {trace_id: [spans]} for cross-tier (edge-stamped) trace ids."""
    by_id: dict = {}
    for span in tracer.export():
        if span["name"].startswith("update."):
            trace_id = span.get("trace_id")
            if isinstance(trace_id, str) and ":" in trace_id:
                by_id.setdefault(trace_id, []).append(span)
    return by_id


async def _complete_fleet_trace(tracer):
    """Wait for one cross-tier trace with the full 11-span chain."""

    def complete():
        for trace_id, spans in _fleet_trace_spans(tracer).items():
            if {span["name"] for span in spans} == FLEET_SPAN_NAMES:
                return trace_id, spans
        return None

    result = None

    async def poll():
        nonlocal result
        while result is None:
            result = complete()
            if result is None:
                await asyncio.sleep(0.02)

    await asyncio.wait_for(poll(), timeout=30.0)
    return result


# -- unit: clock offsets, outbox, digests, rollups ----------------------------


def test_clock_offset_estimator_recovers_injected_skew():
    """NTP-midpoint math: a peer clock running +5s ahead with symmetric
    transit is recovered regardless of RTT; low-RTT samples dominate."""
    est = ClockOffsetEstimator()
    skew = 5.0
    t = 100.0
    for transit in (0.004, 0.002, 0.001, 0.003):
        t_sent = t
        t_peer = t_sent + transit + skew  # peer stamps mid-flight
        t_recv = t_sent + 2 * transit
        est.observe(t_sent, t_peer, t_recv)
        t += 1.0
    assert est.offset_s == pytest.approx(skew, abs=1e-9)
    assert est.samples == 4
    # an asymmetric high-RTT outlier moves the estimate only slightly
    est.observe(200.0, 200.0 + skew + 0.5, 200.0 + 0.6)
    assert abs(est.offset_s - skew) < 0.1


def test_trace_return_outbox_bounded_with_accounting():
    outbox = TraceReturnOutbox()
    wakes = []
    outbox.add_waker(lambda: wakes.append(1))
    for i in range(outbox.MAX_PENDING + 10):
        outbox.deposit(f"doc-{i}", {"id": i})
    assert outbox.pending == outbox.MAX_PENDING
    assert outbox.dropped == 10
    assert len(wakes) == outbox.MAX_PENDING + 10
    assert outbox.take("doc-missing") is None
    drained = outbox.take_all()
    assert outbox.pending == 0
    assert sum(len(v) for v in drained.values()) == outbox.MAX_PENDING


def test_digest_roundtrip_between_views():
    """A digest built on one node ingests into a FRESH FleetView (the
    cross-process federation path, minus the wire): peer table, role
    table and rollups all populate; malformed digests are counted."""
    digest = build_digest(role="cell", node_id="cell-7", interval_s=2.0)
    encoded = json.dumps(digest)  # exactly what rides the DIGEST envelope
    view = FleetView()
    assert view.ingest(json.loads(encoded))
    assert view.peer_state("cell-7") == "up"
    status = view.status()
    assert status["roles"] == {"cell": ["cell-7"]}
    assert status["peers"]["cell-7"]["rung"] == "green"
    assert status["totals"]["fresh"] == 1
    # malformed: wrong version / missing identity — counted, never raised
    assert not view.ingest({"v": 99, "role": "cell", "node_id": "x"})
    assert not view.ingest({"v": 1, "role": "cell"})
    assert not view.ingest("not a digest")
    assert view.counters["digests_invalid"] == 3


def test_fleet_view_stale_down_transitions_hit_fleet_ring():
    recorder = get_flight_recorder()
    recorder.forget("__fleet__")
    view = FleetView()
    view.ingest(build_digest(role="cell", node_id="cell-0", interval_s=0.1))
    view.ingest(build_digest(role="edge", node_id="edge-0", interval_s=0.1))
    events = [e["event"] for e in recorder.events("__fleet__")]
    assert events.count("peer_up") == 2
    # age cell-0 past the stale threshold (floor 5s), then past down
    view._peer_state["cell-0"]["last_seen"] -= 10.0
    assert view.stale_peers() == ["cell-0"]
    assert view.peer_state("cell-0") == "stale"
    view._peer_state["cell-0"]["last_seen"] -= 1000.0
    view._sweep()
    assert view.peer_state("cell-0") == "down"
    # explicit departure (CELL_DOWN) for the edge
    view.mark_down("edge-0")
    events = [e["event"] for e in recorder.events("__fleet__")]
    assert "peer_stale" in events
    assert events.count("peer_down") == 2
    # rollups exclude non-fresh peers
    assert view.fresh_peers() == []
    assert view.status()["totals"]["fresh"] == 0


def test_fleet_view_epoch_skew_flags_shared_stream_epochs_per_role():
    recorder = get_flight_recorder()
    recorder.forget("__fleet__")
    view = FleetView()
    view.ingest(
        build_digest(
            role="edge", node_id="edge-0", extra={"placement_epoch": 4}
        )
    )
    view.ingest(
        build_digest(
            role="edge", node_id="edge-1", extra={"placement_epoch": 4}
        )
    )
    assert not view._epoch_skew()["edge"]["skew"]
    view.ingest(
        build_digest(
            role="edge", node_id="edge-1", extra={"placement_epoch": 9}
        )
    )
    skew = view._epoch_skew()
    assert skew["edge"]["skew"]
    assert skew["edge"]["epochs"] == {"edge-0": 4, "edge-1": 9}
    assert "epoch_skew_detected" in [
        e["event"] for e in recorder.events("__fleet__")
    ]
    # cell PLACEMENT epochs are local bookkeeping: reported, never flagged
    view.ingest(
        build_digest(role="cell", node_id="cell-0", extra={"placement_epoch": 1})
    )
    view.ingest(
        build_digest(role="cell", node_id="cell-1", extra={"placement_epoch": 7})
    )
    assert not view._epoch_skew()["cell"]["skew"]
    view.refresh_gauges()
    assert view.epoch_skew_gauge.value(role="edge") == 1.0
    assert view.epoch_skew_gauge.value(role="cell") == 0.0
    # cell ROSTER epochs derive from the shared control stream
    # (fleet/roster.py PeerRoster) — divergence there IS the skew
    view.ingest(
        build_digest(
            role="cell",
            node_id="cell-0",
            extra={"placement_epoch": 1, "roster_epoch": 3},
        )
    )
    view.ingest(
        build_digest(
            role="cell",
            node_id="cell-1",
            extra={"placement_epoch": 7, "roster_epoch": 3},
        )
    )
    cell_skew = view._epoch_skew()["cell"]
    assert not cell_skew["skew"]
    assert cell_skew["roster_epochs"] == {"cell-0": 3, "cell-1": 3}
    view.ingest(
        build_digest(
            role="cell",
            node_id="cell-1",
            extra={"placement_epoch": 7, "roster_epoch": 5},
        )
    )
    cell_skew = view._epoch_skew()["cell"]
    assert cell_skew["skew"]  # a missed membership transition
    assert cell_skew["epochs"] == {"cell-0": 1, "cell-1": 7}  # still reported
    view.refresh_gauges()
    assert view.epoch_skew_gauge.value(role="cell") == 1.0


def test_fleet_view_autoscale_section_reflects_the_attached_controller():
    """`/debug/fleet` gains an `autoscale` section fed through the
    attach seam; a crashing status callback degrades to an error stub
    instead of taking the whole debug payload down."""
    view = FleetView()
    assert "autoscale" not in view.status()
    view.attach_autoscale(
        lambda: {"enabled": True, "roster": {"active": [0, 1], "total": 4}}
    )
    section = view.status()["autoscale"]
    assert section["roster"] == {"active": [0, 1], "total": 4}

    def _boom():
        raise RuntimeError("controller mid-teardown")

    view.attach_autoscale(_boom)
    assert view.status()["autoscale"] == {"error": "unavailable"}
    view.attach_autoscale(None)  # controller teardown detaches
    assert "autoscale" not in view.status()


def test_fleet_rollups_skip_empty_peers():
    """A peer that doesn't report a field (an edge has no docs; a
    booting cell has no sessions) is skipped, not averaged in as zero —
    and the cross-tier quantiles stay None (never a fabricated 0.0)
    until a trace actually lands."""
    view = FleetView()
    view.ingest(
        build_digest(
            role="cell", node_id="cell-0", extra={"sessions": 10, "docs": 100}
        )
    )
    view.ingest(build_digest(role="edge", node_id="edge-0", extra={"sessions": 7}))
    totals = view.status()["totals"]
    assert totals["sessions"] == 17
    assert totals["docs"] == 100  # the edge's missing docs never count as 0
    assert view.cross_tier_quantiles() is None
    view.record_cross_tier("total", 0.020)
    quantiles = view.cross_tier_quantiles()
    assert quantiles["count"] == 1
    assert quantiles["p99_ms"] > 0


# -- cross-tier trace round trip ----------------------------------------------


async def test_cross_tier_trace_round_trip_span_sum_equals_e2e():
    """THE acceptance invariant: one sampled update relayed
    edge→cell→edge produces ONE trace whose eleven cross-process stage
    spans (edge_ingress through edge_egress) sum exactly to the
    edge-to-edge e2e latency — and the fleet e2e histogram sees it."""
    tracer = enable_tracing(max_spans=4096)
    tracer.clear()
    topo = await FleetTopology().start(cells=2, edges=2)
    try:
        writer = topo.provider(0, "traced-doc")
        reader = topo.provider(1, "traced-doc")
        await wait_for(lambda: writer.synced and reader.synced)
        writer.document.get_text("t").insert(0, "cross-tier hello")
        trace_id, spans = await _complete_fleet_trace(tracer)

        assert trace_id.startswith("edge-0:")
        egress = next(s for s in spans if s["name"] == "update.edge_egress")
        e2e_ms = egress["attributes"]["e2e_ms"]
        span_sum = sum(s["duration_ms"] for s in spans)
        assert span_sum == pytest.approx(e2e_ms, abs=0.01)
        assert all(s["duration_ms"] >= 0 for s in spans), spans
        # every span in the chain carries the node attribute that pins
        # it to a Perfetto role lane
        assert all(s["attributes"].get("node") for s in spans)
        ingress = next(s for s in spans if s["name"] == "update.edge_ingress")
        assert ingress["attributes"]["node"] == "edge-0"
        assert ingress["attributes"]["hop"] == 2  # edge→cell→edge
        # the fleet histogram's total series drives --slo-fleet-e2e-ms
        view = get_fleet_view()
        assert view.e2e_histogram.series_count(stage="total") >= 1
        quantiles = view.cross_tier_quantiles()
        assert quantiles is not None and quantiles["count"] >= 1
        # stamping edge accounting
        gateway = topo.edges[0][1].gateway
        assert gateway.counters["traces_stamped"] >= 1
        assert gateway.counters["traces_closed"] >= 1
    finally:
        await topo.close()


async def test_cross_tier_trace_clock_skew_folds_into_relay_spans():
    """Injected clock skew (a deliberately wrong offset estimate, plus
    real relay latency injected in mini_redis delivery): no span goes
    negative, and the chain still sums exactly to the reported e2e —
    the skew folds into the relay spans."""
    tracer = enable_tracing(max_spans=4096)
    tracer.clear()
    topo = await FleetTopology().start(cells=2, edges=2)
    try:
        # real transit on every relay hop
        topo.redis.publish_latency_ms = 10
        # a wildly wrong offset estimate toward every cell: +250ms skew
        view = get_fleet_view()
        for cell_id in ("cell-0", "cell-1"):
            estimator = view.offset_for(cell_id)
            estimator.offset_s = 0.25
            estimator.samples = max(estimator.samples, 1)
        writer = topo.provider(0, "skewed-doc")
        reader = topo.provider(1, "skewed-doc")
        await wait_for(lambda: writer.synced and reader.synced)
        writer.document.get_text("t").insert(0, "skewed edit")
        _trace_id, spans = await _complete_fleet_trace(tracer)

        egress = next(s for s in spans if s["name"] == "update.edge_egress")
        span_sum = sum(s["duration_ms"] for s in spans)
        assert span_sum == pytest.approx(egress["attributes"]["e2e_ms"], abs=0.01)
        assert all(s["duration_ms"] >= 0 for s in spans), [
            (s["name"], s["duration_ms"]) for s in spans
        ]
        # the injected relay latency is visible: the two relay spans
        # together carry at least one leg's worth of transit
        relay_ms = sum(
            s["duration_ms"]
            for s in spans
            if s["name"] in ("update.relay_out", "update.relay_return")
        )
        assert relay_ms >= 5.0
    finally:
        topo.redis.publish_latency_ms = 0
        await topo.close()


async def test_no_trace_context_fallback_old_envelopes_still_parse():
    """Tracing off = no aux stamped (old-edge behavior), and hand-built
    pre-trace envelopes (empty aux) flow through the new cell unchanged;
    foreign aux decodes to None rather than erroring."""
    assert relay.decode_trace_aux("") is None
    assert relay.decode_trace_aux("not json") is None
    assert relay.decode_trace_aux('{"v": 999, "id": "x"}') is None
    assert relay.decode_trace_aux('["list"]') is None
    context = {"id": "edge-0:1", "e": "edge-0", "t0": 1.0, "t1": 2.0, "h": 1}
    assert relay.decode_trace_aux(relay.encode_trace_aux(context))["id"] == (
        "edge-0:1"
    )

    topo = await FleetTopology().start(cells=1, edges=1)
    try:
        # tracing DISABLED: the edge stamps nothing — byte-for-byte the
        # pre-trace envelope shape — and sync still converges
        writer = topo.provider(0, "legacy-doc")
        await wait_for(lambda: writer.synced)
        writer.document.get_text("t").insert(0, "legacy edit")
        gateway = topo.edges[0][1].gateway
        await wait_for(
            lambda: topo.cells[0][1].counters["frames_in"] > 0
        )
        assert gateway.counters["traces_stamped"] == 0
        server = topo.cells[0][0]
        await wait_for(lambda: "legacy-doc" in server.hocuspocus.documents)
        from hocuspocus_tpu.crdt import encode_state_as_update

        document = server.hocuspocus.documents["legacy-doc"]
        await wait_for(
            lambda: encode_state_as_update(document)
            == encode_state_as_update(writer.document)
        )
    finally:
        await topo.close()


# -- federation over real HTTP (the acceptance endpoint) ----------------------


async def test_debug_fleet_reports_whole_topology_over_http():
    """Acceptance: GET /debug/fleet on ANY Metrics-enabled process of a
    2-edge × 2-cell topology reports every live role/cell with health
    rung, burn rates and placement epoch — plus the attributable
    header; digests really ride the control channel (verified by a raw
    subscriber feeding a fresh FleetView); hocuspocus_fleet_* gauges
    render on /metrics."""
    topo = await FleetTopology().start(cells=2, edges=2)
    raw_digests = []

    def collect(channel, data):
        try:
            kind, node_id, _aux, payload = relay.decode_envelope(data)
        except Exception:
            return
        if kind == relay.DIGEST:
            raw_digests.append((node_id, payload))

    spy = RedisSubscriber(
        "127.0.0.1", topo.redis.port, on_message=collect
    )
    try:
        await spy.subscribe(relay.control_channel(relay.DEFAULT_PREFIX))
        # every role publishes within one heartbeat/digest interval
        await wait_for(
            lambda: {node for node, _ in raw_digests}
            >= {"cell-0", "cell-1", "edge-0", "edge-1"},
            timeout=10.0,
        )
        # the bus carries real, parseable digests a cold process could use
        fresh_view = FleetView()
        for _node, payload in raw_digests[:8]:
            assert fresh_view.ingest(json.loads(payload))
        assert len(fresh_view.peers) >= 1

        async with aiohttp.ClientSession() as session:
            # any edge AND any cell answer with the whole topology
            for server in (topo.edges[0][0], topo.cells[1][0]):
                async with session.get(
                    f"{server.http_url}/debug/fleet"
                ) as response:
                    assert response.status == 200
                    payload = json.loads(await response.text())
                assert {"generated_utc", "role", "node_id"} <= set(payload)
                peers = payload["peers"]
                assert {"cell-0", "cell-1", "edge-0", "edge-1"} <= set(peers)
                assert payload["roles"]["cell"] == ["cell-0", "cell-1"]
                assert payload["roles"]["edge"] == ["edge-0", "edge-1"]
                for node_id in ("cell-0", "cell-1", "edge-0", "edge-1"):
                    assert peers[node_id]["state"] == "up"
                    assert peers[node_id]["rung"] == "green"
                # burn rates ride every digest (engines sample at build)
                for node_id in ("cell-0", "cell-1"):
                    assert "slo_burn" in peers[node_id], peers[node_id]
                    assert peers[node_id]["cell"]["edge_sessions"] >= 0
                # placement epoch: edges report router epochs (equal —
                # same control stream — so no skew flagged)
                assert peers["edge-0"]["placement_epoch"] == (
                    peers["edge-1"]["placement_epoch"]
                )
                assert not payload["epoch_skew"]["edge"]["skew"]
                assert payload["stale_peers"] == []
                assert payload["totals"]["fresh"] == 4

            # hocuspocus_fleet_* rollups on /metrics
            async with session.get(
                f"{topo.edges[0][0].http_url}/metrics"
            ) as response:
                body = await response.text()
            assert 'hocuspocus_fleet_peers{role="cell"} 2' in body
            assert 'hocuspocus_fleet_peers{role="edge"} 2' in body
            assert "hocuspocus_fleet_stale_peers 0" in body
            assert "hocuspocus_fleet_e2e_seconds_count" in body
            assert 'hocuspocus_fleet_digests_ingested_total{role="cell"}' in body

            # the fleet SLO target is folded into /debug/slo
            async with session.get(
                f"{topo.edges[0][0].http_url}/debug/slo"
            ) as response:
                slo = json.loads(await response.text())
            assert "fleet_e2e_latency" in slo["slos"]
            assert {"generated_utc", "role", "node_id"} <= set(slo)
    finally:
        spy.close()
        await topo.close()


async def test_debug_endpoints_stamp_attributable_header():
    """Every /debug payload carries {"generated_utc", "role",
    "node_id"}; /debug/edge stamps it too; /healthz keeps its own
    contract (no header)."""
    topo = await FleetTopology().start(cells=1, edges=1)
    try:
        edge_url = topo.edges[0][0].http_url
        cell_url = topo.cells[0][0].http_url
        async with aiohttp.ClientSession() as session:
            for url in (
                f"{edge_url}/debug/fleet",
                f"{edge_url}/debug/edge",
                f"{cell_url}/debug/slo",
                f"{cell_url}/debug/trace",
                f"{cell_url}/debug/scheduler",
                f"{cell_url}/debug/docs",
            ):
                async with session.get(url) as response:
                    assert response.status == 200, url
                    payload = json.loads(await response.text())
                assert {"generated_utc", "role", "node_id"} <= set(payload), url
                assert payload["generated_utc"].endswith("Z")
            async with session.get(f"{edge_url}/debug/edge") as response:
                edge_payload = json.loads(await response.text())
            assert edge_payload["role"] in ("edge", "cell")  # in-process shared
            async with session.get(f"{cell_url}/healthz") as response:
                health = json.loads(await response.text())
            assert "generated_utc" not in health
    finally:
        await topo.close()


async def test_monolith_fleet_view_shows_itself():
    """A plain monolith (no relay lane) still answers /debug/fleet with
    its own digest — the single pane degrades gracefully to one pane."""
    from tests.utils import new_hocuspocus

    server = await new_hocuspocus(extensions=[Metrics()])
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{server.http_url}/debug/fleet"
            ) as response:
                payload = json.loads(await response.text())
        assert payload["roles"].get("monolith"), payload
        node_id = payload["roles"]["monolith"][0]
        assert payload["peers"][node_id]["state"] == "up"
    finally:
        await server.destroy()
