"""WAL unit differentials: framing, group commit, torn tails, faults.

The recovery contract is bitwise: every committed record replays, a
torn tail is detected (CRC) and skipped — never applied, never fatal —
and a write failure leaves the segment chain in a state where the NEXT
append is still recoverable.
"""

import asyncio
import os

import pytest

from hocuspocus_tpu.storage import (
    REC_SNAPSHOT,
    REC_UPDATE,
    FaultInjector,
    WalManager,
    decode_records,
    encode_record,
)


def _payloads(records):
    return [payload for _type, payload in records]


# -- framing -----------------------------------------------------------------


def test_record_roundtrip_and_types():
    blob = encode_record(b"hello", REC_UPDATE) + encode_record(b"snap", REC_SNAPSHOT)
    records, valid, bad = decode_records(blob)
    assert records == [(REC_UPDATE, b"hello"), (REC_SNAPSHOT, b"snap")]
    assert valid == len(blob)
    assert bad == 0


def test_decode_stops_at_corrupt_frame():
    good = encode_record(b"first")
    corrupt = bytearray(encode_record(b"second"))
    corrupt[-1] ^= 0xFF  # flip a payload bit: CRC mismatch
    unreachable = encode_record(b"third")
    records, valid, bad = decode_records(bytes(good + corrupt + unreachable))
    # frame boundaries are lost past a bad record: third is unreachable
    assert _payloads(records) == [b"first"]
    assert valid == len(good)
    assert bad == 1


def test_decode_tolerates_short_tail():
    good = encode_record(b"first")
    torn = encode_record(b"torn-away-payload")[:-5]
    records, valid, bad = decode_records(good + torn)
    assert _payloads(records) == [b"first"]
    assert bad == 1
    # a partial header alone is also a torn tail
    records, _valid, bad = decode_records(good + b"\x01\x02\x03")
    assert _payloads(records) == [b"first"]
    assert bad == 1


# -- group commit ------------------------------------------------------------


async def test_group_commit_one_fsync_per_tick(tmp_path):
    wal = WalManager(str(tmp_path), fsync="tick")
    futures = [wal.append("doc", b"u%d" % i) for i in range(8)]
    # all appends in one tick share ONE durability future
    assert all(f is futures[0] for f in futures)
    await futures[0]
    assert wal.stats["appended_records"] == 8
    assert wal.stats["fsyncs"] == 1
    assert wal.stats["commit_batch_records_last"] == 8
    records, report = await wal.replay("doc")
    # segment copies + the journal's redo copies (idempotent on replay)
    assert _payloads(records)[:8] == [b"u%d" % i for i in range(8)]
    assert report["journal_records"] == 8
    assert report["torn_tail_records"] == 0


async def test_one_journal_fsync_covers_many_docs(tmp_path):
    """The amortization that makes tick mode viable: N dirty docs in
    one tick cost ONE fsync (the shared commit journal), not N."""
    wal = WalManager(str(tmp_path), fsync="tick")
    futures = [wal.append(f"doc-{i}", b"payload") for i in range(32)]
    await futures[0]
    assert wal.stats["fsyncs"] == 1
    assert wal.stats["appended_records"] == 32
    # every doc's record is durable via the journal
    fresh = WalManager(str(tmp_path), fsync="tick")
    for i in range(32):
        records, report = await fresh.replay(f"doc-{i}")
        assert b"payload" in _payloads(records)


async def test_journal_rotation_settles_segments(tmp_path):
    """When the journal crosses its size bound, the dirty doc segments
    are batch-fsynced and the journal resets — replay is then exact
    again (no redo copies)."""
    wal = WalManager(str(tmp_path), fsync="tick", journal_max_bytes=256)
    for i in range(12):
        await wal.append("doc", b"payload-%02d" % i)
    assert wal.stats["journal_rotations"] >= 1
    # after a rotation the journal no longer re-covers settled records
    fresh = WalManager(str(tmp_path), fsync="tick")
    records, report = await fresh.replay("doc")
    payloads = _payloads(records)
    assert payloads[:12] == [b"payload-%02d" % i for i in range(12)]
    # only the unrotated tail window may appear twice
    assert len(payloads) < 24


async def test_fsync_always_and_off_modes(tmp_path):
    always = WalManager(str(tmp_path / "a"), fsync="always")
    await asyncio.gather(always.append("d", b"x"), always.append("d", b"y"))
    assert always.stats["fsyncs"] == 2
    off = WalManager(str(tmp_path / "b"), fsync="off")
    await off.append("d", b"x")
    assert off.stats["fsyncs"] == 0
    records, _ = await off.replay("d")
    assert _payloads(records) == [b"x"]
    with pytest.raises(ValueError):
        WalManager(str(tmp_path / "c"), fsync="sometimes")


async def test_appends_during_commit_join_next_batch(tmp_path):
    wal = WalManager(str(tmp_path), fsync="off")
    first = wal.append("doc", b"one")
    await first
    second = wal.append("doc", b"two")
    third = wal.append("doc", b"three")
    assert second is third and second is not first
    await second
    records, _ = await wal.replay("doc")
    assert _payloads(records) == [b"one", b"two", b"three"]
    assert wal.stats["commit_batches"] >= 2


# -- truncation / segments ---------------------------------------------------


async def test_truncate_through_drops_covered_segments(tmp_path):
    # fsync="off": no journal, so replay is segment-exact — this test
    # pins SEGMENT truncation, which is mode-independent
    wal = WalManager(str(tmp_path), fsync="off", segment_max_bytes=20)
    for i in range(6):
        await wal.append("doc", b"payload-%d" % i)  # tiny segments: rotation
    doc = wal.doc("doc")
    segment_count = len(doc.segments)
    assert segment_count >= 3
    position = wal.position("doc")
    assert position == 6
    removed = wal.truncate_through("doc", position - 1)
    assert removed == segment_count
    records, _ = await wal.replay("doc")
    assert records == []
    # appends after full truncation start a fresh chain
    await wal.append("doc", b"after")
    records, _ = await wal.replay("doc")
    assert _payloads(records) == [b"after"]


async def test_partial_coverage_keeps_segment(tmp_path):
    wal = WalManager(str(tmp_path), fsync="off", segment_max_bytes=1 << 20)
    await wal.append("doc", b"covered")
    await wal.append("doc", b"not-covered")
    # store covered only seq 0: the shared segment must survive
    assert wal.truncate_through("doc", 0) == 0
    records, _ = await wal.replay("doc")
    assert _payloads(records) == [b"covered", b"not-covered"]


async def test_checkpoint_subsumes_history(tmp_path):
    # tick mode on purpose: a checkpoint must rotate the journal so the
    # subsume-everything property holds ON DISK, not just in segments
    wal = WalManager(str(tmp_path), fsync="tick", segment_max_bytes=64)
    for i in range(5):
        await wal.append("doc", b"edit-%d" % i)
    await wal.checkpoint("doc", b"SNAPSHOT")
    records, _ = await wal.replay("doc")
    assert records == [(REC_SNAPSHOT, b"SNAPSHOT")]
    assert wal.stats["checkpoints"] == 1
    assert wal.stats["journal_rotations"] >= 1
    # post-checkpoint edits append after the snapshot record (the tail
    # also rides the fresh journal window: one redo copy)
    await wal.append("doc", b"tail")
    records, _ = await wal.replay("doc")
    assert records[:2] == [(REC_SNAPSHOT, b"SNAPSHOT"), (REC_UPDATE, b"tail")]


async def test_doc_names_are_path_safe(tmp_path):
    wal = WalManager(str(tmp_path), fsync="off")
    weird = "reports/../q3 2026?*"
    await wal.append(weird, b"payload")
    records, _ = await wal.replay(weird)
    assert _payloads(records) == [b"payload"]
    # nothing escaped the wal root
    assert not (tmp_path.parent / "q3 2026?*").exists()


# -- fault injection ---------------------------------------------------------


async def test_torn_write_recovery_differential(tmp_path):
    """A torn write (crash mid-record) loses ONLY the torn record; the
    tail is repaired so later appends stay reachable."""
    faults = FaultInjector()
    # `always` mode: no journal redo copies, so the differential is
    # record-exact (the torn-write repair itself is mode-independent)
    wal = WalManager(str(tmp_path), fsync="always", faults=faults)
    await wal.append("doc", b"before")
    faults.tear_next_write(0.4)
    await wal.append("doc", b"torn-record-payload-torn-record")
    assert wal.stats["append_errors"] == 1
    records, report = await wal.replay("doc")
    assert _payloads(records) == [b"before"]
    await wal.append("doc", b"after-heal")
    records, report = await wal.replay("doc")
    assert _payloads(records) == [b"before", b"after-heal"]
    assert report["torn_tail_records"] == 0  # tail was repaired
    assert faults.counters["torn_writes_injected"] == 1


async def test_unrepaired_torn_tail_counted_at_replay(tmp_path):
    """A crash AFTER the write but mid-flush leaves a torn tail on
    disk; a fresh manager (the restarted process) counts + skips it."""
    wal = WalManager(str(tmp_path), fsync="off")
    await wal.append("doc", b"durable")
    await wal.append("doc", b"casualty")
    doc = wal.doc("doc")
    path = doc.segments[-1].path
    wal.close()
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 4)  # kill -9 mid-write: partial final record
    fresh = WalManager(str(tmp_path), fsync="off")
    records, report = await fresh.replay("doc")
    assert _payloads(records) == [b"durable"]
    assert report["torn_tail_records"] == 1
    assert fresh.stats["torn_tail_records"] == 1


async def test_journal_recovers_record_lost_from_torn_segment(tmp_path):
    """Tick mode's double-bookkeeping pays off: a record whose SEGMENT
    copy was torn off by the crash still recovers from the fsynced
    commit journal."""
    wal = WalManager(str(tmp_path), fsync="tick")
    await wal.append("doc", b"durable")
    await wal.append("doc", b"casualty")
    path = wal.doc("doc").segments[-1].path
    wal.close()
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) - 4)
    fresh = WalManager(str(tmp_path), fsync="tick")
    records, report = await fresh.replay("doc")
    assert b"casualty" in _payloads(records)
    assert report["torn_tail_records"] == 1
    assert report["journal_records"] == 2


async def test_fsync_failure_counted_not_fatal(tmp_path):
    faults = FaultInjector()
    wal = WalManager(str(tmp_path), fsync="tick", faults=faults)
    faults.fail_fsync(1)
    await wal.append("doc", b"maybe-durable")
    assert wal.stats["append_errors"] == 1
    await wal.append("doc", b"durable")
    records, _ = await wal.replay("doc")
    # the written-but-unfsynced record is still readable in THIS world
    # (no actual crash happened); the error is surfaced for alerting
    assert _payloads(records)[:2] == [b"maybe-durable", b"durable"]


async def test_disk_full_then_heal(tmp_path):
    faults = FaultInjector()
    wal = WalManager(str(tmp_path), fsync="always", faults=faults)
    faults.fail_disk_full(2)
    await wal.append("doc", b"lost-to-enospc")
    await wal.append("doc", b"also-lost")
    assert wal.stats["append_errors"] == 2
    await wal.append("doc", b"disk-freed")
    records, _ = await wal.replay("doc")
    assert _payloads(records) == [b"disk-freed"]


async def test_gate_future_resolves_even_on_failure(tmp_path):
    """Broadcast gating must never hang on a dead disk: the tick future
    resolves (and the error is counted) even when every write fails."""
    faults = FaultInjector()
    wal = WalManager(str(tmp_path), fsync="tick", faults=faults)
    faults.fail_disk_full(1)
    future = wal.append("doc", b"x")
    await asyncio.wait_for(future, timeout=5)
    assert wal.stats["append_errors"] == 1


async def test_checkpoint_fsync_failure_keeps_history(tmp_path):
    """The crash-ordering invariant behind checkpoints: older segments
    may only be dropped AFTER the snapshot is durable. With the
    journal fsync failing, the per-update history must survive."""
    faults = FaultInjector()
    wal = WalManager(str(tmp_path), fsync="tick", faults=faults)
    for i in range(3):
        await wal.append("doc", b"edit-%d" % i)
    faults.fail_fsync(1)  # the checkpoint tick's journal fsync dies
    await wal.checkpoint("doc", b"SNAP")
    assert wal.stats["append_errors"] == 1
    records, _report = await wal.replay("doc")
    payloads = _payloads(records)
    for i in range(3):
        assert b"edit-%d" % i in payloads, (
            "history dropped before the snapshot became durable"
        )


async def test_rotation_settles_unloaded_docs(tmp_path):
    """A doc unloaded (handle released) while its window is journal-
    covered: rotation must settle its tail segment file without the
    doc being resident — and without losing the record."""
    wal = WalManager(str(tmp_path), fsync="tick", journal_max_bytes=128)
    await wal.append("gone", b"payload")
    wal.forget("gone")
    for i in range(20):  # push the journal past its bound
        await wal.append("busy", b"fill-%02d" % i)
    assert wal.stats["journal_rotations"] >= 1
    fresh = WalManager(str(tmp_path), fsync="tick")
    records, _report = await fresh.replay("gone")
    assert b"payload" in _payloads(records)


async def test_restart_append_after_torn_tail_is_recoverable(tmp_path):
    """The restart twin of repair_tail: scan() must cut a torn segment
    tail back to the valid boundary, or post-restart appends land
    after the corrupt frame and vanish at the NEXT recovery."""
    wal = WalManager(str(tmp_path), fsync="off")
    await wal.append("doc", b"good")
    path = wal.doc("doc").segments[-1].path
    wal.close()
    with open(path, "ab") as fh:
        fh.write(b"\xde\xad\xbe")  # the torn frame a crash leaves
    wal2 = WalManager(str(tmp_path), fsync="off")
    await wal2.append("doc", b"post-restart")
    fresh = WalManager(str(tmp_path), fsync="off")
    records, report = await fresh.replay("doc")
    assert _payloads(records) == [b"good", b"post-restart"]


async def test_restart_never_appends_to_torn_journal(tmp_path):
    """A journal surviving a crash may have a torn tail; the restarted
    process must open a NEW journal file — entries appended past a
    corrupt frame would be unreachable, and in tick mode the journal
    is the window's only durable copy."""
    import os as _os

    wal = WalManager(str(tmp_path), fsync="tick")
    await wal.append("doc", b"first")
    jdir = wal._journal_dir
    jfile = _os.path.join(jdir, sorted(_os.listdir(jdir))[0])
    wal.close()
    with open(jfile, "ab") as fh:
        fh.write(b"\x13\x37" * 5)
    wal2 = WalManager(str(tmp_path), fsync="tick")
    await wal2.append("doc", b"second")
    journals = [e for e in _os.listdir(jdir) if e.endswith(".journal")]
    assert len(journals) == 2, journals
    # a third process (crash before rotation) recovers BOTH records
    wal3 = WalManager(str(tmp_path), fsync="tick")
    records, report = await wal3.replay("doc")
    payloads = _payloads(records)
    assert b"first" in payloads and b"second" in payloads
    assert report["journal_torn_records"] == 1


async def test_failed_batch_burns_sequence_numbers(tmp_path):
    """A store captures its position while records are buffered; if
    that batch then fails, its sequence numbers must be BURNED — were
    they re-used by later records, the store's truncation would cover
    (and delete) updates that arrived after its encode."""
    faults = FaultInjector()
    wal = WalManager(str(tmp_path), fsync="off", faults=faults)
    await wal.append("doc", b"durable-0")
    future = wal.append("doc", b"doomed-1")
    wal.append("doc", b"doomed-2")
    captured = wal.position("doc")  # the store's coverage point
    assert captured == 3
    faults.fail_disk_full(1)
    await future
    assert wal.stats["append_errors"] == 1
    # a record landing after the store's encode must stay OUTSIDE the
    # captured coverage even though the doomed batch freed its slots
    await wal.append("doc", b"after-encode")
    wal.truncate_through("doc", captured - 1)
    records, _report = await wal.replay("doc")
    assert b"after-encode" in _payloads(records), (
        "post-encode record was truncated as store-covered"
    )
