"""kill -9 crash/recovery: zero acknowledged-update loss.

The acceptance bar for the durability plane: SIGKILL a real server
process mid-edit-storm, restart it on the same WAL + store directories,
and every update a surviving reference client RECEIVED (i.e. was
broadcast — which the fan-out gate only does after the WAL group
commit) must be present in the recovered state, byte-identically. Torn
tail records (a write cut by the SIGKILL) are skipped and counted,
never applied and never fatal.

Marked `slow`: boots two subprocesses and real websocket clients.
"""

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)
from hocuspocus_tpu.provider import HocuspocusProvider

_EMPTY_DELTA = b"\x00\x00"
_SERVER = os.path.join(os.path.dirname(__file__), "crash_server.py")


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def _spawn_server(wal_dir: str, db_path: str):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = await asyncio.create_subprocess_exec(
        sys.executable,
        _SERVER,
        wal_dir,
        db_path,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=env,
    )
    line = await asyncio.wait_for(proc.stdout.readline(), timeout=30)
    assert line.startswith(b"PORT "), line
    return proc, int(line.split()[1])


@pytest.mark.slow
async def test_sigkill_mid_storm_loses_no_acknowledged_update(tmp_path):
    wal_dir = str(tmp_path / "wal")
    db_path = str(tmp_path / "docs.db")
    proc, port = await _spawn_server(wal_dir, db_path)
    url = f"ws://127.0.0.1:{port}"

    writer = HocuspocusProvider(name="storm-doc", url=url)
    observer = HocuspocusProvider(name="storm-doc", url=url)
    received = asyncio.Event()
    observer.document.on("update", lambda *args: received.set())
    try:
        from tests.utils import wait_synced

        await wait_synced(writer, observer)
        text = writer.document.get_text("t")

        # edit storm: bursts of inserts, killed without warning partway
        killed = False
        for round_no in range(200):
            for burst in range(4):
                text.insert(len(str(text)), f"[{round_no}.{burst}]")
            await asyncio.sleep(0.005)
            # kill once the observer has demonstrably received a chunk
            # of the storm — updates acknowledged THROUGH the server
            if round_no >= 25 and received.is_set():
                proc.send_signal(signal.SIGKILL)
                await proc.wait()
                killed = True
                break
        assert killed, "server outlived the whole storm without acking?"
    finally:
        writer.destroy()
        observer.destroy()
        if proc.returncode is None:
            proc.kill()
            await proc.wait()
    await asyncio.sleep(0.1)

    # snapshot what the reference client was shown: the acknowledged set
    reference_state = encode_state_as_update(observer.document)
    reference_sv = encode_state_vector(observer.document)
    assert len(str(observer.document.get_text("t"))) > 0

    # restart on the same directories and read the recovered state
    proc2, port2 = await _spawn_server(wal_dir, db_path)
    reader = HocuspocusProvider(name="storm-doc", url=f"ws://127.0.0.1:{port2}")
    try:
        from tests.utils import retryable_assertion, wait_synced

        await wait_synced(reader)

        def recovered_contains_reference():
            recovered_sv = encode_state_vector(reader.document)
            # the diff of the reference doc against the recovered state
            # vector is empty <=> every acknowledged update survived
            missing = encode_state_as_update(observer.document, recovered_sv)
            assert missing == _EMPTY_DELTA, (
                f"recovered state is missing acknowledged updates "
                f"({len(missing)}B diff)"
            )

        await retryable_assertion(recovered_contains_reference)

        # byte-identical convergence: merging the reference client's
        # state into the recovered doc changes NOTHING (superset), and
        # a fresh doc built from both orders fingerprints identically
        merged = Doc()
        apply_update(merged, encode_state_as_update(reader.document))
        before = encode_state_as_update(merged)
        apply_update(merged, reference_state)
        assert encode_state_as_update(merged) == before
        other_order = Doc()
        apply_update(other_order, reference_state)
        apply_update(other_order, encode_state_as_update(reader.document))
        assert str(other_order.get_text("t")) == str(merged.get_text("t"))
        assert str(reader.document.get_text("t")).startswith("")  # sanity
    finally:
        reader.destroy()
        proc2.kill()
        await proc2.wait()
    # sanity: the reference actually saw a real chunk of the storm
    assert len(reference_sv) > 1
