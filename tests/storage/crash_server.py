"""Subprocess target for the kill -9 crash/recovery suite.

Runs a real Server with the Durability WAL + SQLite store on an
OS-assigned port and prints `PORT <n>` once listening. The parent test
SIGKILLs this process mid-edit-storm and then boots a second copy on
the same directories to assert recovery. The store debounce is huge on
purpose: the WAL must be the only thing standing between the storm and
data loss.
"""

import asyncio
import os
import sys


async def main() -> None:
    wal_dir, db_path = sys.argv[1], sys.argv[2]
    from hocuspocus_tpu.extensions import SQLite
    from hocuspocus_tpu.server import Configuration, Server
    from hocuspocus_tpu.storage import Durability

    server = Server(
        Configuration(
            extensions=[Durability(wal_dir=wal_dir), SQLite(database=db_path)],
            quiet=True,
            debounce=600_000,  # never stores during the test window
            max_debounce=600_000,
        )
    )
    await server.listen(port=0)
    print(f"PORT {server.port}", flush=True)
    await asyncio.Event().wait()


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    asyncio.run(main())
