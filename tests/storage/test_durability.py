"""Durability plane integration: recovery, store retry/quarantine,
graceful drain, broadcast gating.

These run against real servers + real websocket providers (the repo's
standard harness), with the fault seams from `storage/faults.py`
driving the failure paths deterministically.
"""

import asyncio
import os

from tests.utils import (
    new_hocuspocus,
    new_provider,
    retryable_assertion,
    wait_for,
    wait_synced,
)

from hocuspocus_tpu.extensions import Database, IncrementalSQLite, SQLite
from hocuspocus_tpu.storage import Durability, FaultInjector, FlakyStore


def _assert(cond, message=""):
    assert cond, message


# -- crash recovery (in-process) ---------------------------------------------


async def test_wal_replays_over_stored_snapshot(tmp_path):
    """Snapshot + log-suffix: the store holds an OLD snapshot, the WAL
    holds the edits since; a restart reconstructs the union."""
    wal_dir = str(tmp_path / "wal")
    db = str(tmp_path / "docs.db")
    server = await new_hocuspocus(
        extensions=[Durability(wal_dir=wal_dir), SQLite(database=db)],
        debounce=50,
    )
    provider = new_provider(server, name="recover-me")
    await wait_synced(provider)
    text = provider.document.get_text("t")
    text.insert(0, "stored-part")
    # wait for the debounced store (WAL truncates when it lands)
    durability = server.configuration.extensions[0]
    await retryable_assertion(
        lambda: _assert(durability.wal.pending_records("recover-me") == 0)
    )
    # now edits that will NEVER be stored (debounce re-armed, crash next)
    text.insert(len(str(text)), " +wal-part")
    await wait_for(lambda: provider.unsynced_changes == 0)
    await retryable_assertion(
        lambda: _assert(durability.wal.pending_records("recover-me") >= 1)
    )
    # "crash": no destroy, no store — boot a fresh server on the same dirs
    server2 = await new_hocuspocus(
        extensions=[Durability(wal_dir=wal_dir), SQLite(database=db)],
        debounce=60000,
    )
    provider2 = new_provider(server2, name="recover-me")
    try:
        await wait_synced(provider2)
        await retryable_assertion(
            lambda: _assert(
                provider2.document.get_text("t").to_string()
                == "stored-part +wal-part"
            )
        )
        durability2 = server2.configuration.extensions[0]
        report = durability2.last_recovery["recover-me"]
        assert report["applied"] >= 1
        assert report["torn_tail_records"] == 0
    finally:
        provider2.destroy()
        provider.destroy()
        await server2.destroy()
        await server.destroy()


async def test_recovery_skips_torn_tail_and_counts_it(tmp_path):
    """A torn final record (the kill -9 signature) is skipped and
    counted; every intact record still applies."""
    from hocuspocus_tpu.crdt import Doc, encode_state_as_update

    wal_dir = str(tmp_path / "wal")
    seed = Doc()
    seed.get_text("t").insert(0, "intact")
    from hocuspocus_tpu.storage import WalManager

    wal = WalManager(wal_dir, fsync="tick")
    await wal.append("torn-doc", encode_state_as_update(seed))
    path = wal.doc("torn-doc").segments[-1].path
    wal.close()
    with open(path, "ab") as fh:
        fh.write(b"\x99" * 11)  # partial frame: a write cut by SIGKILL
    server = await new_hocuspocus(
        extensions=[Durability(wal_dir=wal_dir)], debounce=60000
    )
    provider = new_provider(server, name="torn-doc")
    try:
        await wait_synced(provider)
        assert provider.document.get_text("t").to_string() == "intact"
        durability = server.configuration.extensions[0]
        assert durability.last_recovery["torn-doc"]["torn_tail_records"] == 1
        assert durability.wal.stats["torn_tail_records"] == 1
    finally:
        provider.destroy()
        await server.destroy()


# -- store retry / quarantine state machine ----------------------------------


async def test_store_retries_then_succeeds(tmp_path):
    flaky = FlakyStore(failures=2)
    server = await new_hocuspocus(
        extensions=[Database(store=flaky)],
        debounce=20,
        store_retries=3,
        store_retry_base_ms=10,
        store_retry_max_ms=40,
    )
    provider = new_provider(server, name="flaky-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        await retryable_assertion(lambda: _assert(flaky.successes == 1))
        assert flaky.calls == 3  # two failures + the success
        assert "flaky-doc" not in server.hocuspocus.quarantine
    finally:
        provider.destroy()
        await server.destroy()


async def test_store_exhaustion_quarantines_not_drops(tmp_path):
    """Retries exhausted: the doc is quarantined — kept loaded, health
    degraded — and the sweep re-stores it once the backend heals."""
    flaky = FlakyStore(failures=4)
    server = await new_hocuspocus(
        extensions=[Database(store=flaky)],
        debounce=20,
        store_retries=1,  # 2 attempts per chain: first chain exhausts
        store_retry_base_ms=10,
        store_retry_max_ms=20,
        store_quarantine_sweep_ms=100,
    )
    provider = new_provider(server, name="doomed-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "precious")
        await retryable_assertion(
            lambda: _assert("doomed-doc" in server.hocuspocus.quarantine)
        )
        health = server.hocuspocus.get_health()
        assert health["status"] == "degraded"
        assert health["quarantined_documents"] == ["doomed-doc"]
        # the doc is KEPT LOADED even with zero connections
        provider.destroy()
        await asyncio.sleep(0.15)
        assert "doomed-doc" in server.hocuspocus.documents
        # backend heals (failures=4: attempts 1-4 fail) -> sweep stores
        await retryable_assertion(lambda: _assert(flaky.successes >= 1))
        await retryable_assertion(
            lambda: _assert("doomed-doc" not in server.hocuspocus.quarantine)
        )
        assert server.hocuspocus.get_health()["status"] == "ok"
    finally:
        provider.destroy()
        await server.destroy()


async def test_quarantined_doc_keeps_wal(tmp_path):
    """Quarantine + WAL: even while the store backend is down, every
    update stays recoverable from the log."""
    flaky = FlakyStore(failures=10**6)
    wal_dir = str(tmp_path / "wal")
    server = await new_hocuspocus(
        extensions=[Durability(wal_dir=wal_dir), Database(store=flaky)],
        debounce=20,
        store_retries=0,
        store_quarantine_sweep_ms=60000,
    )
    provider = new_provider(server, name="walled")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "survives")
        await retryable_assertion(
            lambda: _assert("walled" in server.hocuspocus.quarantine)
        )
        durability = server.configuration.extensions[0]
        assert durability.wal.pending_records("walled") >= 1
        records, _report = await durability.wal.replay("walled")
        assert records, "WAL must retain the quarantined doc's updates"
    finally:
        provider.destroy()
        await server.destroy()


# -- graceful drain -----------------------------------------------------------


async def test_drain_stores_dirty_docs_and_closes_1012(tmp_path):
    db = str(tmp_path / "drain.db")
    server = await new_hocuspocus(
        extensions=[SQLite(database=db)], debounce=60000
    )
    provider = new_provider(server, name="drain-doc")
    closes = []
    provider.on("close", lambda payload: closes.append(payload["event"]["code"]))
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "dirty at SIGTERM")
        await wait_for(lambda: provider.unsynced_changes == 0)
        outcome = await server.drain(timeout_secs=5)
        assert outcome["stored"] >= 1
        assert not outcome["timed_out"]
        assert outcome["quarantined"] == []
        await retryable_assertion(lambda: _assert(1012 in closes))
        # new connections are refused while draining
        sqlite = server.configuration.extensions[0]
        row = sqlite.db.execute(
            'SELECT data FROM "documents" WHERE name = ?', ("drain-doc",)
        ).fetchone()
        assert row is not None and row[0], "dirty doc must be stored by drain"
    finally:
        provider.destroy()
        await server.destroy()


async def test_drain_deadline_quarantines_slow_store(tmp_path):
    """A store slower than the deadline: drain returns on time, the doc
    is reported quarantined (not lost) and its WAL holds the data."""
    wal_dir = str(tmp_path / "wal")
    slow_release = asyncio.Event()

    async def slow_store(data):
        await slow_release.wait()

    server = await new_hocuspocus(
        extensions=[Durability(wal_dir=wal_dir), Database(store=slow_store)],
        debounce=60000,
        store_retries=0,
    )
    provider = new_provider(server, name="slow-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "slow but safe")
        await wait_for(lambda: provider.unsynced_changes == 0)
        outcome = await server.drain(timeout_secs=0.3)
        assert "slow-doc" in outcome["timed_out"]
        assert "slow-doc" in outcome["quarantined"]
        assert outcome["wal_flushed"] is True
        durability = server.configuration.extensions[0]
        assert durability.wal.pending_records("slow-doc") >= 1
        health = server.hocuspocus.get_health()
        assert health["status"] == "degraded"
    finally:
        slow_release.set()
        provider.destroy()
        await server.destroy()


async def test_drain_refuses_new_connections(tmp_path):
    server = await new_hocuspocus(extensions=[], debounce=60000)
    provider = new_provider(server, name="pre-drain")
    try:
        await wait_synced(provider)
        await server.drain(timeout_secs=2)
        import aiohttp

        async with aiohttp.ClientSession() as session:
            try:
                ws = await session.ws_connect(server.web_socket_url)
            except aiohttp.WSServerHandshakeError as error:
                assert error.status == 503
            else:
                await ws.close()
                raise AssertionError("draining server accepted an upgrade")
    finally:
        provider.destroy()
        await server.destroy()


# -- broadcast gating ---------------------------------------------------------


async def test_broadcast_waits_for_group_commit(tmp_path):
    """No client may see an update whose WAL record is not yet durable:
    with an artificially slow commit, the observer's receipt must come
    after the tick's durability future resolved."""
    wal_dir = str(tmp_path / "wal")
    durability = Durability(wal_dir=wal_dir)
    committed = asyncio.Event()
    real_commit = durability.wal._commit

    def slow_commit(pending):
        import time as _time

        _time.sleep(0.15)  # executor thread: event loop stays live
        real_commit(pending)
        committed.set()

    durability.wal._commit = slow_commit
    server = await new_hocuspocus(extensions=[durability], debounce=60000)
    writer = new_provider(server, name="gated")
    observer = new_provider(server, name="gated")
    received_after_commit = []
    observer.document.on(
        "update",
        lambda *args: received_after_commit.append(committed.is_set()),
    )
    try:
        await wait_synced(writer, observer)
        received_after_commit.clear()  # drop handshake noise
        writer.document.get_text("t").insert(0, "gated-broadcast")
        await retryable_assertion(
            lambda: _assert(
                observer.document.get_text("t").to_string() == "gated-broadcast"
            )
        )
        assert received_after_commit, "observer never received the update"
        assert all(received_after_commit), (
            "a broadcast frame outran its WAL group commit"
        )
    finally:
        writer.destroy()
        observer.destroy()
        await server.destroy()


async def test_incremental_store_truncates_wal(tmp_path):
    """The incremental (delta) backend also covers the log: after its
    store lands, the WAL suffix is gone."""
    wal_dir = str(tmp_path / "wal")
    db = str(tmp_path / "incr.db")
    server = await new_hocuspocus(
        extensions=[
            Durability(wal_dir=wal_dir),
            IncrementalSQLite(database=db),
        ],
        debounce=30,
    )
    provider = new_provider(server, name="incr-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "delta")
        durability = server.configuration.extensions[0]
        # the update hits the log first, then the delta store covers it
        await retryable_assertion(
            lambda: _assert(
                durability.wal.stats["appended_records"] >= 1
                and durability.wal.pending_records("incr-doc") == 0
            )
        )
        assert durability.wal.stats["segments_truncated"] >= 1
    finally:
        provider.destroy()
        await server.destroy()
